"""The bus: per-worker query queues + per-query prediction slots.

Interface (mirrors the reference's Cache verbs, SURVEY.md §2):
  add_worker(job_id, worker_id)          — register a live worker
  get_workers(job_id, max_age_s=None)    — running-worker set
  remove_worker(job_id, worker_id)
  heartbeat(job_id, worker_id)           — refresh the liveness lease
  add_query(worker_id, query_id, query)  — predictor → worker fan-out
  pop_queries(worker_id, max_n, timeout) — worker batch pull
  put_prediction(query_id, worker_id, prediction)
  get_predictions(query_id, n, timeout)  — predictor gather-wait

Trace envelopes (docs/observability.md): when a trace context is
active (or an explicit ``trace`` dict is passed), ``add_query``
enqueues ``(query_id, query, trace)`` instead of the bare 2-tuple, and
``pop_queries`` hands the envelope through — the inference worker
re-binds the trace so its spans/journal records stitch into the same
end-to-end trace as the gateway's. Untraced messages stay 2-tuples, so
the wire format is backward compatible in both bus implementations.

Liveness: registration is a LEASE, not a fact. A SIGKILLed worker
process never runs its ``remove_worker`` cleanup (the reference has
the same hole: its Redis running-worker set outlives the container),
so each worker refreshes a heartbeat timestamp from a tiny daemon
thread and readers pass ``max_age_s`` to see only workers whose lease
is fresh — the predictor stops fanning out to (and waiting on) a dead
worker within one lease TTL. ``reap_stale(max_age_s)`` is the janitor
half: once a lease is several TTLs old the corpse's registration,
timestamp and pending-query queue are deleted outright (counted in
telemetry as ``bus.reaped_workers``), so dead ids stop accumulating.

Chaos hooks (docs/chaos.md): ``bus.add_query`` (drop/delay a fan-out
message), ``bus.put_prediction`` (drop/delay a reply) and
``bus.heartbeat`` (skip a lease refresh — how scenarios simulate a
stalled or dead worker without killing the thread), all keyed by
worker id; plus ``bus.proxy`` on the mp bus (an injected
Manager-proxy fault at the IPC round-trip, keyed by the bus verb).
All inert no-ops unless ``RAFIKI_CHAOS`` is set.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from collections import deque

from rafiki_tpu import telemetry
from rafiki_tpu.chaos import hook as _chaos
from rafiki_tpu.obs import context as _trace_context
from rafiki_tpu.obs.anatomy import hops as _hops
from rafiki_tpu.obs.journal import journal as _journal


def _current_trace() -> Optional[Dict[str, Any]]:
    """The active trace as a plain picklable envelope field (None when
    untraced — the message stays a 2-tuple)."""
    tid = _trace_context.current_trace_id()
    if tid is None:
        return None
    trace: Dict[str, Any] = {"trace_id": tid}
    parent = telemetry.current_span_id()
    if parent:
        trace["parent_span"] = parent
    # The tenant tag rides the same envelope field as the trace (and
    # the PR 6 back-compat rule: absent key = untagged, old consumers
    # ignore it) so worker-side records can attribute work per tenant.
    tenant = _trace_context.current_tenant()
    if tenant:
        trace["tenant"] = tenant
    return trace


def _envelope(query_id: str, query: Any,
              trace: Optional[Dict[str, Any]]) -> tuple:
    trace = trace or _current_trace()
    if trace is None:
        return (query_id, query)
    if "hops" not in trace:
        # Hop marks ride the envelope (docs/serving_anatomy.md): the
        # gateway's thread-local prefix (admit/queue), then the enqueue
        # mark stamped here. Copy before annotating — an explicit trace
        # arg may be a caller-owned dict shared across queries.
        trace = dict(trace)
        trace["hops"] = _hops.prefix_marks() + [_hops.mark("enq")]
    # Journal the fan-out hop so the bus appears in the stitched trace.
    _journal.record("bus", "add_query", query_id=query_id,
                    trace_id=trace.get("trace_id"),
                    parent_span=trace.get("parent_span"))
    return (query_id, query, trace)


class InProcBus:
    _EXPIRED_CAP = 4096  # remembered timed-out query ids (leak guard)
    # Auto-janitor factor: get_workers reaps any lease older than
    # REAP_FACTOR × the caller's max_age_s on sight, so corpse queues
    # cannot grow unboundedly under worker churn even when nothing ever
    # calls reap_stale explicitly. Well above the liveness TTL: a busy
    # host starving a worker for a beat or two must not lose its queue.
    # Env override: RAFIKI_BUS_REAP_FACTOR.
    REAP_FACTOR = 6.0

    def __init__(self):
        import os

        self._reap_factor = float(
            os.environ.get("RAFIKI_BUS_REAP_FACTOR", str(self.REAP_FACTOR)))
        # Queues exist exactly while their worker is registered:
        # created in add_worker, destroyed in remove_worker, and
        # add_query drops (rather than resurrects) queries to dead
        # workers — otherwise repeated inference-job cycles would leak
        # one queue per retired worker id.
        self._queues: Dict[str, queue.Queue] = {}
        # Running total of enqueued-not-yet-popped queries. add_query
        # used to recompute it by summing qsize() over EVERY worker
        # queue under the bus lock — O(workers) on the hot path. The
        # counter can drift slightly (pop_queries drains outside the
        # lock, so a concurrent remove_worker may double-subtract);
        # it feeds a gauge and the least-loaded router, both of which
        # tolerate approximation, so we clamp at 0 rather than pay a
        # stricter protocol.
        self._depth = 0
        self._preds: Dict[str, list] = {}
        self._pred_cv = threading.Condition()
        # Plain dict, NOT defaultdict: read paths (heartbeat of a
        # removed worker, get_workers of a finished job) used to
        # materialize an empty set per probed job id — a slow leak
        # under repeated job cycles.
        self._workers: Dict[str, set] = {}
        self._worker_ts: Dict[Tuple[str, str], float] = {}
        self._expired: "deque[str]" = deque(maxlen=self._EXPIRED_CAP)
        self._expired_set: set = set()
        self._lock = threading.Lock()

    # -- worker registry -----------------------------------------------------

    def add_worker(self, job_id: str, worker_id: str) -> None:
        with self._lock:
            self._workers.setdefault(job_id, set()).add(worker_id)
            self._worker_ts[(job_id, worker_id)] = time.monotonic()
            self._queues.setdefault(worker_id, queue.Queue())

    def remove_worker(self, job_id: str, worker_id: str) -> None:
        with self._lock:
            self._workers.get(job_id, set()).discard(worker_id)
            self._worker_ts.pop((job_id, worker_id), None)
            q = self._queues.pop(worker_id, None)
            if q is not None:  # pending queries die with the queue
                self._depth = max(0, self._depth - q.qsize())

    def heartbeat(self, job_id: str, worker_id: str) -> None:
        if _chaos("bus.heartbeat", worker_id) == "skip":
            return  # injected missed beat: the lease ages as if dead
        with self._lock:
            if worker_id in self._workers.get(job_id, ()):  # never resurrect
                self._worker_ts[(job_id, worker_id)] = time.monotonic()

    def get_workers(self, job_id: str,
                    max_age_s: Optional[float] = None) -> List[str]:
        with self._lock:
            ws = self._workers.get(job_id, ())
            if max_age_s is None:
                return sorted(ws)
            # lint: disable=RF007 — lease cutoff timestamp, not a duration
            cutoff = time.monotonic() - max_age_s
            # Auto-janitor: any lease REAP_FACTOR×TTL old is a corpse
            # (a SIGKILLed worker never runs remove_worker) — reap its
            # registration, timestamp and pending-query queue on sight,
            # inline under the same lock (calling reap_stale here would
            # deadlock on the non-reentrant bus lock).
            self._reap_locked(cutoff - max_age_s * (self._reap_factor - 1.0),
                              [job_id])
            return sorted(w for w in ws
                          if self._worker_ts.get((job_id, w), 0.0) >= cutoff)

    def _reap_locked(self, cutoff: float,
                     jobs: List[str]) -> List[Tuple[str, str]]:
        """Delete registrations with leases older than ``cutoff``.
        Caller holds ``self._lock``."""
        reaped: List[Tuple[str, str]] = []
        for j in jobs:
            ws = self._workers.get(j)
            if not ws:
                continue
            for w in [w for w in ws
                      if self._worker_ts.get((j, w), 0.0) < cutoff]:
                ws.discard(w)
                # lint: disable=RF004 — caller holds self._lock (see docstring)
                self._worker_ts.pop((j, w), None)
                # lint: disable=RF004 — caller holds self._lock (see docstring)
                q = self._queues.pop(w, None)
                if q is not None:
                    self._depth = max(0, self._depth - q.qsize())
                reaped.append((j, w))
        if reaped:
            telemetry.inc("bus.reaped_workers", len(reaped))
        return reaped

    def reap_stale(self, max_age_s: float,
                   job_id: Optional[str] = None) -> List[Tuple[str, str]]:
        """Janitor: delete every registration whose lease is older than
        ``max_age_s`` — worker set entry, timestamp AND pending-query
        queue, so a SIGKILLed worker's leftovers stop accumulating.
        Callers pick max_age_s well above the liveness TTL (the
        predictor uses k×TTL): reaping is for corpses, not for workers
        a busy host merely starved for one beat. ``get_workers`` also
        runs this automatically at REAP_FACTOR× the caller's TTL."""
        # lint: disable=RF007 — lease cutoff timestamp, not a duration
        cutoff = time.monotonic() - max_age_s
        with self._lock:
            jobs = [job_id] if job_id is not None else list(self._workers)
            return self._reap_locked(cutoff, jobs)

    # -- queries -------------------------------------------------------------

    def add_query(self, worker_id: str, query_id: str, query: Any,
                  trace: Optional[Dict[str, Any]] = None) -> None:
        if _chaos("bus.add_query", worker_id) == "drop":
            telemetry.inc("bus.queries_dropped_chaos")
            return  # injected loss: the gather just sees one fewer reply
        item = _envelope(query_id, query, trace)
        with self._lock:
            q = self._queues.get(worker_id)
            if q is not None:
                q.put(item)  # unbounded Queue: put never blocks
                self._depth += 1
                depth = self._depth
        if q is not None:  # dead worker → drop; the gather just sees n-1
            telemetry.inc("bus.queries_added")
            telemetry.set_gauge("bus.queue_depth", depth)
        else:
            telemetry.inc("bus.queries_dropped_dead_worker")

    def queue_depth(self, worker_id: str) -> int:
        """Pending (unpopped) queries for one worker — the signal the
        gateway's least-loaded router keys on."""
        with self._lock:
            q = self._queues.get(worker_id)
            return q.qsize() if q is not None else 0

    def pop_queries(self, worker_id: str, max_n: int = 64,
                    timeout: float = 0.1) -> List[tuple]:
        """Block up to ``timeout`` for the first query, then drain up to
        max_n without blocking — natural micro-batching for the device.
        Items are ``(qid, query)`` or traced ``(qid, query, trace)``."""
        with self._lock:
            q = self._queues.get(worker_id)
        if q is None:  # not registered (stopped): nothing to serve
            time.sleep(min(timeout, 0.05))
            return []
        out: List[tuple] = []
        try:
            out.append(q.get(timeout=timeout))
        except queue.Empty:
            return out
        while len(out) < max_n:
            try:
                out.append(q.get_nowait())
            except queue.Empty:
                break
        with self._lock:
            self._depth = max(0, self._depth - len(out))
        telemetry.inc("bus.queries_popped", len(out))
        telemetry.observe("bus.pop_batch_size", len(out))
        return out

    # -- predictions ---------------------------------------------------------

    def put_prediction(self, query_id: str, worker_id: str, prediction: Any,
                       hops: Optional[list] = None) -> None:
        if _chaos("bus.put_prediction", worker_id) == "drop":
            return  # injected reply loss
        # Reply-leg hop carriage, back-compat like the query-leg trace
        # 3-tuple: plain replies stay (worker_id, prediction); a worker
        # with a hop chain appends it as an optional third element.
        item = ((worker_id, prediction) if hops is None
                else (worker_id, prediction, hops))
        with self._pred_cv:
            if query_id in self._expired_set:
                return  # late answer to a timed-out query: drop, don't leak
            self._preds.setdefault(query_id, []).append(item)
            self._pred_cv.notify_all()

    def get_predictions(self, query_id: str, n: int,
                        timeout: float = 10.0,
                        min_n: Optional[int] = None,
                        grace_s: Optional[float] = None) -> List[Tuple[str, Any]]:
        """Wait until n predictions arrived (or timeout); pops the slot.
        After this returns, late answers for query_id are discarded.

        Quorum gather: with ``min_n`` (and optionally ``grace_s``), the
        wait relaxes once ``min_n`` replies are in — from that moment
        at most ``grace_s`` more seconds are granted for stragglers
        before the partial set is returned. This is how the gateway
        keeps p99 tracking the median replica instead of the slowest.
        """
        deadline = time.monotonic() + timeout
        quorum = n if min_n is None else max(1, min(min_n, n))
        quorum_at: Optional[float] = None
        with self._pred_cv:
            while True:
                got = len(self._preds.get(query_id, []))
                if got >= n:
                    break
                now = time.monotonic()
                limit = deadline
                if got >= quorum:
                    if quorum_at is None:
                        quorum_at = now
                    if grace_s is not None:
                        limit = min(limit, quorum_at + grace_s)
                if now >= limit:
                    break
                self._pred_cv.wait(limit - now)
            if len(self._expired) == self._expired.maxlen:
                self._expired_set.discard(self._expired[0])
            self._expired.append(query_id)
            self._expired_set.add(query_id)
            return self._preds.pop(query_id, [])


def make_mp_bus(manager=None):
    """A multiprocessing-shared bus with the same interface.

    Built on a ``multiprocessing.Manager`` so predictor and inference
    workers can run as separate processes on the TPU host — the
    deployment shape the reference achieves with Redis.
    """
    import multiprocessing as mp

    # spawn, not fork: JAX is multithreaded and fork() can deadlock.
    manager = manager or mp.get_context("spawn").Manager()
    return _MpBus(manager)


class _MpBus:
    """Cross-process bus over Manager dict/Lock proxies ONLY.

    Every shared structure is a manager.dict holding PLAIN values
    updated copy-on-write (read, rebuild, reassign under the lock) —
    no nested proxies and no manager handle needed after construction,
    so the bus object itself pickles into spawn children (the Manager
    object does not pickle; nested list/Queue proxies would force
    children to create new shared objects through it). Manager ops are
    IPC round-trips either way, so polling instead of blocking
    Queue.get costs nothing extra at this bus's scale.
    """

    _EXPIRED_CAP = 4096  # remembered gathered/timed-out query ids
    REAP_FACTOR = 6.0    # same auto-janitor contract as InProcBus
    # Poll period for pop/gather waits. This is a FLOOR under every
    # serving hop that crosses the bus (enq→deq and reply→gather): at
    # the old 5ms, a k=3 replicated fan-out paid ~2×5ms of pure polling
    # per query — most of the fanout_cost_s the stacked route exists to
    # collapse. 1ms keeps the Manager round-trip rate trivial (~1k/s
    # per idle waiter) while cutting the wire-tax floor 5×.
    _POLL_S = 0.001

    def __init__(self, manager):
        import os

        self._reap_factor = float(
            os.environ.get("RAFIKI_BUS_REAP_FACTOR", str(self.REAP_FACTOR)))
        self._manager = manager         # keepalive only; dropped on pickle
        self._queues = manager.dict()   # worker_id -> tuple of (qid, query)
        self._preds = manager.dict()    # query_id -> tuple of (worker, pred)
        self._workers = manager.dict()  # job_id -> tuple of worker ids
        self._worker_ts = manager.dict()  # "job|worker" -> epoch seconds
        self._expired = manager.dict()  # gathered/timed-out query ids
        self._expired_cap = self._EXPIRED_CAP  # instance-level for tests
        self._lock = manager.Lock()

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_manager"] = None  # children use proxies, never the manager
        return state

    @staticmethod
    def _proxy(op: str):
        """``bus.proxy`` chaos site (docs/chaos.md): an injected
        Manager-proxy fault at the start of an IPC round-trip, keyed by
        the bus verb. ``error`` raises ChaosError in the calling
        process (a dead manager / broken pipe), ``delay`` stalls the
        round-trip; the caller's own error handling — breakers, quorum
        gathers, lease expiry — must absorb it."""
        return _chaos("bus.proxy", op)

    def add_worker(self, job_id, worker_id):
        with self._lock:
            ws = self._workers.get(job_id, ())
            if worker_id not in ws:
                self._workers[job_id] = ws + (worker_id,)
            self._queues.setdefault(worker_id, ())
            # time.time(), not monotonic: leases are compared across
            # processes and wall clock is the shared clock here.
            self._worker_ts[f"{job_id}|{worker_id}"] = time.time()

    def remove_worker(self, job_id, worker_id):
        with self._lock:
            ws = self._workers.get(job_id, ())
            if worker_id in ws:
                self._workers[job_id] = tuple(w for w in ws if w != worker_id)
            self._worker_ts.pop(f"{job_id}|{worker_id}", None)
            self._queues.pop(worker_id, None)

    def heartbeat(self, job_id, worker_id):
        if _chaos("bus.heartbeat", worker_id) == "skip":
            return  # injected missed beat (chaos fires in the CALLING process)
        with self._lock:
            if worker_id in self._workers.get(job_id, ()):  # never resurrect
                self._worker_ts[f"{job_id}|{worker_id}"] = time.time()

    def get_workers(self, job_id, max_age_s=None):
        self._proxy("get_workers")
        ws = self._workers.get(job_id, ())
        if max_age_s is None:
            return sorted(ws)
        # lint: disable=RF009 — lease cutoff vs cross-process wall-clock beats, not a duration
        cutoff = time.time() - max_age_s
        ts = dict(self._worker_ts)
        # Auto-janitor (same contract as InProcBus.get_workers): the
        # stale set is computed from this read's snapshot, then reaped
        # through reap_stale — a lock-free read here, so no deadlock.
        reap_age = max_age_s * self._reap_factor
        # lint: disable=RF009 — reap cutoff vs cross-process wall-clock beats, not a duration
        if any(ts.get(f"{job_id}|{w}", 0.0) < time.time() - reap_age
               for w in ws):
            self.reap_stale(reap_age, job_id)
        return sorted(w for w in ws
                      if ts.get(f"{job_id}|{w}", 0.0) >= cutoff)

    def reap_stale(self, max_age_s, job_id=None):
        """Same janitor contract as InProcBus.reap_stale, over the
        manager proxies (copy-on-write tuple rebuild under the lock).
        The reap counter is per-process — whichever process runs the
        janitor (normally the predictor's) observes the reaps."""
        # lint: disable=RF009 — lease cutoff vs cross-process wall-clock beats, not a duration
        cutoff = time.time() - max_age_s
        reaped = []
        with self._lock:
            jobs = [job_id] if job_id is not None else list(self._workers.keys())
            ts = dict(self._worker_ts)
            for j in jobs:
                ws = self._workers.get(j, ())
                dead = tuple(w for w in ws
                             if ts.get(f"{j}|{w}", 0.0) < cutoff)
                if not dead:
                    continue
                self._workers[j] = tuple(w for w in ws if w not in dead)
                for w in dead:
                    self._worker_ts.pop(f"{j}|{w}", None)
                    self._queues.pop(w, None)
                    reaped.append((j, w))
        if reaped:
            telemetry.inc("bus.reaped_workers", len(reaped))
        return reaped

    def add_query(self, worker_id, query_id, query, trace=None):
        if _chaos("bus.add_query", worker_id) == "drop":
            telemetry.inc("bus.queries_dropped_chaos")
            return
        self._proxy("add_query")
        item = _envelope(query_id, query, trace)
        with self._lock:
            pending = self._queues.get(worker_id)
            if pending is None:  # dead worker → drop; gather sees n-1
                return
            self._queues[worker_id] = pending + (item,)

    def queue_depth(self, worker_id):
        """Pending (unpopped) queries for one worker (least-loaded
        routing signal). One proxy read; no lock needed for a gauge."""
        return len(self._queues.get(worker_id, ()))

    def pop_queries(self, worker_id, max_n=64, timeout=0.1):
        self._proxy("pop_queries")
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                pending = self._queues.get(worker_id)
                if pending:
                    self._queues[worker_id] = pending[max_n:]
                    return list(pending[:max_n])
            if pending is None:  # not registered (stopped)
                time.sleep(min(timeout, 0.05))
                return []
            if time.monotonic() >= deadline:
                return []
            time.sleep(self._POLL_S)

    def put_prediction(self, query_id, worker_id, prediction, hops=None):
        if _chaos("bus.put_prediction", worker_id) == "drop":
            return
        self._proxy("put_prediction")
        # Same optional-3rd-element reply shape as InProcBus.
        item = ((worker_id, prediction) if hops is None
                else (worker_id, prediction, hops))
        with self._lock:
            if query_id in self._expired:
                return  # late answer to a timed-out query: drop, don't leak
            self._preds[query_id] = (self._preds.get(query_id, ())
                                     + (item,))

    def get_predictions(self, query_id, n, timeout=10.0, min_n=None,
                        grace_s=None):
        """Same contract as InProcBus.get_predictions, including the
        quorum/hedge relaxation, over polling instead of a condvar."""
        deadline = time.monotonic() + timeout
        quorum = n if min_n is None else max(1, min(min_n, n))
        quorum_at = None
        while True:
            preds = self._preds.get(query_id, ())
            now = time.monotonic()
            if len(preds) >= n:
                break
            limit = deadline
            if len(preds) >= quorum:
                if quorum_at is None:
                    quorum_at = now
                if grace_s is not None:
                    limit = min(limit, quorum_at + grace_s)
            if now >= limit:
                break
            time.sleep(self._POLL_S)
        with self._lock:
            preds = self._preds.pop(query_id, ())
            self._expired[query_id] = True
            overflow = len(self._expired) - self._expired_cap
            if overflow > 0:
                # Insertion-ordered trim (manager dicts keep insert
                # order), mirroring InProcBus's deque+set pair. The old
                # coarse `.clear()` forgot EVERY expired id at once,
                # reopening the late-answer leak for all inflight
                # gathers the moment the cap was hit.
                for old in list(self._expired.keys())[:overflow]:
                    del self._expired[old]
        return list(preds)
