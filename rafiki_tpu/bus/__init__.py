"""Query/prediction bus between the predictor frontend and inference
workers.

Reference parity: rafiki/cache/cache.py (unverified) — a Redis wrapper
with per-worker query queues, per-query prediction slots, and a
running-worker registry. Redis is not needed for a one-host TPU
topology: the in-proc bus is plain queues + dict; the multiprocessing
variant shares the same interface over a Manager, so predictor and
workers can live in separate processes (the reference's deployment
shape) without an external service.
"""

from rafiki_tpu.bus.queues import InProcBus, make_mp_bus

__all__ = ["InProcBus", "make_mp_bus"]
