"""Auth: HS256 JWTs and password hashing, stdlib only.

Reference parity: rafiki/utils/auth.py (unverified — SURVEY.md §2):
``generate_token`` / JWT decode and an ``@auth(user_types=[...])``
route decorator over roles SUPERADMIN / ADMIN / MODEL_DEVELOPER /
APP_DEVELOPER. The reference uses PyJWT; this environment has no PyJWT,
and an HS256 JWT is ~30 lines of stdlib (hmac + sha256 + base64url),
so we implement it directly — wire-compatible with any standard JWT
library.

Passwords are hashed with PBKDF2-HMAC-SHA256 (the reference used
bcrypt; PBKDF2 is the stdlib equivalent), stored as
``pbkdf2$<iterations>$<salt_hex>$<hash_hex>``.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import time
from typing import Any, Dict, List, Optional

from rafiki_tpu.constants import UserType

_PBKDF2_ITERATIONS = 100_000


class AuthError(Exception):
    """Raised on bad credentials, bad tokens, or insufficient role."""


# -- password hashing --------------------------------------------------------


def hash_password(password: str) -> str:
    salt = os.urandom(16)
    digest = hashlib.pbkdf2_hmac(
        "sha256", password.encode(), salt, _PBKDF2_ITERATIONS)
    return f"pbkdf2${_PBKDF2_ITERATIONS}${salt.hex()}${digest.hex()}"


def verify_password(password: str, stored: str) -> bool:
    try:
        scheme, iters, salt_hex, hash_hex = stored.split("$")
        if scheme != "pbkdf2":
            return False
        digest = hashlib.pbkdf2_hmac(
            "sha256", password.encode(), bytes.fromhex(salt_hex), int(iters))
        return hmac.compare_digest(digest.hex(), hash_hex)
    except (ValueError, AttributeError):
        return False


# -- JWT (HS256) -------------------------------------------------------------


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64url(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def generate_token(payload: Dict[str, Any], secret: str,
                   ttl_s: Optional[float] = None) -> str:
    """Standard JWT: header.payload.signature, HS256."""
    header = {"alg": "HS256", "typ": "JWT"}
    body = dict(payload)
    if ttl_s is not None:
        body["exp"] = time.time() + ttl_s
    signing_input = f"{_b64url(json.dumps(header).encode())}.{_b64url(json.dumps(body).encode())}"
    sig = hmac.new(secret.encode(), signing_input.encode(), hashlib.sha256).digest()
    return f"{signing_input}.{_b64url(sig)}"


def decode_token(token: str, secret: str) -> Dict[str, Any]:
    try:
        signing_input, sig_b64 = token.rsplit(".", 1)
        header_b64, payload_b64 = signing_input.split(".")
        header = json.loads(_unb64url(header_b64))
        sig = _unb64url(sig_b64)
    except (ValueError, json.JSONDecodeError):
        raise AuthError("Malformed token")
    if header.get("alg") != "HS256":  # no alg-confusion: HS256 only
        raise AuthError("Unsupported token algorithm")
    expected = hmac.new(secret.encode(), signing_input.encode(), hashlib.sha256).digest()
    if not hmac.compare_digest(sig, expected):
        raise AuthError("Invalid token signature")
    try:
        payload = json.loads(_unb64url(payload_b64))
    except (ValueError, json.JSONDecodeError):
        raise AuthError("Malformed token payload")
    exp = payload.get("exp")
    if exp is not None and time.time() > float(exp):
        raise AuthError("Token expired")
    return payload


# -- role checks -------------------------------------------------------------


def check_user_type(user_type: str, allowed: List[str]) -> None:
    """Raise AuthError unless ``user_type`` is one of ``allowed`` or an
    admin role (SUPERADMIN/ADMIN can do anything a developer can — same
    convention as the reference's decorator use; the two developer
    roles are otherwise disjoint)."""
    if user_type in allowed:
        return
    if user_type in (UserType.SUPERADMIN.value, UserType.ADMIN.value):
        return
    raise AuthError(f"User type {user_type} not permitted (need one of {allowed})")
