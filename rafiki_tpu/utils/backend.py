"""Backend pinning helpers.

This image's sitecustomize force-registers the axon TPU PJRT backend
regardless of ``JAX_PLATFORMS`` in the environment; ``jax.devices()``
then hangs initializing it when the tunnel is unreachable. The explicit
``jax.config.update("jax_platforms", "cpu")`` wins over the hijack, so
every entry point that must run on CPU (tests, multichip dryrun,
subprocess workers asked for cpu) funnels through here instead of
hand-rolling the same dance.
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def ensure_host_device_count(n_devices: int) -> None:
    """Ensure XLA_FLAGS requests >= n_devices virtual CPU devices.

    Replaces an inherited smaller count (e.g. a scheduler-injected
    ``=1``) rather than deferring to it. Must run before jax's CPU
    backend initializes to take effect.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"{_COUNT_FLAG}=(\d+)", flags)
    if m is None:
        flags = (flags + f" {_COUNT_FLAG}={n_devices}").strip()
    elif int(m.group(1)) < n_devices:
        flags = flags[: m.start(1)] + str(n_devices) + flags[m.end(1):]
    else:
        return
    os.environ["XLA_FLAGS"] = flags


def host_device_count_flag(n_devices: int) -> str:
    """The XLA_FLAGS fragment requesting n virtual CPU devices (the
    single source of truth for the flag's spelling)."""
    return f"{_COUNT_FLAG}={n_devices}"


def force_cpu_backend(n_devices: int | None = None) -> None:
    """Pin jax to the CPU backend, optionally with >= n virtual devices."""
    if n_devices is not None:
        ensure_host_device_count(n_devices)
    import jax

    jax.config.update("jax_platforms", "cpu")


def honor_env_platform() -> bool:
    """Apply a ``JAX_PLATFORMS=cpu`` request for real.

    This image's sitecustomize registers the axon TPU plugin regardless
    of the env var, so the env alone is silently ignored — and when the
    TPU tunnel is down, the first ``jax.devices()`` then hangs forever.
    Entry points that respect the env (quickstart, serve, workers,
    bench) call this once before touching jax. Returns True when a CPU
    request was applied (callers can then skip TPU reachability
    probes).
    """
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        force_cpu_backend()
        return True
    return False


def enable_compilation_cache(cache_dir: str | os.PathLike | None = None,
                             min_compile_time_s: float = 1.0) -> str:
    """Turn on XLA's persistent (on-disk) compilation cache.

    The in-process program cache (ops.train.get_program) amortizes
    compiles across trials of ONE worker process; this cache amortizes
    them across processes and restarts — the second process-per-chip
    worker to hit a given (program, topology) loads the serialized
    executable from disk instead of recompiling. Every long-lived entry
    point (subprocess workers, bench, admin boot) calls this.

    Returns the cache directory in use.
    """
    if cache_dir is None:
        cache_dir = os.environ.get("RAFIKI_XLA_CACHE_DIR")
    if cache_dir is None:
        from rafiki_tpu.config import get_config

        cache_dir = get_config().data_dir / "xla_cache"
    min_compile_time_s = float(
        os.environ.get("RAFIKI_XLA_CACHE_MIN_S", min_compile_time_s))
    cache_dir = str(cache_dir)
    os.makedirs(cache_dir, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_time_s))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return cache_dir
