"""Cross-cutting utilities: auth (JWT + password hashing), misc.

Reference parity: rafiki/utils/ (unverified — SURVEY.md §1 cross-cutting
row): JWT auth decorator, logging, parsing helpers.
"""
