"""JSON-serializable coercion for API responses (numpy → plain types)."""

from __future__ import annotations

from typing import Any


def jsonable(obj: Any) -> Any:
    """Recursively convert numpy arrays/scalars so json.dumps accepts it."""
    import numpy as np

    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (np.floating, np.integer, np.bool_)):
        return obj.item()
    if isinstance(obj, bytes):
        return obj.decode("utf-8", "replace")
    if isinstance(obj, dict):
        return {k: jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [jsonable(v) for v in obj]
    return obj
