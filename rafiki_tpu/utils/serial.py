"""Fast single-transfer pytree serialization for trial parameters.

Why this exists: persisting a trial's parameters is on the steady-state
throughput path (the async saver overlaps it with the next trial's
training, so trial wall-clock is max(compute, persist) — see
worker/train.py). Measured on the v5e chip, fetching VGG16's params
costs ~2.6s at full precision while the host-side serialization costs
~0.1s: the device→host transfer is bandwidth-bound and dominates. So:

  * float32 leaves are optionally cast to bfloat16 ON DEVICE by a
    single jit'd elementwise tree-map (compiles in <1s; a device-side
    concat into one buffer was also tried and fetches slightly faster
    warm, but its 43-way concat took XLA:TPU ~2 minutes to compile —
    not worth it), halving the bytes over the wire (~0.9s for VGG16);
  * leaf transfers are started with ``copy_to_host_async`` before any
    is consumed, so the host walk overlaps the device DMA;
  * the host side writes raw little-endian buffers — no msgpack.

The bf16 cast is the DEFAULT for serving blobs and loses nothing:
model templates compute in bfloat16 on the MXU anyway (every
conv/dense casts its params down per flax ``dtype=bfloat16``), so a
bf16-stored parameter produces bit-identical serving math. Full-
precision masters for resume live in ``dump_checkpoint``, not here.
Opt out with cast_f32_to_bf16=False (config:
serving_params_dtype="float32").

Format (version RTPK1): magic, u64-le header length, JSON header
listing (key, shape, dtype) per leaf in key order, then the raw
concatenated little-endian buffers. Readable with numpy alone.
"""

from __future__ import annotations

import json
from typing import Any, Dict

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np

MAGIC = b"RTPK1\n"

_EXTRA_DTYPES = {
    "bfloat16": ml_dtypes.bfloat16,
    "float8_e4m3fn": getattr(ml_dtypes, "float8_e4m3fn", None),
}


def _np_dtype(name: str) -> np.dtype:
    if name in _EXTRA_DTYPES and _EXTRA_DTYPES[name] is not None:
        return np.dtype(_EXTRA_DTYPES[name])
    return np.dtype(name)


@jax.jit
def _cast_tree_bf16(tree):
    return jax.tree.map(
        lambda l: l.astype(jnp.bfloat16) if l.dtype == jnp.float32 else l, tree)


def _flat_items(tree: Any):
    """Stable (path-string, leaf) pairs for a params pytree / state dict."""
    from flax import serialization
    from flax.traverse_util import flatten_dict

    state = serialization.to_state_dict(tree)
    flat = flatten_dict(state, sep="/")
    return sorted(flat.items())


def dump_pytree(tree: Any, cast_f32_to_bf16: bool = True) -> bytes:
    """Serialize a pytree of arrays: raw buffers, pipelined transfers."""
    if cast_f32_to_bf16:
        tree = _cast_tree_bf16(tree)
    items = _flat_items(tree)
    spec = []
    leaves = []
    for k, v in items:
        v = jnp.asarray(v)
        leaves.append(v)
        spec.append({"k": k, "shape": list(v.shape), "dtype": v.dtype.name})
    header = json.dumps(spec).encode()
    # Kick off every device->host copy before consuming any.
    for v in leaves:
        if hasattr(v, "copy_to_host_async"):
            v.copy_to_host_async()
    parts = [MAGIC, len(header).to_bytes(8, "little"), header]
    parts.extend(np.ascontiguousarray(np.asarray(v)).tobytes() for v in leaves)
    return b"".join(parts)


def is_packed(blob: bytes) -> bool:
    return blob[: len(MAGIC)] == MAGIC


def load_pytree(blob: bytes) -> Dict[str, Any]:
    """Inverse of :func:`dump_pytree` → nested state dict of np arrays
    (restore into a template with ``flax.serialization.from_state_dict``)."""
    from flax.traverse_util import unflatten_dict

    if not is_packed(blob):
        raise ValueError("not a RTPK1 packed pytree blob")
    off = len(MAGIC)
    hlen = int.from_bytes(blob[off : off + 8], "little")
    off += 8
    spec = json.loads(blob[off : off + hlen].decode())
    off += hlen
    flat = {}
    for ent in spec:
        dt = _np_dtype(ent["dtype"])
        shape = tuple(ent["shape"])
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        arr = np.frombuffer(blob, dtype=dt, count=n, offset=off).reshape(shape)
        flat[ent["k"]] = arr
        off += n * dt.itemsize
    return unflatten_dict(flat, sep="/")
