"""Structured JSONL event stream.

Reference parity: SURVEY.md §5 "Metrics / logging / observability" —
the reference's only channels are trial logs and `docker service logs`;
the rebuild adds "the same trial-log channel + a structured JSONL
event stream". Every lifecycle transition (job/trial/service) appends
one JSON object per line to ``<logs_dir>/events.jsonl``.

Append semantics: each process opens the file in append mode and
writes whole lines; on POSIX, O_APPEND writes of < PIPE_BUF bytes are
atomic, so subprocess workers can share the file with the scheduler
without interleaving corruption.

Usage::

    from rafiki_tpu.utils.events import events
    events.configure(cfg.logs_dir)          # once per process (optional)
    events.emit("trial_completed", trial_id=..., score=...)

Unconfigured, ``emit`` is a no-op — library code can emit
unconditionally.
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path
from typing import Any, Iterator, Optional

from rafiki_tpu.obs.journal import journal as _journal


class EventLog:
    def __init__(self, logs_dir: Optional[str | os.PathLike] = None,
                 filename: str = "events.jsonl"):
        self._lock = threading.Lock()
        self._path: Optional[Path] = None
        self._fh = None
        self.filename = filename
        if logs_dir is not None:
            self.configure(logs_dir)

    def configure(self, logs_dir: str | os.PathLike) -> "EventLog":
        with self._lock:
            if self._fh is not None:
                self._fh.close()
            path = Path(logs_dir) / self.filename
            path.parent.mkdir(parents=True, exist_ok=True)
            self._path = path
            self._fh = open(path, "a", buffering=1)  # line-buffered append
        return self

    @property
    def path(self) -> Optional[Path]:
        return self._path

    def emit(self, event: str, **fields: Any) -> None:
        # Mirror into the per-process journal (no-op unless the process
        # opted in via RAFIKI_LOG_DIR) so trial lifecycle / checkpoint
        # events land in the same stream spans do (docs/observability.md).
        _journal.record("event", event, **fields)
        with self._lock:
            if self._fh is None:
                return
            record = {"time": time.time(), "event": event,
                      "pid": os.getpid(), **fields}
            self._fh.write(json.dumps(record, default=str) + "\n")

    def read(self, event: Optional[str] = None) -> Iterator[dict]:
        """Iterate recorded events (optionally filtered by type)."""
        if self._path is None or not self._path.exists():
            return
        with open(self._path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn line from a crashed writer
                if event is None or rec.get("event") == event:
                    yield rec

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


#: Process-global event log; workers/schedulers emit into it
#: unconditionally, hosts opt in via ``events.configure(logs_dir)``.
events = EventLog()


def configure_from_env() -> None:
    """Subprocess workers inherit the sink via RAFIKI_EVENTS_DIR."""
    d = os.environ.get("RAFIKI_EVENTS_DIR")
    if d:
        events.configure(d)
