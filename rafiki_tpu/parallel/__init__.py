"""Parallelism: device meshes, within-trial data parallelism, ensemble
sharding, and sequence parallelism.

Reference contrast (SURVEY.md §2 "Parallelism strategies"): the
reference's only parallelism is job-level (one trial per GPU container;
one inference worker per served trial). This package adds the
TPU-native axes the north star requires: within-trial data parallelism
over ICI (mesh + sharding annotations → XLA psum), stacked-ensemble
serving (vmap over trials, sharded over chips), and — for completeness
beyond the reference — ring-attention sequence parallelism for
long-context models.
"""

from rafiki_tpu.parallel.mesh import (
    data_parallel_mesh,
    local_devices,
    partition_devices,
)

__all__ = ["data_parallel_mesh", "local_devices", "partition_devices"]
