"""Device mesh helpers.

The scheduler thinks in *device sets*: each train worker owns a set of
chips; a 1-chip set runs the trial under ``jax.default_device``; a
k-chip set becomes a 1-axis ``Mesh(("dp",))`` for within-trial data
parallelism (gradient all-reduce over ICI inserted by XLA from sharding
annotations — see rafiki_tpu/ops/train.py).

Multi-host: `jax.distributed.initialize()` + `jax.devices()` yields the
global device list; the same partitioning logic then spans hosts, with
collectives riding ICI within a slice and DCN across slices.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


def local_devices(platform: Optional[str] = None) -> List:
    import jax

    return list(jax.local_devices()) if platform is None else [
        d for d in jax.local_devices() if d.platform == platform
    ]


def data_parallel_mesh(devices: Sequence) -> "jax.sharding.Mesh":
    """A 1-D mesh with axis "dp" over the given devices."""
    import jax

    return jax.sharding.Mesh(np.asarray(list(devices)), ("dp",))


def partition_devices(devices: Sequence, n_workers: int) -> List[List]:
    """Split a device list into n_workers contiguous groups (contiguous
    device ids share ICI neighbourhoods on TPU slices).

    len(devices) must be divisible by n_workers so every worker's dp
    mesh has the same size (uniform trial throughput).
    """
    devices = list(devices)
    if n_workers <= 0:
        raise ValueError("n_workers must be positive")
    if len(devices) % n_workers != 0:
        raise ValueError(
            f"{len(devices)} devices do not split evenly over {n_workers} workers")
    per = len(devices) // n_workers
    return [devices[i * per : (i + 1) * per] for i in range(n_workers)]
