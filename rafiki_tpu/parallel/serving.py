"""Stacked serving adapter: k same-architecture trials behind one
``predict()``.

This is the serving-path payoff of SURVEY.md §7 step 8: when an
inference job's top-k trials share a compiled-shape signature, the
services manager serves them as ONE InferenceWorker wrapping this
adapter — a single vmapped XLA program per query batch (optionally
chip-sharded over a "model" mesh axis) instead of k separate workers
each doing its own device round-trip. Heterogeneous top-k falls back
to the reference-shaped one-worker-per-trial path.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from rafiki_tpu.parallel.ensemble import StackedEnsemble


class StackedTrialModel:
    """Implements the slice of the model contract InferenceWorker uses
    (``predict``/``destroy``), fusing k loaded same-arch JaxModels."""

    def __init__(self, models: Sequence[Any], devices: Optional[Sequence] = None,
                 batch_size: int = 64):
        if not models:
            raise ValueError("Need at least one model to stack")
        first = models[0]
        module = first._module
        if any(m._arch != first._arch for m in models):
            raise ValueError("Models disagree on architecture; cannot stack")
        self.batch_size = int(batch_size)
        self._first = first

        def apply_fn(params, batch):
            return module.apply({"params": params}, batch["x"], train=False)

        params_list = [m._loop.params for m in models]
        self._ens = StackedEnsemble(apply_fn, params_list, devices=devices)
        # The stacked copy is the serving copy: drop the per-model loops
        # (all but the first, which predict() still uses for preprocess).
        for m in models[1:]:
            m.destroy()

    def predict(self, queries: List[Any]) -> List[List[float]]:
        x = self._first.preprocess(
            np.asarray(queries, dtype=self._first._input_dtype()))
        return self.predict_proba(x).tolist()

    def predict_proba(self, x: np.ndarray) -> np.ndarray:
        """Fixed-size padded chunks: one compiled program regardless of
        query count (micro-batches vary; XLA shapes must not)."""
        bs = self.batch_size
        out = []
        for start in range(0, len(x), bs):
            chunk = x[start:start + bs]
            valid = len(chunk)
            if valid < bs:
                pad = np.zeros((bs - valid,) + chunk.shape[1:], chunk.dtype)
                chunk = np.concatenate([chunk, pad])
            probs = self._ens.ensemble_proba({"x": chunk})
            out.append(probs[:valid])
        return np.concatenate(out) if out else np.zeros((0, 0))

    def warmup(self) -> float:
        """Pay the stacked program's XLA compile at SERVICE CREATION,
        not on the first live request: one forward over a zero batch of
        the compiled shape. Returns the warmup wall seconds (≈ compile
        time) for the serving/route journal record."""
        t0 = time.monotonic()
        input_shape = tuple(self._first._arch[1])
        x = self._first.preprocess(
            np.zeros((self.batch_size,) + input_shape,
                     self._first._input_dtype()))
        self.predict_proba(x)
        return time.monotonic() - t0

    def destroy(self) -> None:
        self._first.destroy()
        self._ens = None


def _param_shape_tree(model) -> Any:
    import jax

    return jax.tree_util.tree_map(lambda a: (tuple(a.shape), str(a.dtype)),
                                  model._loop.params)


def build_stacked(trials: List[dict], models: List[Any],
                  devices: Optional[Sequence] = None,
                  batch_size: int = 64,
                  ) -> Tuple[Optional[StackedTrialModel], str]:
    """Return ``(stacked adapter, reason)`` — the adapter when every
    trial is stackable (reason ``"stacked"``), else ``(None, why)`` so
    the route decision is journal-able per job (docs/serving.md).

    Stackable = same model template, a JaxModel-style loaded instance
    (module + params pytree), and IDENTICAL param tree shapes — the
    exact predictor of whether k param sets can be stacked into one
    vmapped program. Notably this is weaker than equal compiled-shape
    signatures: the training-time shape signature includes knobs like
    batch_size that change nothing about the serving architecture, and
    gating on it would needlessly send stackable top-k sets down the
    k-workers fallback. Width/depth differences DO differ in param
    shapes and fall back. Dropout-rate differences vanish at eval time
    (deterministic apply), so serving through the first model's module
    is exact for all k.
    """
    if len(models) < 2:
        return None, "single-trial"
    if len({t.get("model_name") for t in trials}) != 1:
        return None, "mixed-templates"
    if not all(hasattr(m, "_module") and getattr(m, "_loop", None) is not None
               for m in models):
        return None, "not-jax-loaded"
    try:
        shapes0 = _param_shape_tree(models[0])
        if any(_param_shape_tree(m) != shapes0 for m in models[1:]):
            return None, "param-shape-mismatch"
        return (StackedTrialModel(models, devices=devices,
                                  batch_size=batch_size), "stacked")
    except Exception as e:  # any mismatch → caller falls back to per-trial
        return None, f"build-error: {type(e).__name__}"


def try_build_stacked(trials: List[dict], models: List[Any],
                      devices: Optional[Sequence] = None,
                      batch_size: int = 64) -> Optional[StackedTrialModel]:
    """Back-compat wrapper over :func:`build_stacked` (adapter only)."""
    return build_stacked(trials, models, devices=devices,
                         batch_size=batch_size)[0]
