"""Stacked ensemble forward: k trials, one XLA program.

Reference contrast: the reference serves k trials as k separate
processes and ensembles on the host (SURVEY.md §3.2). When the top-k
trials share an architecture (same compiled-shape signature), the
TPU-native form stacks their parameter pytrees along a leading "model"
axis and ``vmap``s the forward — one program, one launch, k logits
batches — optionally sharded across chips via a ("model",) mesh axis
so each chip holds 1/k of the ensemble (ICI gathers the outputs).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_params(params_list: Sequence[Any]):
    """Stack k identically-shaped pytrees along a new leading axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)


def make_ensemble_forward(apply_fn, mesh: Optional[Mesh] = None):
    """Build jit'd fn: (stacked_params, batch) -> (k, B, C) probabilities.

    apply_fn: (params, batch) -> logits for ONE model.
    With a ("model",)-axis mesh, stacked params are sharded across chips
    (each chip computes its sub-ensemble) and the batch is replicated.
    """

    def fwd(stacked, batch):
        logits = jax.vmap(lambda p: apply_fn(p, batch))(stacked)
        return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    if mesh is None:
        return jax.jit(fwd)

    # shard_map, not sharded-vmap: vmap lowers convs to grouped convs
    # whose feature_group dimension the SPMD partitioner cannot split
    # over "model". Under shard_map each chip vmaps over its local k/n
    # sub-ensemble with ordinary convs — embarrassingly parallel, no
    # collectives until the host gathers the output.
    try:
        from jax import shard_map  # jax >= 0.8 (check_rep renamed check_vma)
        kw = {"check_vma": False}
    except ImportError:  # pragma: no cover — older jax
        from jax.experimental.shard_map import shard_map
        kw = {"check_rep": False}

    body = shard_map(
        fwd, mesh=mesh,
        in_specs=(P("model"), P()),
        out_specs=P("model"),
        **kw,
    )
    return jax.jit(body)


class StackedEnsemble:
    """Serve k same-architecture trials as one vmapped program."""

    def __init__(self, apply_fn, params_list: Sequence[Any],
                 devices: Optional[Sequence] = None):
        self.k = len(params_list)
        mesh = None
        if devices is not None and len(devices) > 1:
            # The model axis must divide the ensemble across chips evenly;
            # use as many chips as divide k.
            n = max(d for d in range(1, min(len(devices), self.k) + 1) if self.k % d == 0)
            if n > 1:
                mesh = Mesh(np.asarray(list(devices)[:n]), ("model",))
        self.mesh = mesh
        self._fwd = make_ensemble_forward(apply_fn, mesh)
        stacked = stack_params(list(params_list))
        if mesh is not None:
            stacked = jax.device_put(stacked, NamedSharding(mesh, P("model")))
        self._stacked = stacked

    def predict_proba(self, batch: dict) -> np.ndarray:
        """Returns (k, B, C) per-model probabilities (host array)."""
        return np.asarray(self._fwd(self._stacked, batch))

    def ensemble_proba(self, batch: dict) -> np.ndarray:
        """Mean over the model axis → (B, C), computed with the SAME
        host-side op sequence as the replicated route's ensembler
        (predictor/ensemble.py: f32 stack-mean, shared renormalize) —
        the stacked route must bit-match the host ensemble of k serial
        forwards, which is what the parity test pins."""
        from rafiki_tpu.predictor.ensemble import renormalize_probs

        probs = self.predict_proba(batch).astype(np.float32)
        return renormalize_probs(np.mean(probs, axis=0))
