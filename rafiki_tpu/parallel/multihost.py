"""Multi-host (DCN) helpers: one logical dp worker spanning processes.

TPU pods put chips behind multiple hosts; JAX's model is SPMD — every
process runs the same program over its local chips while XLA runs the
collectives over ICI within a host and DCN across hosts
(``jax.distributed.initialize`` in worker/main.py joins the cluster;
the reference's NCCL/MPI role — SURVEY.md §5 comm-backend row).

The control plane stays single-headed: process 0 of a worker group is
the LEADER and runs the normal trial loop (meta store writes, advisor
calls, params persistence); the other processes run
``worker.follower.FollowerWorker``, which mirrors the leader's trials
compute-for-compute so the collective steps line up. Helpers here are
the small shared vocabulary for that split.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


def is_leader() -> bool:
    """Process 0 of the jax.distributed cluster owns the control plane."""
    import jax

    return jax.process_index() == 0


def global_put(batch: Dict[str, np.ndarray], sharding):
    """Build global device arrays for a host batch whose full value is
    known (identically) on every process.

    ``jax.device_put`` cannot place onto a sharding with
    non-addressable devices; ``make_array_from_callback`` materializes
    only this process's shards. Determinism note: callers guarantee the
    same host batch on every process (dataset iteration is seeded by
    trial seed + epoch, so leader and followers draw identical
    batches).
    """
    import jax

    out = {}
    for k, v in batch.items():
        v = np.asarray(v)
        out[k] = jax.make_array_from_callback(
            v.shape, sharding, lambda idx, v=v: v[idx])
    return out
