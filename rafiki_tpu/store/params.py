"""Params store: trained model parameters on disk, keyed by params id.

Reference parity: the reference persists each trial's
``dump_parameters()`` blob via the meta store / a shared params volume
(SURVEY.md §5 "Checkpoint / resume"). Same trial-granular model here:
one file per params id with sha256 integrity, plus a mid-trial
checkpoint namespace (``<trial>/ckpt_<step>``) the reference lacks —
used by the worker for resumable long trials.

Blobs are whatever the model's ``dump_parameters`` returned (for
JaxModel: a pickled dict holding flax msgpack bytes — a host-side
pytree snapshot, cheap to write from one `jax.device_get`).

Chaos hook: ``store.params_write`` fires before each write — ``delay``
simulates a slow disk, ``error`` a failing one (raises
:class:`rafiki_tpu.chaos.ChaosError`, an OSError). Keyed by params id
so scenarios can target checkpoint writes (``match=_ckpt_``) apart
from final params. Inert unless ``RAFIKI_CHAOS`` is set.
"""

from __future__ import annotations

import hashlib
import os
import uuid
from pathlib import Path
from typing import List, Optional

from rafiki_tpu.chaos import hook as _chaos


class ParamsStore:
    def __init__(self, params_dir: str | os.PathLike):
        self._dir = Path(params_dir)
        self._dir.mkdir(parents=True, exist_ok=True)

    @property
    def directory(self) -> Path:
        """Root directory (subprocess workers reopen it by path)."""
        return self._dir

    def _path(self, params_id: str) -> Path:
        if "/" in params_id or ".." in params_id:
            raise ValueError(f"Bad params id {params_id!r}")
        return self._dir / f"{params_id}.params"

    def save(self, blob: bytes, params_id: Optional[str] = None) -> str:
        params_id = params_id or uuid.uuid4().hex
        _chaos("store.params_write", params_id)  # delay=slow disk, error=failed write
        path = self._path(params_id)
        tmp = path.with_suffix(".tmp")
        digest = hashlib.sha256(blob).hexdigest().encode()
        with open(tmp, "wb") as f:
            f.write(digest + b"\n" + blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic: readers never see a torn file
        return params_id

    def load(self, params_id: str) -> bytes:
        with open(self._path(params_id), "rb") as f:
            digest, blob = f.read().split(b"\n", 1)
        if hashlib.sha256(blob).hexdigest().encode() != digest:
            raise IOError(f"Params {params_id} failed integrity check")
        return blob

    def exists(self, params_id: str) -> bool:
        return self._path(params_id).exists()

    def size(self, params_id: str) -> int:
        """On-disk byte size of the params blob (0 when absent) — the
        HBM residency charge estimate for co-hosted serving."""
        try:
            return self._path(params_id).stat().st_size
        except OSError:
            return 0

    def delete(self, params_id: str) -> None:
        self._path(params_id).unlink(missing_ok=True)

    def list(self) -> List[str]:
        return sorted(p.stem for p in self._dir.glob("*.params"))

    # -- mid-trial checkpoints ----------------------------------------------

    def save_checkpoint(self, trial_id: str, step: int, blob: bytes) -> str:
        return self.save(blob, params_id=f"{trial_id}_ckpt_{step}")

    def latest_checkpoint(self, trial_id: str) -> Optional[tuple]:
        """Return (step, blob) of the newest checkpoint for a trial.

        Only ``<trial>_ckpt_<int>`` ids are checkpoint heads; sharded
        checkpoints park their per-shard chunk blobs in the same
        namespace with a non-integer suffix (``..._ckpt_3_s0of2``,
        shard/checkpoint.py) so one ``delete_checkpoints`` sweep
        reclaims both — those are skipped here, never parsed."""
        best = None
        for p in self._dir.glob(f"{trial_id}_ckpt_*.params"):
            suffix = p.stem.rsplit("_", 1)[1]
            if not suffix.isdigit():
                continue
            step = int(suffix)
            if best is None or step > best:
                best = step
        if best is None:
            return None
        return best, self.load(f"{trial_id}_ckpt_{best}")

    def delete_checkpoints(self, trial_id: str) -> None:
        for p in self._dir.glob(f"{trial_id}_ckpt_*.params"):
            p.unlink(missing_ok=True)
