"""Meta store: all control-plane state in one sqlite3 file.

Reference parity: rafiki/db/database.py `Database` (unverified):
create/get users, models, train jobs (+ per-model sub-jobs), trials
(knobs JSON, score, params ref, status, logs), inference jobs,
services; queries like ``get_best_trials_of_train_job(limit=k)`` and
``mark_trial_as_errored``. The reference backs this with Postgres;
sqlite3-in-WAL is the TPU-host-native choice (one host drives the
chips; multi-host pods still share one control-plane host) and keeps
the framework dependency-free. Writes are short transactions; trial
claiming uses an atomic UPDATE so concurrent workers never double-run
a trial.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from rafiki_tpu.constants import (
    InferenceJobStatus,
    ServiceStatus,
    ServiceType,
    TrainJobStatus,
    TrialStatus,
)

_SCHEMA = """
PRAGMA journal_mode=WAL;
CREATE TABLE IF NOT EXISTS users (
    id TEXT PRIMARY KEY, email TEXT UNIQUE NOT NULL,
    password_hash TEXT NOT NULL, user_type TEXT NOT NULL,
    banned INTEGER DEFAULT 0, created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS models (
    id TEXT PRIMARY KEY, name TEXT NOT NULL, task TEXT NOT NULL,
    user_id TEXT, model_file BLOB NOT NULL, model_class TEXT NOT NULL,
    dependencies TEXT DEFAULT '{}', access_right TEXT DEFAULT 'PRIVATE',
    docs TEXT DEFAULT '', created_at REAL NOT NULL,
    UNIQUE(name, user_id)
);
CREATE TABLE IF NOT EXISTS train_jobs (
    id TEXT PRIMARY KEY, app TEXT NOT NULL, app_version INTEGER NOT NULL,
    task TEXT NOT NULL, user_id TEXT,
    train_dataset_uri TEXT NOT NULL, val_dataset_uri TEXT NOT NULL,
    budget TEXT NOT NULL, status TEXT NOT NULL,
    created_at REAL NOT NULL, stopped_at REAL,
    UNIQUE(app, app_version, user_id)
);
CREATE TABLE IF NOT EXISTS sub_train_jobs (
    id TEXT PRIMARY KEY, train_job_id TEXT NOT NULL, model_id TEXT NOT NULL,
    status TEXT NOT NULL, advisor_id TEXT, claimed INTEGER DEFAULT 0,
    created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS trials (
    id TEXT PRIMARY KEY, sub_train_job_id TEXT NOT NULL, no INTEGER NOT NULL,
    model_name TEXT NOT NULL, knobs TEXT NOT NULL, status TEXT NOT NULL,
    score REAL, params_id TEXT, worker_id TEXT, shape_sig TEXT,
    service_id TEXT,
    error TEXT, started_at REAL, stopped_at REAL, created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS trial_logs (
    id INTEGER PRIMARY KEY AUTOINCREMENT, trial_id TEXT NOT NULL,
    time REAL NOT NULL, entry TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS inference_jobs (
    id TEXT PRIMARY KEY, train_job_id TEXT NOT NULL, user_id TEXT,
    status TEXT NOT NULL, predictor_host TEXT,
    created_at REAL NOT NULL, stopped_at REAL
);
CREATE TABLE IF NOT EXISTS services (
    id TEXT PRIMARY KEY, service_type TEXT NOT NULL, status TEXT NOT NULL,
    job_id TEXT, worker_index INTEGER, devices TEXT,
    heartbeat_at REAL, created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_trials_sub_job ON trials(sub_train_job_id);
CREATE INDEX IF NOT EXISTS idx_trials_score ON trials(status, score);
CREATE INDEX IF NOT EXISTS idx_trial_logs ON trial_logs(trial_id);
"""


def _now() -> float:
    return time.time()


def _uid() -> str:
    return uuid.uuid4().hex


class MetaStore:
    """Typed CRUD over sqlite3; safe across threads and processes."""

    def __init__(self, db_path: str | os.PathLike):
        self._path = str(db_path)
        self._local = threading.local()
        with self._conn() as c:
            c.executescript(_SCHEMA)
            self._migrate(c)

    @staticmethod
    def _migrate(c: sqlite3.Connection) -> None:
        """Additive migrations for databases created by older versions."""
        cols = {r[1] for r in c.execute("PRAGMA table_info(trials)")}
        if "service_id" not in cols:
            c.execute("ALTER TABLE trials ADD COLUMN service_id TEXT")

    @property
    def path(self) -> str:
        """Filesystem path of the sqlite file (subprocess workers reopen it)."""
        return self._path

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self._path, timeout=30.0)
            conn.row_factory = sqlite3.Row
            conn.execute("PRAGMA busy_timeout=30000")
            conn.execute("PRAGMA synchronous=NORMAL")
            self._local.conn = conn
        return conn

    def _one(self, sql: str, args=()) -> Optional[dict]:
        row = self._conn().execute(sql, args).fetchone()
        return dict(row) if row else None

    def _all(self, sql: str, args=()) -> List[dict]:
        return [dict(r) for r in self._conn().execute(sql, args).fetchall()]

    # -- users ---------------------------------------------------------------

    def create_user(self, email: str, password_hash: str, user_type: str) -> dict:
        uid = _uid()
        with self._conn() as c:
            c.execute(
                "INSERT INTO users (id, email, password_hash, user_type, created_at)"
                " VALUES (?,?,?,?,?)",
                (uid, email, password_hash, user_type, _now()),
            )
        return self.get_user(uid)

    def get_user(self, user_id: str) -> Optional[dict]:
        return self._one("SELECT * FROM users WHERE id=?", (user_id,))

    def get_user_by_email(self, email: str) -> Optional[dict]:
        return self._one("SELECT * FROM users WHERE email=?", (email,))

    def ban_user(self, user_id: str, banned: bool = True) -> None:
        with self._conn() as c:
            c.execute("UPDATE users SET banned=? WHERE id=?", (int(banned), user_id))

    def get_users(self) -> List[dict]:
        return self._all("SELECT * FROM users ORDER BY created_at")

    # -- models --------------------------------------------------------------

    def create_model(self, name: str, task: str, user_id: Optional[str],
                     model_file: bytes, model_class: str,
                     dependencies: Optional[Dict[str, str]] = None,
                     access_right: str = "PRIVATE", docs: str = "") -> dict:
        mid = _uid()
        with self._conn() as c:
            c.execute(
                "INSERT INTO models (id, name, task, user_id, model_file, model_class,"
                " dependencies, access_right, docs, created_at) VALUES (?,?,?,?,?,?,?,?,?,?)",
                (mid, name, task, user_id, model_file, model_class,
                 json.dumps(dependencies or {}), access_right, docs, _now()),
            )
        return self.get_model(mid)

    def get_model(self, model_id: str) -> Optional[dict]:
        m = self._one("SELECT * FROM models WHERE id=?", (model_id,))
        return self._load_model_row(m)

    def get_model_by_name(self, name: str, user_id: Optional[str] = None) -> Optional[dict]:
        if user_id is not None:
            m = self._one("SELECT * FROM models WHERE name=? AND user_id=?", (name, user_id))
            if m:
                return self._load_model_row(m)
        m = self._one("SELECT * FROM models WHERE name=? ORDER BY created_at DESC", (name,))
        return self._load_model_row(m)

    def get_models(self) -> List[dict]:
        return [self._load_model_row(m) for m in
                self._all("SELECT * FROM models ORDER BY created_at")]

    def get_models_of_task(self, task: str) -> List[dict]:
        return [self._load_model_row(m) for m in
                self._all("SELECT * FROM models WHERE task=? ORDER BY created_at", (task,))]

    @staticmethod
    def _load_model_row(m: Optional[dict]) -> Optional[dict]:
        if m is None:
            return None
        m["dependencies"] = json.loads(m["dependencies"])
        return m

    # -- train jobs ----------------------------------------------------------

    def create_train_job(self, app: str, task: str, user_id: Optional[str],
                         train_dataset_uri: str, val_dataset_uri: str,
                         budget: Dict[str, Any]) -> dict:
        prev = self._one(
            "SELECT MAX(app_version) AS v FROM train_jobs WHERE app=? AND user_id IS ?",
            (app, user_id))
        version = (prev["v"] or 0) + 1
        jid = _uid()
        with self._conn() as c:
            c.execute(
                "INSERT INTO train_jobs (id, app, app_version, task, user_id,"
                " train_dataset_uri, val_dataset_uri, budget, status, created_at)"
                " VALUES (?,?,?,?,?,?,?,?,?,?)",
                (jid, app, version, task, user_id, train_dataset_uri, val_dataset_uri,
                 json.dumps(budget), TrainJobStatus.STARTED.value, _now()),
            )
        return self.get_train_job(jid)

    def get_train_job(self, job_id: str) -> Optional[dict]:
        j = self._one("SELECT * FROM train_jobs WHERE id=?", (job_id,))
        if j:
            j["budget"] = json.loads(j["budget"])
        return j

    def get_train_job_by_app(self, app: str, app_version: int = -1,
                             user_id: Optional[str] = None) -> Optional[dict]:
        """``user_id`` scopes the lookup to that user's jobs (pass None
        for an unscoped/admin lookup)."""
        q = "SELECT * FROM train_jobs WHERE app=?"
        args: list = [app]
        if app_version > 0:
            q += " AND app_version=?"
            args.append(app_version)
        if user_id is not None:
            q += " AND user_id=?"
            args.append(user_id)
        q += " ORDER BY app_version DESC"
        j = self._one(q, tuple(args))
        if j:
            j["budget"] = json.loads(j["budget"])
        return j

    def get_train_jobs(self, user_id: Optional[str] = None) -> List[dict]:
        rows = (self._all("SELECT * FROM train_jobs WHERE user_id=? ORDER BY created_at", (user_id,))
                if user_id else self._all("SELECT * FROM train_jobs ORDER BY created_at"))
        for j in rows:
            j["budget"] = json.loads(j["budget"])
        return rows

    def update_train_job_status(self, job_id: str, status: str) -> None:
        stopped = _now() if status in (TrainJobStatus.STOPPED.value,
                                       TrainJobStatus.COMPLETED.value,
                                       TrainJobStatus.ERRORED.value) else None
        with self._conn() as c:
            if stopped:
                c.execute("UPDATE train_jobs SET status=?, stopped_at=? WHERE id=?",
                          (status, stopped, job_id))
            else:
                c.execute("UPDATE train_jobs SET status=? WHERE id=?", (status, job_id))

    # -- sub train jobs (one per model in the job) --------------------------

    def create_sub_train_job(self, train_job_id: str, model_id: str,
                             advisor_id: Optional[str] = None) -> dict:
        sid = _uid()
        with self._conn() as c:
            c.execute(
                "INSERT INTO sub_train_jobs (id, train_job_id, model_id, status,"
                " advisor_id, created_at) VALUES (?,?,?,?,?,?)",
                (sid, train_job_id, model_id, TrainJobStatus.STARTED.value,
                 advisor_id, _now()),
            )
        return self._one("SELECT * FROM sub_train_jobs WHERE id=?", (sid,))

    def get_sub_train_job(self, sub_id: str) -> Optional[dict]:
        return self._one("SELECT * FROM sub_train_jobs WHERE id=?", (sub_id,))

    def get_sub_train_jobs(self, train_job_id: str) -> List[dict]:
        return self._all("SELECT * FROM sub_train_jobs WHERE train_job_id=?", (train_job_id,))

    def update_sub_train_job(self, sub_id: str, status: Optional[str] = None,
                             advisor_id: Optional[str] = None) -> None:
        with self._conn() as c:
            if status is not None:
                c.execute("UPDATE sub_train_jobs SET status=? WHERE id=?", (status, sub_id))
            if advisor_id is not None:
                c.execute("UPDATE sub_train_jobs SET advisor_id=? WHERE id=?", (advisor_id, sub_id))

    @staticmethod
    def _claim_slot(c: sqlite3.Connection, sub_id: str, max_trials: int) -> bool:
        """Claim one of ``max_trials`` trial slots inside the caller's
        open transaction; False = budget exhausted. The single source of
        the budget-gate SQL for both claim forms below."""
        cur = c.execute(
            "UPDATE sub_train_jobs SET claimed = claimed + 1"
            " WHERE id=? AND claimed < ?", (sub_id, int(max_trials)))
        return cur.rowcount > 0

    def claim_trial_slot(self, sub_id: str, max_trials: int) -> bool:
        """Standalone atomic slot claim — the concurrency gate that
        stops N workers racing past a trial-count budget (the reference
        leaned on Postgres transactions for the same invariant).
        Production workers use ``create_trial(budget_max=...)`` instead,
        which claims in the same transaction as the row insert; this
        form remains for callers that size work before creating rows."""
        with self._conn() as c:
            return self._claim_slot(c, sub_id, max_trials)

    # -- trials --------------------------------------------------------------

    def create_trial(self, sub_train_job_id: str, model_name: str,
                     knobs: Dict[str, Any], worker_id: Optional[str] = None,
                     shape_sig: Optional[str] = None,
                     service_id: Optional[str] = None,
                     budget_max: Optional[int] = None) -> Optional[dict]:
        """Insert a RUNNING trial row; with ``budget_max``, a trial-count
        slot is claimed in the SAME write transaction (claimed++ guarded
        by claimed < budget_max) and None is returned when the budget is
        exhausted. The combined form exists for crash safety: a worker
        killed between a separate ``claim_trial_slot`` and the insert
        would leak the slot and silently shrink the job's budget."""
        tid = _uid()
        with self._conn() as c:
            if budget_max is not None and not self._claim_slot(
                    c, sub_train_job_id, budget_max):
                return None
            # 'no' is assigned inside the INSERT's write transaction so
            # concurrent workers can't get duplicate trial numbers.
            c.execute(
                "INSERT INTO trials (id, sub_train_job_id, no, model_name, knobs, status,"
                " worker_id, shape_sig, service_id, started_at, created_at)"
                " VALUES (?,?,"
                "   (SELECT COUNT(*)+1 FROM trials WHERE sub_train_job_id=?),"
                " ?,?,?,?,?,?,?,?)",
                (tid, sub_train_job_id, sub_train_job_id, model_name, json.dumps(knobs),
                 TrialStatus.RUNNING.value, worker_id, shape_sig, service_id,
                 _now(), _now()),
            )
        return self.get_trial(tid)

    def get_trial(self, trial_id: str) -> Optional[dict]:
        t = self._one("SELECT * FROM trials WHERE id=?", (trial_id,))
        if t:
            t["knobs"] = json.loads(t["knobs"])
        return t

    def mark_trial_as_completed(self, trial_id: str, score: float, params_id: Optional[str]) -> None:
        with self._conn() as c:
            c.execute(
                "UPDATE trials SET status=?, score=?, params_id=?, stopped_at=? WHERE id=?",
                (TrialStatus.COMPLETED.value, float(score), params_id, _now(), trial_id),
            )

    def mark_trial_as_errored(self, trial_id: str, error: str) -> None:
        with self._conn() as c:
            c.execute(
                "UPDATE trials SET status=?, error=?, stopped_at=? WHERE id=?",
                (TrialStatus.ERRORED.value, error[:4000], _now(), trial_id),
            )

    def mark_trial_as_running(self, trial_id: str,
                              service_id: Optional[str] = None,
                              worker_id: Optional[str] = None) -> None:
        """Re-adopt a trial for resume: back to RUNNING, stale error and
        stop time cleared, and — when the adopter passes its identity —
        rebound to the new service/worker so a concurrent recovery sweep
        sees a live owner and does not double-adopt."""
        with self._conn() as c:
            c.execute(
                "UPDATE trials SET status=?, error=NULL, stopped_at=NULL,"
                " started_at=?,"
                " service_id=COALESCE(?, service_id),"
                " worker_id=COALESCE(?, worker_id)"
                " WHERE id=?",
                (TrialStatus.RUNNING.value, _now(), service_id, worker_id,
                 trial_id))

    def adopt_trial(self, trial_id: str, prev_service_id: Optional[str],
                    service_id: str, worker_id: str,
                    expected_status: Optional[str] = None) -> bool:
        """Atomically take ownership of an orphaned trial.

        Compare-and-swap on (status, service_id): succeeds only if the
        trial still has the status the sweep observed (RUNNING by
        default; ``resume_sweep`` also adopts QUEUED rows a crashed
        supervisor claimed but never assigned) and is still bound to
        the service the sweep observed, so (a) two concurrent recovery
        sweeps adopt each orphan exactly once — the loser's UPDATE
        matches zero rows — and (b) a zombie worker that finished the
        trial in the meantime keeps its terminal status (no COMPLETED
        -> RUNNING regression).
        """
        expected = expected_status or TrialStatus.RUNNING.value
        with self._conn() as c:
            cur = c.execute(
                "UPDATE trials SET status=?, error=NULL, stopped_at=NULL,"
                " started_at=?, service_id=?, worker_id=?"
                " WHERE id=? AND status=? AND service_id IS ?",
                (TrialStatus.RUNNING.value, _now(), service_id, worker_id,
                 trial_id, expected, prev_service_id))
            return cur.rowcount > 0

    def mark_trial_as_terminated(self, trial_id: str) -> None:
        with self._conn() as c:
            c.execute("UPDATE trials SET status=?, stopped_at=? WHERE id=?",
                      (TrialStatus.TERMINATED.value, _now(), trial_id))

    def get_trials_of_sub_train_job(self, sub_train_job_id: str) -> List[dict]:
        rows = self._all(
            "SELECT * FROM trials WHERE sub_train_job_id=? ORDER BY no", (sub_train_job_id,))
        for t in rows:
            t["knobs"] = json.loads(t["knobs"])
        return rows

    def get_trials_of_train_job(self, train_job_id: str) -> List[dict]:
        rows = self._all(
            "SELECT t.* FROM trials t JOIN sub_train_jobs s ON t.sub_train_job_id=s.id"
            " WHERE s.train_job_id=? ORDER BY t.created_at", (train_job_id,))
        for t in rows:
            t["knobs"] = json.loads(t["knobs"])
        return rows

    def get_best_trials_of_train_job(self, train_job_id: str, limit: int = 2) -> List[dict]:
        rows = self._all(
            "SELECT t.* FROM trials t JOIN sub_train_jobs s ON t.sub_train_job_id=s.id"
            " WHERE s.train_job_id=? AND t.status=? AND t.score IS NOT NULL"
            " ORDER BY t.score DESC, t.stopped_at ASC LIMIT ?",
            (train_job_id, TrialStatus.COMPLETED.value, limit))
        for t in rows:
            t["knobs"] = json.loads(t["knobs"])
        return rows

    def count_trials_of_sub_train_job(self, sub_train_job_id: str,
                                      statuses: Optional[List[str]] = None) -> int:
        if statuses:
            marks = ",".join("?" * len(statuses))
            return self._one(
                f"SELECT COUNT(*) AS n FROM trials WHERE sub_train_job_id=? AND status IN ({marks})",
                (sub_train_job_id, *statuses))["n"]
        return self._one("SELECT COUNT(*) AS n FROM trials WHERE sub_train_job_id=?",
                         (sub_train_job_id,))["n"]

    def get_orphaned_trials(self, stale_after_s: float,
                            sub_train_job_id: Optional[str] = None) -> List[dict]:
        """RUNNING trials whose owning service is terminal, missing, or
        heartbeat-stale — i.e. trials whose worker died mid-trial. The
        failure-detection primitive (SURVEY.md §5: heartbeats in the
        meta store; the reference loses such trials). Trials with no
        service_id at all are NOT flagged: a worker that registered no
        service row opted out of failure detection, and flagging those
        would adopt healthy in-flight trials."""
        cutoff = _now() - stale_after_s
        q = ("SELECT t.* FROM trials t LEFT JOIN services s ON t.service_id=s.id"
             " WHERE t.status=? AND t.service_id IS NOT NULL AND ("
             "   s.id IS NULL"
             "   OR s.status IN ('STOPPED','ERRORED')"
             "   OR s.heartbeat_at < ?)")
        args: list = [TrialStatus.RUNNING.value, cutoff]
        if sub_train_job_id is not None:
            q += " AND t.sub_train_job_id=?"
            args.append(sub_train_job_id)
        rows = self._all(q, tuple(args))
        for t in rows:
            t["knobs"] = json.loads(t["knobs"])
        return rows

    # -- trial logs ----------------------------------------------------------

    def add_trial_log(self, trial_id: str, entry: Dict[str, Any]) -> None:
        with self._conn() as c:
            c.execute("INSERT INTO trial_logs (trial_id, time, entry) VALUES (?,?,?)",
                      (trial_id, entry.get("time", _now()), json.dumps(entry)))

    def get_trial_logs(self, trial_id: str) -> List[dict]:
        return [json.loads(r["entry"]) for r in
                self._all("SELECT * FROM trial_logs WHERE trial_id=? ORDER BY id", (trial_id,))]

    # -- inference jobs ------------------------------------------------------

    def create_inference_job(self, train_job_id: str, user_id: Optional[str]) -> dict:
        iid = _uid()
        with self._conn() as c:
            c.execute(
                "INSERT INTO inference_jobs (id, train_job_id, user_id, status, created_at)"
                " VALUES (?,?,?,?,?)",
                (iid, train_job_id, user_id, InferenceJobStatus.STARTED.value, _now()),
            )
        return self.get_inference_job(iid)

    def get_inference_job(self, job_id: str) -> Optional[dict]:
        return self._one("SELECT * FROM inference_jobs WHERE id=?", (job_id,))

    def get_inference_job_of_train_job(self, train_job_id: str) -> Optional[dict]:
        return self._one(
            "SELECT * FROM inference_jobs WHERE train_job_id=? AND status IN ('STARTED','RUNNING')"
            " ORDER BY created_at DESC", (train_job_id,))

    def update_inference_job(self, job_id: str, status: Optional[str] = None,
                             predictor_host: Optional[str] = None) -> None:
        with self._conn() as c:
            if status is not None:
                stopped = _now() if status in (InferenceJobStatus.STOPPED.value,
                                               InferenceJobStatus.ERRORED.value) else None
                c.execute("UPDATE inference_jobs SET status=?, stopped_at=COALESCE(?, stopped_at)"
                          " WHERE id=?", (status, stopped, job_id))
            if predictor_host is not None:
                c.execute("UPDATE inference_jobs SET predictor_host=? WHERE id=?",
                          (predictor_host, job_id))

    # -- services (worker registry; replaces Docker Swarm service rows) -----

    def create_service(self, service_type: str, job_id: Optional[str] = None,
                       worker_index: Optional[int] = None,
                       devices: Optional[List[str]] = None) -> dict:
        sid = _uid()
        with self._conn() as c:
            c.execute(
                "INSERT INTO services (id, service_type, status, job_id, worker_index,"
                " devices, heartbeat_at, created_at) VALUES (?,?,?,?,?,?,?,?)",
                (sid, service_type, ServiceStatus.STARTED.value, job_id, worker_index,
                 json.dumps(devices or []), _now(), _now()),
            )
        return self._one("SELECT * FROM services WHERE id=?", (sid,))

    def update_service(self, service_id: str, status: Optional[str] = None,
                       heartbeat: bool = False) -> None:
        if heartbeat:
            # Chaos hook: a skipped service heartbeat ages the lease the
            # orphan sweep (get_orphaned_trials) reads — how scenarios
            # simulate a wedged train worker without killing it. Status
            # updates are never skipped: they are state, not liveness.
            from rafiki_tpu.chaos import hook as _chaos

            if _chaos("store.heartbeat", service_id) == "skip":
                heartbeat = False
        with self._conn() as c:
            if status is not None:
                c.execute("UPDATE services SET status=? WHERE id=?", (status, service_id))
            if heartbeat:
                c.execute("UPDATE services SET heartbeat_at=? WHERE id=?", (_now(), service_id))

    def get_service(self, service_id: str) -> Optional[dict]:
        return self._one("SELECT * FROM services WHERE id=?", (service_id,))

    def get_jobs_with_dead_supervisor(self, stale_after_s: float) -> List[dict]:
        """RUNNING train jobs whose sweep supervisor is provably gone:
        at least one SUPERVISOR service row exists (the job IS a
        supervised sweep — pre-WAL jobs without one are not flagged),
        and none of them is live (non-terminal status AND a heartbeat
        newer than the staleness cutoff). The resume reaper's detection
        query (docs/recovery.md)."""
        cutoff = _now() - float(stale_after_s)
        return self._all(
            "SELECT j.* FROM train_jobs j WHERE j.status=?"
            " AND EXISTS (SELECT 1 FROM services s WHERE s.job_id=j.id"
            "   AND s.service_type=?)"
            " AND NOT EXISTS (SELECT 1 FROM services s WHERE s.job_id=j.id"
            "   AND s.service_type=? AND s.status IN (?,?)"
            "   AND s.heartbeat_at >= ?)",
            (TrainJobStatus.RUNNING.value, ServiceType.SUPERVISOR.value,
             ServiceType.SUPERVISOR.value, ServiceStatus.STARTED.value,
             ServiceStatus.RUNNING.value, cutoff))

    def get_services_of_job(self, job_id: str) -> List[dict]:
        return self._all("SELECT * FROM services WHERE job_id=?", (job_id,))

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None
