"""Content-addressed params store: chunk-level dedup for checkpoints.

BENCH_r02's ``params_dump_s=2.94`` doubles a trial's fixed cost, and a
sweep's checkpoints are the worst case: per-epoch snapshots of the
same params tree differ by one epoch of updates, and pack-mates share
most bytes early. :class:`CasParamsStore` keeps the
:class:`~rafiki_tpu.store.params.ParamsStore` contract (same ids, same
``*.params`` namespace, same ``store.params_write`` chaos site, same
integrity guarantee) but stores each blob as a MANIFEST over
fixed-size content-addressed chunks:

    <params_id>.params   cas-manifest-v1\\n{"digest": ..., "chunks": [...]}
    chunks/<sha256>      raw chunk bytes, written once, shared forever

A chunk already present is never rewritten, so the second checkpoint
of a near-identical tree streams only its deltas over the existing
``copy_to_host_async`` dump path (`measure_store_throughput.py`
gates: second write < 20% of the first's bytes). ``load`` verifies
the whole-blob sha256 exactly like the plain store — and still reads
plain-format files, so a directory can migrate in place.

Opt-in via RAFIKI_PARAMS_CAS=1 (the :func:`make_params_store` factory
in ``rafiki_tpu.store``); chunk size via RAFIKI_CAS_CHUNK_KB
(default 64).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import uuid
from pathlib import Path
from typing import Any, Dict, Optional

from rafiki_tpu import telemetry
from rafiki_tpu.chaos import hook as _chaos
from rafiki_tpu.store.params import ParamsStore

MANIFEST_MARKER = b"cas-manifest-v1"
DEFAULT_CHUNK_KB = 64


def _chunk_size() -> int:
    try:
        kb = int(os.environ.get("RAFIKI_CAS_CHUNK_KB", str(DEFAULT_CHUNK_KB)))
    except ValueError:
        kb = DEFAULT_CHUNK_KB
    return max(1, kb) * 1024


class CasParamsStore(ParamsStore):
    """Drop-in ParamsStore with content-addressed chunk storage."""

    def __init__(self, params_dir: "str | os.PathLike"):
        super().__init__(params_dir)
        self._chunks = self._dir / "chunks"
        self._chunks.mkdir(parents=True, exist_ok=True)
        self._chunk_bytes = _chunk_size()
        self._stats_lock = threading.Lock()
        self._bytes_logical = 0
        self._bytes_written = 0

    # -- write path ----------------------------------------------------------

    def save(self, blob: bytes, params_id: Optional[str] = None) -> str:
        params_id = params_id or uuid.uuid4().hex
        _chaos("store.params_write", params_id)  # delay=slow disk, error=failed write
        path = self._path(params_id)
        digest = hashlib.sha256(blob).hexdigest()
        chunk_ids = []
        written = 0
        for off in range(0, len(blob), self._chunk_bytes):
            piece = blob[off:off + self._chunk_bytes]
            cid = hashlib.sha256(piece).hexdigest()
            chunk_ids.append(cid)
            written += self._write_chunk(cid, piece)
        manifest = json.dumps({
            "size": len(blob),
            "digest": digest,
            "chunk_bytes": self._chunk_bytes,
            "chunks": chunk_ids,
        }, sort_keys=True).encode()
        tmp = path.with_suffix(".tmp")
        with open(tmp, "wb") as f:
            f.write(MANIFEST_MARKER + b"\n" + manifest)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic: readers never see a torn file
        written += len(MANIFEST_MARKER) + 1 + len(manifest)
        with self._stats_lock:
            self._bytes_logical += len(blob)
            self._bytes_written += written
        telemetry.inc("cas.bytes_logical", len(blob))
        telemetry.inc("cas.bytes_written", written)
        return params_id

    def _write_chunk(self, cid: str, piece: bytes) -> int:
        """Write a chunk once; a present chunk is the dedup hit.
        Returns bytes physically written."""
        cpath = self._chunks / cid
        if cpath.exists():
            telemetry.inc("cas.chunk_hits")
            return 0
        tmp = cpath.with_suffix(".tmp")
        with open(tmp, "wb") as f:
            f.write(piece)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, cpath)
        telemetry.inc("cas.chunk_writes")
        return len(piece)

    # -- read path -----------------------------------------------------------

    def load(self, params_id: str) -> bytes:
        with open(self._path(params_id), "rb") as f:
            head, rest = f.read().split(b"\n", 1)
        if head != MANIFEST_MARKER:
            # Plain-format file (pre-CAS, or written by the base store
            # into the same directory): head is the hex digest.
            blob = rest
            if hashlib.sha256(blob).hexdigest().encode() != head:
                raise IOError(f"Params {params_id} failed integrity check")
            return blob
        manifest = json.loads(rest.decode())
        parts = []
        for cid in manifest["chunks"]:
            cpath = self._chunks / cid
            try:
                piece = cpath.read_bytes()
            except FileNotFoundError:
                raise IOError(f"Params {params_id} missing chunk {cid}")
            if hashlib.sha256(piece).hexdigest() != cid:
                raise IOError(f"Params {params_id} chunk {cid} corrupt")
            parts.append(piece)
        blob = b"".join(parts)
        if hashlib.sha256(blob).hexdigest() != manifest["digest"]:
            raise IOError(f"Params {params_id} failed integrity check")
        return blob

    # -- accounting / maintenance --------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """Physical-vs-logical accounting since this instance opened:
        ``dedup_ratio`` is the fraction of logical bytes NOT written."""
        with self._stats_lock:
            logical, written = self._bytes_logical, self._bytes_written
        return {
            "bytes_logical": logical,
            "bytes_written": written,
            "dedup_ratio": (round(1.0 - written / logical, 6)
                            if logical else 0.0),
            "chunk_bytes": self._chunk_bytes,
            "chunks": sum(1 for _ in self._chunks.iterdir()),
        }

    def gc(self) -> int:
        """Delete chunks no surviving manifest references (deleted
        checkpoints leave shared chunks behind by design). Returns the
        number of chunks removed."""
        live = set()
        for pid in self.list():
            with open(self._path(pid), "rb") as f:
                head, rest = f.read().split(b"\n", 1)
            if head != MANIFEST_MARKER:
                continue
            live.update(json.loads(rest.decode())["chunks"])
        removed = 0
        for cpath in list(self._chunks.iterdir()):
            if cpath.suffix == ".tmp" or cpath.name not in live:
                cpath.unlink(missing_ok=True)
                removed += 1
        telemetry.inc("cas.chunks_gced", removed)
        return removed


def make_params_store(params_dir: "str | os.PathLike") -> ParamsStore:
    """Factory honouring RAFIKI_PARAMS_CAS: the CAS store when set,
    the plain one otherwise. The CAS store reads plain-format files,
    so an existing directory can turn the flag on in place (turning it
    OFF strands only manifests written while it was on)."""
    if os.environ.get("RAFIKI_PARAMS_CAS", "").lower() in (
            "1", "true", "yes", "on"):
        return CasParamsStore(params_dir)
    return ParamsStore(params_dir)
