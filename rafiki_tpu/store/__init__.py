"""Persistence layer: meta store (sqlite3) + params store (files).

Reference parity: rafiki/db/ (schema.py + database.py, unverified
paths — SURVEY.md §2): SQLAlchemy ORM over PostgreSQL with typed CRUD.
Here: first-party sqlite3 (WAL mode) — single-file, multi-process-safe
for the one-host-many-chips topology, with the same entity vocabulary
(User, Model, TrainJob, SubTrainJob, Trial, InferenceJob, Service,
TrialLog). Swappable for Postgres by reimplementing MetaStore's SQL.
"""

from rafiki_tpu.store.cas import CasParamsStore, make_params_store
from rafiki_tpu.store.meta import MetaStore
from rafiki_tpu.store.params import ParamsStore

__all__ = ["CasParamsStore", "MetaStore", "ParamsStore",
           "make_params_store"]
