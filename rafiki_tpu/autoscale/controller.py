"""SLO-burn-driven autoscale controller (docs/autoscale.md).

A tick-driven reconciler in the style of the chaos plane: injectable
clock, explicit seed, byte-deterministic decisions. Each tick reads
one sensor snapshot — SLO burn state (obs/perf/slo.py), gateway queue
depth / inflight / shed rate, and the search plane's
``effective_trials_per_hour`` gauge — and emits a
:class:`ScaleDecision` per lane:

  * ``inference`` — worker count behind the serving gateway (spawn via
    the services-manager surface, drain via the worker drain path with
    the drain→reap→freed ordering contract in :mod:`actuators`).
  * ``sweep`` — chip count of a live mesh sweep (grow/shrink through
    :class:`rafiki_tpu.scheduler.mesh.ElasticHandle`, riding the
    existing elastic re-pack machinery).

Stability machinery, all per lane:

  * **hysteresis band** — scale up at ``pressure >= up_threshold``,
    down at ``pressure <= down_threshold``, hold in between, so a
    signal hovering near one edge cannot oscillate the fleet.
  * **per-direction cooldowns** — a fresh scale-up does not block a
    scale-down (and vice versa); each direction rate-limits itself.
  * **flap damping** — direction flips inside ``flap_window_s`` grow a
    guard interval exponentially (``flap_backoff ** flips``, capped),
    so an adversarial oscillating signal converges to a bounded
    actuation count instead of thrashing (the
    ``autoscale-flap-damping`` chaos scenario proves it). Damping can
    be disabled (``damping=False`` / RAFIKI_AUTOSCALE_DAMPING=0) only
    so tests and the smoke's vacuous-pass polarity can demonstrate the
    flapping it prevents.

Every decision — including holds — journals ``autoscale/decision``
with its full sensor snapshot, so ``obs autoscale`` replays exactly
why each action fired (or didn't). An optional twin pre-gate forecasts
the actuation before real hardware moves: a veto journals but never
actuates. Knobs: the ``RAFIKI_AUTOSCALE_*`` table in
docs/autoscale.md.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from rafiki_tpu import chaos, telemetry
from rafiki_tpu.obs.journal import journal as _journal
from rafiki_tpu.obs.perf import slo as _slo

ENV_PREFIX = "RAFIKI_AUTOSCALE_"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(ENV_PREFIX + name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(ENV_PREFIX + name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def _env_bool(name: str, default: bool) -> bool:
    raw = os.environ.get(ENV_PREFIX + name)
    if raw is None:
        return default
    return raw.lower() in ("1", "true", "yes", "on")


def enabled() -> bool:
    """Whether the admin plane should run a controller at all
    (RAFIKI_AUTOSCALE=1; default off — elasticity is opt-in)."""
    return os.environ.get("RAFIKI_AUTOSCALE", "").lower() in (
        "1", "true", "yes", "on")


def prewarm_enabled() -> bool:
    """Whether job admission pre-warms compiled packs
    (RAFIKI_AUTOSCALE_PREWARM=1; default off)."""
    return _env_bool("PREWARM", False)


# -- sensors -----------------------------------------------------------------


def read_sensors(gateway: Any = None,
                 slo_engine: Optional[_slo.SloEngine] = None) -> Dict[str, Any]:
    """One JSON-able snapshot of everything the controller reads: SLO
    state from the burn engine, admission context from the gateway,
    and the search plane's throughput gauge. The snapshot is embedded
    verbatim in every ``autoscale/decision`` record."""
    eng = slo_engine if slo_engine is not None else _slo.engine
    col = eng.collector()
    burns = [st.get("burn") for st in col["state"].values()
             if st.get("breaching") and st.get("burn") is not None]
    out: Dict[str, Any] = {
        "slo_breaching": col["breaching"],
        "slo_burn": max(burns) if burns else 0.0,
        "slo": col["state"],
        "effective_trials_per_hour":
            telemetry.get_gauge("search.effective_trials_per_hour"),
    }
    if gateway is not None:
        out.update(gateway.sensors())
    return out


def inference_pressure(sensors: Dict[str, Any]) -> Tuple[Optional[float], str]:
    """Serving-lane pressure: the max of normalized burn, queue
    fraction, and (weighted) shed rate — 1.0 is 'at the line'. All
    three at zero reads as idle capacity, which is the scale-down
    signal the hysteresis band gates."""
    components = {
        "slo_burn": (float(sensors.get("slo_burn") or 0.0)
                     if sensors.get("slo_breaching") else 0.0),
        "queue_frac": float(sensors.get("queue_frac") or 0.0),
        "shed": float(sensors.get("shed_rate") or 0.0) * 10.0,
    }
    reason = max(components, key=lambda k: components[k])
    return components[reason], reason


def sweep_pressure(sensors: Dict[str, Any]) -> Tuple[Optional[float], str]:
    """Sweep-lane pressure: target / actual effective trials per hour.
    No target configured (RAFIKI_AUTOSCALE_TARGET_EPH) or no ledger
    data yet -> None, which the controller treats as hold — scaling a
    sweep on a missing signal is how fleets thrash."""
    target = _env_float("TARGET_EPH", 0.0)
    if target <= 0.0:
        return None, "no-target"
    eph = sensors.get("effective_trials_per_hour")
    if eph is None or eph <= 0.0:
        return None, "no-data"
    return target / float(eph), "eph"


# -- decisions ---------------------------------------------------------------


@dataclasses.dataclass
class LaneSpec:
    """One scaling lane's policy: bounds, hysteresis band, cooldowns,
    and the pressure function mapping a sensor snapshot to a scalar."""

    name: str
    min_size: int = 1
    max_size: int = 8
    up_threshold: float = 1.0
    down_threshold: float = 0.3
    up_cooldown_s: float = 5.0
    down_cooldown_s: float = 30.0
    step: int = 1
    pressure_fn: Callable[[Dict[str, Any]], Tuple[Optional[float], str]] = \
        inference_pressure

    @classmethod
    def from_env(cls, name: str, **overrides: Any) -> "LaneSpec":
        base = dict(
            min_size=_env_int("MIN", 1),
            max_size=_env_int("MAX", 8),
            up_threshold=_env_float("UP_THRESHOLD", 1.0),
            down_threshold=_env_float("DOWN_THRESHOLD", 0.3),
            up_cooldown_s=_env_float("UP_COOLDOWN_S", 5.0),
            down_cooldown_s=_env_float("DOWN_COOLDOWN_S", 30.0),
            step=_env_int("STEP", 1),
        )
        base.update(overrides)
        return cls(name=name, **base)


@dataclasses.dataclass
class ScaleDecision:
    """One lane's verdict for one tick — journaled whole, holds
    included, so the decision stream replays without gaps."""

    lane: str
    direction: str            # "up" | "down" | "hold"
    current: Optional[int]
    target: Optional[int]
    pressure: Optional[float]
    reason: str
    tick_ts: float = 0.0      # the controller CLOCK's now — journal ts
    # stays wall time, but flap replay (`obs autoscale --check`) reads
    # this so fake-clock runs stay byte-deterministic
    cooldown_s: float = 0.0   # effective (damped) cooldown that gated
    damp_factor: float = 1.0
    damped: bool = False      # held (or stretched) by flap damping
    vetoed: bool = False      # twin pre-gate said no
    forecast: Optional[Dict[str, Any]] = None
    actuated: bool = False
    sensors: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class AutoscaleController:
    """The closed loop. Deterministic given (clock, seed, sensors):
    construct with fake clocks and stub actuators in tests, with the
    real surfaces in the admin plane. ``tick()`` is the whole control
    law; ``start()`` wraps it in a daemon thread for live use."""

    def __init__(self,
                 lanes: Sequence[LaneSpec],
                 sensor_fn: Callable[[], Dict[str, Any]],
                 actuators: Dict[str, Any],
                 clock: Callable[[], float] = time.monotonic,
                 seed: Optional[int] = None,
                 tick_s: Optional[float] = None,
                 damping: Optional[bool] = None,
                 pregate_fn: Optional[Callable[..., Optional[Dict[str, Any]]]] = None,
                 flap_window_s: Optional[float] = None,
                 flap_flips: Optional[int] = None,
                 flap_backoff: Optional[float] = None,
                 flap_guard_s: Optional[float] = None,
                 flap_guard_cap_s: Optional[float] = None,
                 tick_global_slo: bool = True):
        self.lanes = list(lanes)
        self._sensor_fn = sensor_fn
        self._actuators = dict(actuators)
        self._clock = clock
        self.seed = _env_int("SEED", 0) if seed is None else int(seed)
        self._rng = random.Random(self.seed)
        self.tick_s = _env_float("TICK_S", 1.0) if tick_s is None else tick_s
        self.damping = (_env_bool("DAMPING", True) if damping is None
                        else bool(damping))
        self._pregate_fn = pregate_fn
        self.flap_window_s = (_env_float("FLAP_WINDOW_S", 60.0)
                              if flap_window_s is None else flap_window_s)
        self.flap_flips = (_env_int("FLAP_FLIPS", 2)
                           if flap_flips is None else flap_flips)
        self.flap_backoff = (_env_float("FLAP_BACKOFF", 2.0)
                             if flap_backoff is None else flap_backoff)
        self.flap_guard_s = (_env_float("FLAP_GUARD_S", 2.0)
                             if flap_guard_s is None else flap_guard_s)
        self.flap_guard_cap_s = (_env_float("FLAP_GUARD_CAP_S", 64.0)
                                 if flap_guard_cap_s is None
                                 else flap_guard_cap_s)
        self._tick_global_slo = tick_global_slo
        # (lane, direction) -> last actuation ts; lane -> (ts, dir) tail
        self._last_act: Dict[Tuple[str, str], float] = {}
        self._history: Dict[str, deque] = {
            lane.name: deque(maxlen=64) for lane in self.lanes}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        telemetry.register_collector("autoscale", self.collector)

    # -- introspection -------------------------------------------------------

    def collector(self) -> Dict[str, Any]:
        lanes: Dict[str, Any] = {}
        for lane in self.lanes:
            try:
                size = self._actuators[lane.name].size()
            except Exception:
                size = None
            lanes[lane.name] = {
                "size": size,
                "actuations": len(self._history[lane.name]),
                "flips": self._recent_flips(lane.name, self._clock()),
            }
        return {
            "damping": int(self.damping),
            "decisions": telemetry.get_counter("autoscale.decisions"),
            "lanes": lanes,
        }

    def actuation_count(self, lane_name: str) -> int:
        """Total actuations recorded for a lane (bounded-actuation
        assertions in the flap scenario/smoke)."""
        return len(self._history[lane_name])

    def _recent_flips(self, lane_name: str, now: float) -> int:
        """Direction flips among this lane's actuations inside the
        flap window ending at ``now``."""
        recent = [(ts, d) for ts, d in self._history[lane_name]
                  if now - ts <= self.flap_window_s]
        return sum(1 for (_, a), (_, b) in zip(recent, recent[1:]) if a != b)

    def damp_factor(self, lane_name: str, now: float) -> float:
        """Exponential flap multiplier: 1.0 below the flip threshold
        (or with damping off), else ``backoff ** excess_flips`` capped
        so the guard cannot grow unbounded."""
        if not self.damping:
            return 1.0
        flips = self._recent_flips(lane_name, now)
        if flips < self.flap_flips:
            return 1.0
        cap = max(1.0, self.flap_guard_cap_s / max(self.flap_guard_s, 1e-9))
        return min(cap, self.flap_backoff ** (flips - self.flap_flips + 1))

    # -- the control law -----------------------------------------------------

    def tick(self, now: Optional[float] = None) -> List[ScaleDecision]:
        """One reconcile pass: sense, decide per lane, actuate what
        survived the gates. Returns every decision (holds included)."""
        now = self._clock() if now is None else now
        if self._tick_global_slo:
            # SLO wiring: the control loop itself keeps burn windows
            # fresh even when no request/epoch path is ticking them.
            try:
                _slo.maybe_tick()
            except Exception:
                pass
        try:
            # Chaos site: a sensor-plane fault (error mode) must leave
            # the fleet exactly where it is — never actuate blind.
            chaos.hook("autoscale.sensor")
            sensors = self._sensor_fn()
        except Exception as e:
            telemetry.inc("autoscale.sensor_errors")
            decisions = [ScaleDecision(lane=lane.name, direction="hold",
                                       current=None, target=None,
                                       pressure=None,
                                       reason="sensor-error",
                                       tick_ts=now,
                                       sensors={"error": str(e)})
                         for lane in self.lanes]
            for d in decisions:
                self._record(d)
            return decisions
        decisions = []
        for lane in self.lanes:
            d = self._decide(lane, sensors, now)
            if d.direction != "hold" and not d.vetoed:
                self._actuate(lane, d, now)
            self._record(d)
            decisions.append(d)
        return decisions

    def _decide(self, lane: LaneSpec, sensors: Dict[str, Any],
                now: float) -> ScaleDecision:
        d = ScaleDecision(lane=lane.name, direction="hold", current=None,
                          target=None, pressure=None, reason="",
                          tick_ts=now, sensors=sensors)
        try:
            d.current = int(self._actuators[lane.name].size())
        except Exception as e:
            d.reason = "size-error"
            d.sensors = dict(sensors, size_error=str(e))
            return d
        pressure, preason = lane.pressure_fn(sensors)
        d.pressure = pressure
        if pressure is None:
            d.reason = preason
            return d
        if pressure >= lane.up_threshold:
            want = "up"
        elif pressure <= lane.down_threshold:
            want = "down"
        else:
            d.reason = "in-band"
            return d
        d.reason = preason
        if want == "up" and d.current >= lane.max_size:
            d.reason = "at-max"
            return d
        if want == "down" and d.current <= lane.min_size:
            d.reason = "at-min"
            return d
        # Per-direction cooldown: the same direction rate-limits itself.
        base = lane.up_cooldown_s if want == "up" else lane.down_cooldown_s
        factor = self.damp_factor(lane.name, now)
        d.damp_factor = factor
        d.cooldown_s = base * factor
        last_same = self._last_act.get((lane.name, want))
        if last_same is not None and now - last_same < d.cooldown_s:
            d.reason = "cooldown"
            d.damped = factor > 1.0
            return d
        # Flap guard: a direction FLIP additionally waits out a guard
        # interval from the last actuation in ANY direction; the guard
        # grows exponentially with recent flips. This is the damping
        # that makes an oscillating signal converge.
        history = self._history[lane.name]
        if history:
            last_ts, last_dir = history[-1]
            if last_dir != want:
                guard = (self.flap_guard_s * factor if self.damping else 0.0)
                if now - last_ts < guard:
                    d.reason = "flap-guard"
                    d.damped = True
                    d.cooldown_s = guard
                    return d
        step = max(1, int(lane.step))
        target = d.current + step if want == "up" else d.current - step
        target = max(lane.min_size, min(lane.max_size, target))
        d.direction = want
        d.target = target
        if self._pregate_fn is not None:
            # Twin pre-gate (Maya-style rehearsal): forecast Δp99/Δshed
            # before touching real capacity; a veto journals but never
            # actuates.
            try:
                d.forecast = self._pregate_fn(lane.name, d.current, target,
                                              sensors)
            except Exception as e:
                d.forecast = {"error": str(e)}
            if d.forecast and d.forecast.get("veto"):
                d.vetoed = True
                telemetry.inc("autoscale.vetoed")
        return d

    def _actuate(self, lane: LaneSpec, d: ScaleDecision, now: float) -> None:
        try:
            # Chaos site: an actuator fault is a failed spawn/drain —
            # the decision records the error and cooldown still arms
            # (retrying a broken actuator every tick is its own flap).
            chaos.hook("autoscale.actuate", lane.name)
            with telemetry.span("autoscale.actuate", lane=lane.name,
                                direction=d.direction):
                self._actuators[lane.name].scale_to(d.target)
            d.actuated = True
            telemetry.inc("autoscale.actuations")
        except Exception as e:
            telemetry.inc("autoscale.actuate_errors")
            d.sensors = dict(d.sensors, actuate_error=str(e))
        self._last_act[(lane.name, d.direction)] = now
        self._history[lane.name].append((now, d.direction))
        if d.damp_factor > 1.0:
            telemetry.inc("autoscale.damped_actuations")

    def _record(self, d: ScaleDecision) -> None:
        telemetry.inc("autoscale.decisions")
        if d.damped:
            telemetry.inc("autoscale.damped_holds")
        _journal.record("autoscale", "decision", **d.to_dict())

    # -- live loop -----------------------------------------------------------

    def start(self, interval_s: Optional[float] = None) -> None:
        if self._thread is not None:
            return
        interval = self.tick_s if interval_s is None else interval_s
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(interval):
                try:
                    self.tick()
                except Exception:
                    telemetry.inc("autoscale.tick_errors")

        self._thread = threading.Thread(target=loop, name="autoscale",
                                        daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
