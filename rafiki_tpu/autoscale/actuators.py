"""The scale actuator surface (docs/autoscale.md).

RF012 guards this module: only code inside ``rafiki_tpu.autoscale``
may call into it. Every other path to capacity change goes through
:class:`~rafiki_tpu.autoscale.controller.AutoscaleController`, so
ad-hoc code cannot bypass hysteresis, cooldowns, or flap damping —
an undamped actuator is a flap amplifier.

Two lanes:

  * :class:`InferenceWorkerLane` — worker count behind the serving
    gateway. Scale-down honours the drain→reap→freed ordering
    contract: a drained worker's slot is NOT counted free until (1)
    its inflight replies flushed (the worker's ``drained`` event), and
    (2) its liveness lease has left the bus (graceful
    ``remove_worker``, or the janitor reap for a worker that died
    mid-drain). Without the contract, the controller re-scales against
    phantom capacity and the gateway fans out to a corpse.
  * :class:`SweepChipLane` — chip count of a live mesh sweep, through
    :class:`rafiki_tpu.scheduler.mesh.ElasticHandle` (the supervisor
    applies deltas with the existing elastic re-pack machinery).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional, Tuple

from rafiki_tpu import telemetry
from rafiki_tpu.obs.journal import journal as _journal

# (worker_id, worker, thread) — what spawn_fn returns per replica.
SpawnResult = Tuple[str, Any, Optional[threading.Thread]]


class InferenceWorkerLane:
    """Inference-lane actuator over a bus + a spawn callable.

    ``spawn_fn(index) -> (worker_id, worker, thread)`` must start the
    replica (thread running ``worker.run()``); the lane waits for its
    bus registration before counting it. ``initial`` seeds the lane
    with replicas spawned before the controller attached (the
    services-manager path).
    """

    def __init__(self, bus: Any, job_id: str,
                 spawn_fn: Callable[[int], SpawnResult],
                 initial: Optional[List[SpawnResult]] = None,
                 register_timeout_s: float = 5.0,
                 drain_timeout_s: float = 10.0,
                 poll_s: float = 0.02):
        self.bus = bus
        self.job_id = job_id
        self._spawn_fn = spawn_fn
        self._entries: List[SpawnResult] = list(initial or [])
        self._spawned = len(self._entries)
        self._register_timeout_s = register_timeout_s
        self._drain_timeout_s = drain_timeout_s
        self._poll_s = poll_s
        self._lock = threading.RLock()
        # Ordering audit for the drain→reap→freed regression test:
        # ("drained"|"reaped"|"freed", worker_id) in observed order.
        self.events: List[Tuple[str, str]] = []

    def size(self) -> int:
        with self._lock:
            return len(self._entries)

    def worker_ids(self) -> List[str]:
        with self._lock:
            return [wid for wid, _, _ in self._entries]

    def scale_to(self, n: int) -> None:
        with self._lock:
            while len(self._entries) < n:
                self._spawn_one()
            while len(self._entries) > n:
                self._drain_one()

    def _spawn_one(self) -> None:
        # Re-entered under scale_to's RLock; holding it again keeps the
        # mutation-under-lock contract visible in each step.
        with self._lock:
            index = self._spawned
            self._spawned += 1
            wid, worker, thread = self._spawn_fn(index)
            deadline = time.monotonic() + self._register_timeout_s
            while wid not in self.bus.get_workers(self.job_id):
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"worker {wid} never registered on the bus")
                time.sleep(self._poll_s)
            self._entries.append((wid, worker, thread))
            telemetry.inc("autoscale.workers_spawned")
            _journal.record("autoscale", "spawn", job_id=self.job_id,
                            worker_id=wid, size=len(self._entries))

    def _drain_one(self) -> None:
        with self._lock:
            # Victim = newest replica: the oldest carry the warmed
            # compiles.
            wid, worker, thread = self._entries[-1]
            worker.stop()
            # (1) inflight replies flush: the worker sets ``drained``
            # only after its serve loop exited and it left the bus —
            # every already-popped query has had its prediction
            # published.
            drained = getattr(worker, "drained", None)
            if drained is not None:
                drained.wait(self._drain_timeout_s)
            self.events.append(("drained", wid))
            # (2) lease gone: graceful exit removes it synchronously; a
            # worker that died mid-drain ages out via the janitor reap
            # (get_workers reaps corpses on sight). Only then is the
            # slot free — re-scaling before this double-counts capacity.
            deadline = time.monotonic() + self._drain_timeout_s
            while wid in self.bus.get_workers(self.job_id):
                if time.monotonic() >= deadline:
                    telemetry.inc("autoscale.drain_timeouts")
                    break
                time.sleep(self._poll_s)
            self.events.append(("reaped", wid))
            if thread is not None:
                thread.join(self._drain_timeout_s)
            self._entries.pop()
            self.events.append(("freed", wid))
            telemetry.inc("autoscale.workers_drained")
            _journal.record("autoscale", "drain", job_id=self.job_id,
                            worker_id=wid, size=len(self._entries))


class SweepChipLane:
    """Sweep-lane actuator over a mesh ElasticHandle. The handle is
    asynchronous — the supervisor applies deltas at its next poll — so
    ``size()`` reports desired capacity (live + pending delta) to keep
    the controller's view consistent between polls."""

    def __init__(self, handle: Any):
        self._handle = handle

    def size(self) -> int:
        return int(self._handle.desired())

    def scale_to(self, n: int) -> None:
        delta = int(n) - self.size()
        if delta:
            self._handle.request(delta)
