"""Compiled-pack pre-warming at job admission (docs/autoscale.md).

BENCH_r02 puts the scale-up fixed cost in one number: ``compile_s=12.8``
against ``canonical_trial_s=2.94`` — a cold scale-up spends 4× a
trial's work on XLA before doing anything. This module moves that cost
to ADMISSION time: group a job's proposals by ``packing_key``, build
each bucket's :class:`~rafiki_tpu.ops.train.PackedTrainLoop` once
(which fetches-or-builds the Program via the process-wide cache and
jits the init executable), and let
:func:`~rafiki_tpu.utils.backend.enable_compilation_cache` persist the
XLA artifacts — so a later scale-up (a new chip joining the sweep, a
replacement worker process) lands on a warm compile in BOTH caches:
in-process (``get_program``) and cross-process (the persistent XLA
dir).

The probe trial per bucket is derived deterministically from the knob
config (fixed → value, ranges → midpoint, categorical → first), NOT
from an advisor — admission must not burn advisor state or journal
phantom proposals. Shape-affecting knobs sampled by the real sweep can
still produce unseen keys; pre-warming is best-effort and every
outcome journals ``autoscale/prewarm``.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence

from rafiki_tpu import telemetry
from rafiki_tpu.model.knobs import (CategoricalKnob, FixedKnob, FloatKnob,
                                    IntegerKnob)
from rafiki_tpu.obs.journal import journal as _journal
from rafiki_tpu.utils.backend import enable_compilation_cache


def probe_knobs(knob_config: Dict[str, Any]) -> Dict[str, Any]:
    """A deterministic representative sample of a knob config: the
    middle of every range, the first categorical value. Advisor-free
    so admission never touches sweep state."""
    out: Dict[str, Any] = {}
    for name, knob in knob_config.items():
        if isinstance(knob, FixedKnob):
            out[name] = knob.value
        elif isinstance(knob, CategoricalKnob):
            out[name] = knob.values[0]
        elif isinstance(knob, IntegerKnob):
            out[name] = int((knob.value_min + knob.value_max) // 2)
        elif isinstance(knob, FloatKnob):
            if getattr(knob, "is_exp", False) and knob.value_min > 0:
                out[name] = float(math.exp(
                    (math.log(knob.value_min) + math.log(knob.value_max))
                    / 2.0))
            else:
                out[name] = (knob.value_min + knob.value_max) / 2.0
        # unknown knob kinds are skipped; the model ctor defaults apply
    return out


def prewarm_models(model_cls: type, knobs_list: Sequence[Dict[str, Any]],
                   dataset_uri: str, k: int = 2,
                   persist: bool = True) -> Dict[str, Any]:
    """Build the packed program for every distinct ``packing_key`` in
    ``knobs_list`` at width ``k``. Returns per-key stats; never raises
    (a template whose probe fails to trace just reports an error —
    pre-warming must not fail admission)."""
    if persist:
        # Cross-process half: compiled executables land in the
        # persistent XLA dir so a fresh worker process skips the
        # compile too (RAFIKI_XLA_CACHE_DIR).
        enable_compilation_cache()
    from rafiki_tpu.ops.train import PackedTrainLoop

    buckets: Dict[str, List[Any]] = {}
    errors: List[str] = []
    for kn in knobs_list:
        try:
            m = model_cls(**kn)
            key = repr(m.packing_key(m._prepared_dataset(dataset_uri)))
        except Exception as e:
            errors.append(str(e))
            continue
        buckets.setdefault(key, []).append(m)
    warmed = 0
    hits = 0
    for key, models in buckets.items():
        width = min(max(1, int(k)), len(models)) if models else 1
        pack = models[:width]
        misses0 = telemetry.get_counter("program_cache.misses")
        try:
            lead = pack[0]
            ds = lead._prepared_dataset(dataset_uri)
            num_classes, input_shape = lead._dataset_arch(ds)
            fns = lead._loop_fns(num_classes, input_shape)
            hypers = []
            for m in pack:
                m._planned_steps = m.epochs * max(1, ds.size // m.batch_size)
                hypers.append(m._loop_fns(num_classes, input_shape)["hyper"])
            with telemetry.span("autoscale.prewarm", key=key):
                # Constructing the loop fetches-or-builds the Program
                # at this width AND jits the init executable — the two
                # compiles a scale-up would otherwise pay cold.
                PackedTrainLoop(fns["init_fn"], fns["apply_eval"],
                                fns["loss_fn"], fns["optimizer"],
                                seeds=[m._seed for m in pack],
                                hypers=hypers,
                                program_key=fns["program_key"])
            hit = telemetry.get_counter("program_cache.misses") == misses0
            warmed += 1
            hits += int(hit)
            _journal.record("autoscale", "prewarm", key=key, k=width,
                            hit=hit)
        except Exception as e:
            errors.append(f"{key}: {e}")
            _journal.record("autoscale", "prewarm", key=key, k=width,
                            error=str(e))
    telemetry.inc("autoscale.prewarmed_packs", warmed)
    return {"keys": len(buckets), "warmed": warmed, "cache_hits": hits,
            "errors": errors}


def prewarm_train_job(store: Any, job_id: str, k: int = 2) -> Dict[str, Any]:
    """Admission-time entry: pre-warm one probe pack per model attached
    to ``job_id`` (deterministic knob probe, no advisor). Called from
    the services manager when RAFIKI_AUTOSCALE_PREWARM is on."""
    from rafiki_tpu.model.base import load_model_class

    job = store.get_train_job(job_id)
    if job is None:
        return {"keys": 0, "warmed": 0, "cache_hits": 0,
                "errors": [f"no train job {job_id!r}"]}
    totals: Dict[str, Any] = {"keys": 0, "warmed": 0, "cache_hits": 0,
                              "errors": []}
    for sub in store.get_sub_train_jobs(job_id):
        model_row = store.get_model(sub["model_id"])
        try:
            cls = load_model_class(model_row["model_file"],
                                   model_row["model_class"])
            if not cls.packable():
                continue
            probe = probe_knobs(cls.get_knob_config())
            res = prewarm_models(cls, [probe] * max(1, int(k)),
                                 job["train_dataset_uri"], k=k)
        except Exception as e:
            totals["errors"].append(f"{model_row.get('name')}: {e}")
            continue
        for key in ("keys", "warmed", "cache_hits"):
            totals[key] += res[key]
        totals["errors"].extend(res["errors"])
    return totals
