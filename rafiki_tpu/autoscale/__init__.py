"""Closed-loop elasticity (docs/autoscale.md).

The repo grew every sensor (SLO burn rates, per-second serving rollup,
effective-trials-per-hour ledger) and every actuator (worker
spawn/drain, elastic mesh re-packing) before it grew the controller
connecting them. This package is that controller:

  * :mod:`controller` — the tick-driven reconciler: reads sensors,
    applies hysteresis / per-direction cooldowns / flap damping, and
    emits journaled scale decisions for the inference and sweep lanes.
  * :mod:`actuators` — the actuation surface the controller drives
    (RF012 keeps ad-hoc callers out so damping can't be bypassed).
  * :mod:`prewarm` — compiled-pack pre-warming at job admission so a
    scale-up lands on a warm compile instead of paying the cold one.
"""

from rafiki_tpu.autoscale.controller import (AutoscaleController, LaneSpec,
                                             ScaleDecision, inference_pressure,
                                             read_sensors, sweep_pressure)

__all__ = [
    "AutoscaleController",
    "LaneSpec",
    "ScaleDecision",
    "inference_pressure",
    "read_sensors",
    "sweep_pressure",
]
