"""Advisor rehydration: rebuild an equivalent posterior in a fresh
process (docs/recovery.md).

A sweep's GP/TPE state lives only in the supervisor's memory — a crash
loses every observation unless it can be replayed. Two sources
reconstruct it, in a canonical order so the result is deterministic no
matter what the dead process was mid-way through:

1. **Completed trial rows** (MetaStore) — the authoritative
   (knobs, score) pairs, replayed sorted by trial ``no``.
2. **`kind="advisor"` audit journals** (PR 12) — scores the store
   never saw as completed rows (doomed-trial consolation feedback):
   each ``advisor/feedback`` record is joined to its
   ``advisor/propose`` record by ``knobs_hash`` to recover the full
   knob dict, and replayed (sorted by hash) after the store rows.

Replay goes through the engine's normal ``feedback()`` path, so the
rehydrated advisor re-journals its decisions like any live one and its
internal rng advances exactly as a fresh advisor fed the same
observations would — which, with the GP's canonical-order fit, makes
the first post-resume ``propose_batch`` byte-identical between a
crashed-and-resumed sweep and an unfaulted one (the equivalence
contract tests/test_recovery.py pins).

Speculative scores in flight at the crash (``advisor/speculate``
records with no later ``advisor/feedback`` for the hash — the
correction that would have superseded them never landed) are replayed
AFTER all real observations, sorted by hash, through the normal
``speculate()`` path. The engine's speculate op has the same
append+fit shape as feedback, so the rehydrated advisor's training
set and rng position equal a fresh advisor fed the same (real, then
speculative) sequences — byte-identical proposals even mid-speculation
(docs/early_kill.md's rehydration contract).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from rafiki_tpu.advisor.service import AdvisorService
from rafiki_tpu.obs.journal import journal as _journal
from rafiki_tpu.obs.search.audit import knobs_hash


def journal_observations(records: Sequence[Dict[str, Any]],
                         advisor_id: Optional[str] = None,
                         exclude_hashes: Optional[set] = None,
                         ) -> List[Tuple[Dict[str, Any], float]]:
    """(knobs, score) pairs recoverable from ``kind="advisor"`` journal
    records alone: feedback joined to its propose by ``knobs_hash``.
    Deduplicated per hash (last score wins), excluding ``exclude_hashes``
    (observations the store already supplies), sorted by hash for a
    replay order independent of journal file interleaving."""
    knobs_by_hash: Dict[str, Dict[str, Any]] = {}
    score_by_hash: Dict[str, float] = {}
    for r in records:
        if r.get("kind") != "advisor":
            continue
        if advisor_id is not None and r.get("advisor_id") != advisor_id:
            continue
        if r.get("name") == "propose" and isinstance(r.get("knobs"), dict):
            knobs_by_hash[r.get("knobs_hash")] = r["knobs"]
        elif r.get("name") == "feedback" and r.get("knobs_hash"):
            try:
                score_by_hash[r["knobs_hash"]] = float(r.get("score"))
            except (TypeError, ValueError):
                continue
    out: List[Tuple[Dict[str, Any], float]] = []
    for h in sorted(score_by_hash):
        if exclude_hashes and h in exclude_hashes:
            continue
        if h in knobs_by_hash:
            out.append((knobs_by_hash[h], score_by_hash[h]))
    return out


def journal_speculations(records: Sequence[Dict[str, Any]],
                         advisor_id: Optional[str] = None,
                         exclude_hashes: Optional[set] = None,
                         ) -> List[Tuple[Dict[str, Any], float, Optional[dict]]]:
    """(knobs, predicted, fit) for every speculation still UNCORRECTED
    in the journals: an ``advisor/speculate`` record whose hash has no
    ``advisor/feedback`` record anywhere in the stream (a correction
    or true score supersedes the speculation). Last prediction wins
    per hash; sorted by hash like :func:`journal_observations` so the
    replay order is independent of journal interleaving."""
    spec_by_hash: Dict[str, Tuple[Dict[str, Any], float, Optional[dict]]] = {}
    fed_hashes: set = set()
    for r in records:
        if r.get("kind") != "advisor":
            continue
        if advisor_id is not None and r.get("advisor_id") != advisor_id:
            continue
        if r.get("name") == "feedback" and r.get("knobs_hash"):
            fed_hashes.add(r["knobs_hash"])
        elif r.get("name") == "speculate" \
                and isinstance(r.get("knobs"), dict):
            try:
                pred = float(r.get("predicted"))
            except (TypeError, ValueError):
                continue
            spec_by_hash[r.get("knobs_hash")] = (
                r["knobs"], pred,
                r.get("fit") if isinstance(r.get("fit"), dict) else None)
    out = []
    for h in sorted(spec_by_hash):
        if h in fed_hashes:
            continue
        if exclude_hashes and h in exclude_hashes:
            continue
        out.append(spec_by_hash[h])
    return out


def rehydrate_advisor(advisors: AdvisorService,
                      knob_config,
                      kind: str,
                      advisor_id: str,
                      completed: Sequence[Dict[str, Any]],
                      journal_records: Sequence[Dict[str, Any]] = (),
                      seed: int = 0,
                      engine_kwargs: Optional[dict] = None,
                      job_id: Optional[str] = None) -> str:
    """Build a fresh advisor under the dead sweep's ``advisor_id`` and
    replay its observations into it. ``completed`` are MetaStore trial
    rows (replayed sorted by ``no``); ``journal_records`` supplement
    scores that never became completed rows. Returns the advisor id
    (identical to the input — the identity is adopted so post-resume
    audit records join the same sweep in ``obs sweep``)."""
    aid = advisors.create_advisor(knob_config, kind=kind, seed=seed,
                                  advisor_id=advisor_id,
                                  engine_kwargs=engine_kwargs)
    try:
        advisors.get(aid).job_id = job_id
    except KeyError:
        pass
    obs: List[Tuple[Dict[str, Any], float]] = []
    seen = set()
    for t in sorted(completed, key=lambda t: (t.get("no") or 0, t["id"])):
        if t.get("score") is None or not isinstance(t.get("knobs"), dict):
            continue
        obs.append((t["knobs"], float(t["score"])))
        seen.add(knobs_hash(t["knobs"]))
    obs.extend(journal_observations(journal_records, advisor_id=advisor_id,
                                    exclude_hashes=seen))
    for kn, score in obs:
        advisors.feedback(aid, score, kn)
    # Real observations first, THEN speculations still in flight at the
    # crash — same op order a fresh advisor would see, which is what
    # keeps post-resume proposals byte-identical (module docstring).
    scored = seen | {knobs_hash(kn) for kn, _ in obs}
    specs = journal_speculations(journal_records, advisor_id=advisor_id,
                                 exclude_hashes=scored)
    for kn, pred, fit in specs:
        advisors.speculate(aid, pred, kn, fit=fit)
    _journal.record("recovery", "rehydrated", advisor_id=aid,
                    job_id=job_id, engine=kind, n_observations=len(obs),
                    n_from_store=len(seen), n_from_journal=len(obs) - len(seen),
                    n_speculations=len(specs))
    return aid
