"""Advisor ABC + knob-space vectorisation shared by engines.

The vectorisation (knobs dict ↔ R^d point) lives here so every engine
(GP, random, future TPE/ENAS) shares one encoding:
  * FloatKnob(is_exp)   → log-space float dim
  * IntegerKnob         → float dim, rounded on decode (log if is_exp)
  * CategoricalKnob     → one float dim in [0, k), floor on decode
    (GP kernels handle this adequately for the small spaces Rafiki
    templates declare; matches skopt's Categorical treatment in spirit)
  * FixedKnob           → excluded from the search space
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from rafiki_tpu.model.knobs import (
    BaseKnob,
    CategoricalKnob,
    FixedKnob,
    FloatKnob,
    IntegerKnob,
    KnobConfig,
    Knobs,
)
from rafiki_tpu.obs.search import audit


class KnobSpace:
    """Bidirectional mapping between knob dicts and unit-ish R^d vectors."""

    def __init__(self, knob_config: KnobConfig):
        self.knob_config = dict(knob_config)
        self.dims: List[Tuple[str, BaseKnob]] = [
            (name, k) for name, k in sorted(knob_config.items())
            if not isinstance(k, FixedKnob)
        ]
        self.fixed: Knobs = {
            name: k.value for name, k in knob_config.items() if isinstance(k, FixedKnob)
        }

    @property
    def d(self) -> int:
        return len(self.dims)

    def bounds(self) -> np.ndarray:
        """(d, 2) array of [lo, hi] in encoded space."""
        out = []
        for _, k in self.dims:
            if isinstance(k, FloatKnob):
                lo, hi = ((math.log(k.value_min), math.log(k.value_max))
                          if k.is_exp else (k.value_min, k.value_max))
            elif isinstance(k, IntegerKnob):
                lo, hi = ((math.log(k.value_min), math.log(k.value_max))
                          if k.is_exp else (k.value_min, k.value_max))
            elif isinstance(k, CategoricalKnob):
                lo, hi = 0.0, float(len(k.values)) - 1e-9
            else:
                raise TypeError(f"Unsupported knob type {type(k).__name__}")
            out.append((lo, hi))
        return np.asarray(out, dtype=np.float64) if out else np.zeros((0, 2))

    def encode(self, knobs: Knobs) -> np.ndarray:
        v = np.zeros(self.d)
        for i, (name, k) in enumerate(self.dims):
            val = knobs[name]
            if isinstance(k, FloatKnob):
                v[i] = math.log(val) if k.is_exp else float(val)
            elif isinstance(k, IntegerKnob):
                v[i] = math.log(val) if k.is_exp else float(val)
            elif isinstance(k, CategoricalKnob):
                v[i] = float(k.values.index(val))
        return v

    def decode(self, v: np.ndarray) -> Knobs:
        knobs = dict(self.fixed)
        b = self.bounds()
        for i, (name, k) in enumerate(self.dims):
            x = float(np.clip(v[i], b[i, 0], b[i, 1]))
            if isinstance(k, FloatKnob):
                val = float(math.exp(x)) if k.is_exp else float(x)
                # exp(log(max)) can overshoot max by 1 ulp → clamp
                knobs[name] = min(max(val, k.value_min), k.value_max)
            elif isinstance(k, IntegerKnob):
                val = int(round(math.exp(x))) if k.is_exp else int(round(x))
                knobs[name] = int(np.clip(val, k.value_min, k.value_max))
            elif isinstance(k, CategoricalKnob):
                knobs[name] = k.values[int(x)]
        return knobs

    def sample(self, rng: np.random.Generator) -> Knobs:
        knobs = dict(self.fixed)
        for name, k in self.dims:
            knobs[name] = k.sample(rng)
        return knobs


class BaseAdvisor:
    """Ask/tell interface (reference: Advisor.propose()/feedback()).

    Thread-safe: the scheduler shares one advisor across all train
    workers; ask/tell are serialized behind a lock (cheap on CPU —
    SURVEY.md §7 "advisor fidelity").
    """

    #: constant-liar list cap: a worker that dies before feedback()
    #: must not suppress a region forever (oldest liars expire first).
    PENDING_CAP = 16

    #: short engine tag stamped onto every advisor/* journal record
    #: (docs/search_anatomy.md); subclasses override.
    engine = "base"

    def __init__(self, knob_config: KnobConfig, seed: int = 0):
        self.space = KnobSpace(knob_config)
        self.knob_config = dict(knob_config)
        self.seed = int(seed)
        # Stamped by AdvisorService / the mesh scheduler so journal
        # records are filterable per sweep; None for bare advisors.
        self.advisor_id: Optional[str] = None
        self.job_id: Optional[str] = None
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()
        self.history: List[Tuple[Knobs, float]] = []
        # Proposed-but-unscored points (constant liars). Engines add
        # via _pending_add and read via _pending / _pending_dists; the
        # drain on feedback happens here so no engine can forget it.
        self._pending: List[np.ndarray] = []
        # Speculative scores in flight: knobs_hash -> predicted score
        # (advisor/speculative.py). Tracked here so feedback() can
        # route the true score into a correction instead of a fresh
        # observation; never touches `history` — best() only ever
        # reports real scores.
        self._speculative: Dict[str, float] = {}

    def propose(self) -> Knobs:
        with self._lock:
            return self._propose()

    def propose_batch(self, n: int) -> List[Knobs]:
        """q proposals for one trial pack (worker/train.py
        PackedTrialRunner). Default: n sequential ``_propose`` calls
        under one lock hold — the constant-liar pending list already
        steers each call away from its predecessors. Engines with a
        cheaper/better q-batch strategy override ``_propose_batch``."""
        with self._lock:
            return self._propose_batch(max(1, int(n)))

    def feedback(self, score: float, knobs: Knobs) -> None:
        with self._lock:
            predicted = self._speculative.pop(audit.knobs_hash(knobs),
                                              None)
            self.history.append((dict(knobs), float(score)))
            if self._pending and self.space.d:
                x = self.space.encode(knobs)
                self._pending = [p for p in self._pending
                                 if not np.allclose(p, x, atol=1e-9)]
            if predicted is not None:
                self._correct(float(score), dict(knobs), predicted)
            else:
                self._feedback(float(score), dict(knobs))

    def speculate(self, score: float, knobs: Knobs,
                  fit: Optional[Dict] = None) -> None:
        """Tell with a *predicted* score for a still-running trial
        (advisor/speculative.py). The prediction enters the engine's
        training set (``_speculate``) but NOT ``history``; when the
        true score lands, ``feedback`` routes it into ``_correct`` and
        the engine refits. Idempotent per knob assignment while the
        speculation is outstanding."""
        with self._lock:
            h = audit.knobs_hash(knobs)
            if h in self._speculative:
                return
            self._speculative[h] = float(score)
            # A speculation supersedes the constant-liar damping for
            # this point — the engine now has a real-ish value there.
            if self._pending and self.space.d:
                x = self.space.encode(knobs)
                self._pending = [p for p in self._pending
                                 if not np.allclose(p, x, atol=1e-9)]
            self._speculate(float(score), dict(knobs))
            audit.record_speculate(self, float(score), knobs, fit=fit)

    # -- constant-liar helpers (called under the lock) ----------------------

    def _pending_add(self, x: np.ndarray) -> None:
        """Record a proposal awaiting its score; capped on EVERY append
        (an uncapped path would grow forever under lost feedbacks)."""
        # lint: disable=RF004 — locked-caller contract: only reached from propose() which holds self._lock
        self._pending.append(x)
        while len(self._pending) > self.PENDING_CAP:
            # lint: disable=RF004 — same locked-caller contract as the append above
            self._pending.pop(0)

    def _pending_dists(self, cand: np.ndarray, span: np.ndarray):
        """Span-normalized distance array (n_cand,) per pending point —
        engines turn these into their own damping."""
        for p in self._pending:
            yield np.linalg.norm((cand - p) / span, axis=1)

    def best(self) -> Optional[Tuple[Knobs, float]]:
        with self._lock:
            if not self.history:
                return None
            return max(self.history, key=lambda t: t[1])

    # engine hooks (called under the lock). Every implementation must
    # journal its decision through rafiki_tpu.obs.search.audit — the
    # RF011 checker errors on a hook body that returns without it.
    def _propose(self) -> Knobs:
        raise NotImplementedError

    def _propose_batch(self, n: int) -> List[Knobs]:
        out = [self._propose() for _ in range(n)]
        audit.record_propose_batch(self, n, out, strategy="sequential")
        return out

    def _feedback(self, score: float, knobs: Knobs) -> None:
        audit.record_feedback(self, score, knobs)

    def _speculate(self, score: float, knobs: Knobs) -> None:
        """Engine hook: absorb a predicted score into the training set.
        Default no-op — engines without a surrogate (random) have
        nothing to speculate into; the base still journals the
        speculation so rehydration sees a uniform record stream."""

    def _correct(self, score: float, knobs: Knobs,
                 predicted: float) -> None:
        """Engine hook: the true score for a previously speculated
        assignment. Default: journal the correction, then treat it as
        a fresh observation (matches the no-op ``_speculate``)."""
        audit.record_correct(self, knobs, predicted, score)
        self._feedback(score, knobs)


def make_advisor(knob_config: KnobConfig, kind: str = "gp", seed: int = 0,
                 **engine_kwargs) -> BaseAdvisor:
    """Factory: 'gp' (default, reference's BTB-GP/skopt analog), 'tpe'
    (Parzen-estimator engine — cheap past hundreds of observations),
    or 'random'. ``engine_kwargs`` pass through to the chosen engine's
    constructor (e.g. ``n_initial`` for GP) — the caller owns matching
    them to the kind; ``resume_sweep`` replays them from the sweep WAL
    so a rehydrated advisor is built exactly like the original."""
    from rafiki_tpu.advisor.gp import GpAdvisor
    from rafiki_tpu.advisor.random_advisor import RandomAdvisor
    from rafiki_tpu.advisor.tpe import TpeAdvisor

    kinds = {"gp": GpAdvisor, "bayesian": GpAdvisor, "btb_gp": GpAdvisor,
             "skopt": GpAdvisor, "random": RandomAdvisor,
             "tpe": TpeAdvisor, "hyperopt": TpeAdvisor}
    if kind not in kinds:
        raise ValueError(f"Unknown advisor kind {kind!r}; choose from {sorted(kinds)}")
    return kinds[kind](knob_config, seed=seed, **engine_kwargs)
