"""Advisor service: a registry of per-(sub)job advisors shared by workers.

Reference parity: rafiki/advisor/app.py (unverified) — a small service
exposing create/propose/feedback/delete so train workers in other
processes can share one optimisation state. In-proc workers call this
object directly; process-per-chip workers reach it over the control
plane's HTTP (admin app mounts these verbs) or a multiprocessing proxy.
"""

from __future__ import annotations

import threading
import uuid
from typing import Dict, Optional, Tuple

from rafiki_tpu.advisor.base import BaseAdvisor, make_advisor
from rafiki_tpu.model.knobs import KnobConfig, Knobs, deserialize_knob_config


class AdvisorService:
    def __init__(self):
        self._advisors: Dict[str, BaseAdvisor] = {}
        self._lock = threading.Lock()

    def create_advisor(self, knob_config: KnobConfig | str, kind: str = "gp",
                       seed: int = 0, advisor_id: Optional[str] = None,
                       engine_kwargs: Optional[dict] = None) -> str:
        if isinstance(knob_config, str):
            knob_config = deserialize_knob_config(knob_config)
        aid = advisor_id or uuid.uuid4().hex
        with self._lock:
            if aid not in self._advisors:
                adv = make_advisor(knob_config, kind=kind, seed=seed,
                                   **(engine_kwargs or {}))
                # Stamp the registry id so every advisor/* journal
                # record this engine emits is filterable per sweep
                # (obs sweep <job> — docs/search_anatomy.md).
                adv.advisor_id = aid
                self._advisors[aid] = adv
        return aid

    def get(self, advisor_id: str) -> BaseAdvisor:
        with self._lock:
            adv = self._advisors.get(advisor_id)
        if adv is None:
            raise KeyError(f"No advisor {advisor_id!r}")
        return adv

    def propose(self, advisor_id: str) -> Knobs:
        return self.get(advisor_id).propose()

    def propose_batch(self, advisor_id: str, n: int) -> list:
        return self.get(advisor_id).propose_batch(n)

    def feedback(self, advisor_id: str, score: float, knobs: Knobs) -> None:
        self.get(advisor_id).feedback(score, knobs)

    def speculate(self, advisor_id: str, score: float, knobs: Knobs,
                  fit: Optional[dict] = None) -> None:
        """Tell with a predicted score for a still-running trial
        (advisor/speculative.py); the true score lands later through
        ``feedback`` and becomes a correction."""
        self.get(advisor_id).speculate(score, knobs, fit=fit)

    def best(self, advisor_id: str) -> Optional[Tuple[Knobs, float]]:
        return self.get(advisor_id).best()

    def delete_advisor(self, advisor_id: str) -> None:
        with self._lock:
            self._advisors.pop(advisor_id, None)
