"""Speculative scoring coordinator: the three curve-fit consumers.

One :class:`CurveCoordinator` per sweep (the mesh scheduler shares one
across its chip workers; a standalone ``TrainWorker`` builds its own)
collects live (epoch, score) points per in-flight knob assignment and
answers three questions for the hot loop:

* **kill?** (:meth:`kill_verdict`) — at an epoch boundary, should this
  trial die because its credible band's *upper* edge sits below
  best-so-far minus the margin? Gated by ``RAFIKI_CURVE_KILL``.
* **speculate?** (:meth:`speculate_inflight`) — before the advisor
  drafts new proposals (backfill, next round), feed it predicted
  scores for stragglers still mid-flight so ``propose_batch`` never
  idles a chip waiting on them. Gated by ``RAFIKI_CURVE_SPECULATE``.
  The true score lands later through the normal ``feedback`` path,
  which the advisor base routes into a correction (engine refits).
* **done** (:meth:`note_scored` / :meth:`note_done`) — bookkeeping
  that keeps best-so-far honest and stops a finished trial from being
  speculated or killed retroactively.

Everything is journaled through rafiki_tpu.obs.search.audit
(``advisor/predict``, ``advisor/kill``, ``advisor/speculate``) — the
load-bearing constraint is that PR 15 crash-resume can rebuild the
advisor's effective training set (real observations + uncorrected
speculations) from journals alone and re-propose byte-identically;
docs/early_kill.md spells out the contract.

With both knobs off :func:`CurveCoordinator.from_env` returns ``None``
and every call site short-circuits on ``is None`` — today's loops run
bit-exactly.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from rafiki_tpu.advisor.curve import CurveFit, KillConfig, fit_curve
from rafiki_tpu.obs.search import audit as search_audit

#: Fallback extrapolation horizon when the knob assignment carries no
#: integer ``epochs`` knob — long enough that a slow riser is judged
#: near its asymptote, not its prefix.
DEFAULT_HORIZON = 16


class CurveCoordinator:
    """Thread-safe per-sweep curve tracker + kill/speculate decider."""

    def __init__(self, config: Optional[KillConfig] = None):
        self.config = config or KillConfig.from_env()
        self._lock = threading.RLock()
        self._points: Dict[str, List[Tuple[int, float]]] = {}
        self._knobs: Dict[str, Dict[str, Any]] = {}
        self._horizon: Dict[str, int] = {}
        self._trial: Dict[str, Optional[str]] = {}
        self._killed: set = set()
        self._done: set = set()
        self._speculated: Dict[str, float] = {}
        self._best: Optional[float] = None

    @classmethod
    def from_env(cls) -> Optional["CurveCoordinator"]:
        """None unless at least one consumer is switched on — call
        sites guard on ``is None`` so the off path adds zero work."""
        cfg = KillConfig.from_env()
        if not (cfg.enabled or cfg.speculate):
            return None
        return cls(cfg)

    # -- feeding -------------------------------------------------------------

    def observe(self, knobs: Dict[str, Any], epoch: int, score: float,
                trial_id: Optional[str] = None,
                horizon: Optional[int] = None) -> None:
        """One live curve point from an epoch boundary."""
        h = search_audit.knobs_hash(knobs)
        with self._lock:
            if h in self._done or h in self._killed:
                return
            self._points.setdefault(h, []).append((int(epoch),
                                                   float(score)))
            self._knobs.setdefault(h, dict(knobs))
            if trial_id is not None:
                self._trial[h] = trial_id
            if horizon is None:
                ek = knobs.get("epochs")
                horizon = int(ek) if isinstance(ek, (int, float)) \
                    else DEFAULT_HORIZON
            self._horizon[h] = max(int(horizon), int(epoch) + 1)

    def note_scored(self, knobs: Dict[str, Any], score: float) -> None:
        """True final score landed: retire the curve, advance
        best-so-far."""
        h = search_audit.knobs_hash(knobs)
        with self._lock:
            self._done.add(h)
            self._speculated.pop(h, None)
            if self._best is None or float(score) > self._best:
                self._best = float(score)

    def note_done(self, knobs: Dict[str, Any]) -> None:
        """Trial left without a real score (diverged/errored/killed):
        retire the curve without moving best-so-far."""
        h = search_audit.knobs_hash(knobs)
        with self._lock:
            self._done.add(h)

    @property
    def best_so_far(self) -> Optional[float]:
        with self._lock:
            return self._best

    # -- consumers -----------------------------------------------------------

    def kill_verdict(self, knobs: Dict[str, Any], epoch: int,
                     trial_id: Optional[str] = None) -> Optional[CurveFit]:
        """The fit that condemns the trial, or None to keep training.
        Journals every consultation (``advisor/predict``) and every
        verdict (``advisor/kill``)."""
        if not self.config.enabled:
            return None
        h = search_audit.knobs_hash(knobs)
        with self._lock:
            if h in self._killed or h in self._done:
                return None
            pts = list(self._points.get(h, ()))
            horizon = self._horizon.get(h, DEFAULT_HORIZON)
            best = self._best
        fit = fit_curve(pts, horizon)
        if fit is None:
            return None
        search_audit.record_predict(knobs, fit.to_record(), epoch=epoch,
                                    best_so_far=best, trial_id=trial_id)
        if not self.config.should_kill(fit, epoch, best):
            return None
        with self._lock:
            self._killed.add(h)
            self._speculated.pop(h, None)
        search_audit.record_kill(
            knobs, fit.to_record(), epoch=epoch, best_so_far=best,
            config={
                "warmup_epochs": self.config.warmup_epochs,
                "margin": self.config.margin,
                "min_obs": self.config.min_obs,
            },
            trial_id=trial_id,
        )
        return fit

    def speculate_inflight(self, advisor: Any) -> int:
        """Feed the advisor predicted scores for every in-flight curve
        with enough points and no speculation yet. Iterates hashes in
        sorted order so concurrent call sites produce a deterministic
        speculation sequence for a given state. Returns how many were
        fed."""
        if not self.config.speculate:
            return 0
        with self._lock:
            candidates = []
            for h in sorted(self._points):
                if h in self._done or h in self._killed \
                        or h in self._speculated:
                    continue
                pts = self._points[h]
                if len(pts) < self.config.min_obs:
                    continue
                candidates.append((h, list(pts), self._horizon[h],
                                   dict(self._knobs[h])))
        n = 0
        for h, pts, horizon, knobs in candidates:
            fit = fit_curve(pts, horizon)
            if fit is None:
                continue
            with self._lock:
                if h in self._done or h in self._killed \
                        or h in self._speculated:
                    continue
                self._speculated[h] = fit.predicted_final
            advisor.speculate(fit.predicted_final, knobs,
                              fit=fit.to_record())
            n += 1
        return n
