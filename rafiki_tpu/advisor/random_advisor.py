"""Uniform random search — the baseline engine and the test advisor."""

from __future__ import annotations

from rafiki_tpu.advisor.base import BaseAdvisor
from rafiki_tpu.model.knobs import Knobs
from rafiki_tpu.obs.search import audit


class RandomAdvisor(BaseAdvisor):
    engine = "random"

    def _propose(self) -> Knobs:
        knobs = self.space.sample(self._rng)
        audit.record_propose(self, knobs, {"phase": "random"})
        return knobs
