"""Uniform random search — the baseline engine and the test advisor."""

from __future__ import annotations

from rafiki_tpu.advisor.base import BaseAdvisor
from rafiki_tpu.model.knobs import Knobs


class RandomAdvisor(BaseAdvisor):
    def _propose(self) -> Knobs:
        return self.space.sample(self._rng)
