"""Ask/tell hyperparameter-optimisation engines.

Reference parity: rafiki/advisor/ (advisor.py + btb_gp_advisor.py /
skopt variant; unverified paths). The reference exposes
``propose() -> knobs`` and ``feedback(score, knobs)`` behind either an
in-proc object or a small HTTP service. Same here: ``BaseAdvisor`` is
the in-proc engine, ``rafiki_tpu.advisor.service`` wraps it for
concurrent workers; the GP engine is built on sklearn's Gaussian
process (skopt is not available in this environment).
"""

from rafiki_tpu.advisor.base import BaseAdvisor, make_advisor
from rafiki_tpu.advisor.random_advisor import RandomAdvisor
from rafiki_tpu.advisor.gp import GpAdvisor
from rafiki_tpu.advisor.tpe import TpeAdvisor
from rafiki_tpu.advisor.service import AdvisorService

__all__ = ["BaseAdvisor", "RandomAdvisor", "GpAdvisor", "TpeAdvisor",
           "AdvisorService", "make_advisor"]
