"""Learning-curve extrapolation: predict a trial's final score mid-flight.

The measurement half landed in PR 12 (per-epoch ``trial/epoch_eval``
journals, ``obs curves``); this module is the model half the ROADMAP's
learning-curve-predictive advisor item calls for. Ground: ADA-GP
(PAPERS.md) — a cheap predictor with a corrective phase steering an
expensive loop — applied at trial granularity: fit a tiny saturating
family on the live (epoch, score) prefix, extrapolate to the trial's
epoch budget, and hand consumers a CONSERVATIVE credible band.

Deliberately boring numerics: two closed-form families

    pow:  s(e) = a - b * (e + 1) ** -c
    exp:  s(e) = a - b * exp(-c * e)

fit by linear least squares over a fixed decay grid (no iterative
optimiser, no rng) — every fit is deterministic and costs microseconds,
so consulting the predictor at an epoch boundary is free next to one
training step. The band is residual-scaled and inflated at small n, so
the early-kill rule ("upper band below best-so-far minus margin") stays
conservative exactly when the curve is least trustworthy.

Consumers: the kill rule in :class:`KillConfig` /
:func:`kill_verdict` (worker/train.py consults it at epoch boundaries,
off by default — ``RAFIKI_CURVE_KILL``), and the speculative scorer
(advisor/speculative.py) that feeds predicted-then-corrected scores to
the GP. Every decision made off a fit is journaled through
rafiki_tpu.obs.search.audit (docs/early_kill.md).
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

#: Master switch + knobs for the early-kill rule (docs/early_kill.md).
#: Off by default: with RAFIKI_CURVE_KILL unset the train loops run
#: today's behavior bit-exactly (no fits, no journals, no rng use).
ENV_KILL = "RAFIKI_CURVE_KILL"
ENV_KILL_WARMUP = "RAFIKI_CURVE_KILL_WARMUP"
ENV_KILL_MARGIN = "RAFIKI_CURVE_KILL_MARGIN"
ENV_KILL_MIN_OBS = "RAFIKI_CURVE_KILL_MIN_OBS"
ENV_SPECULATE = "RAFIKI_CURVE_SPECULATE"

#: Fixed decay-rate grid shared by both families: small enough to be
#: free, wide enough to bracket every curve the zoo produces. A grid
#: (not an optimiser) keeps the fit closed-form and deterministic.
_DECAY_GRID = tuple(float(c) for c in np.geomspace(0.05, 3.0, 16))


@dataclasses.dataclass(frozen=True)
class CurveFit:
    """One fitted extrapolation: point prediction + conservative band."""

    family: str              # "pow" | "exp"
    decay: float             # grid decay rate of the winning fit
    n_obs: int
    rmse: float              # residual RMSE on the observed prefix
    predicted_final: float   # point estimate at the trial's last epoch
    band: float              # half-width of the credible band
    horizon: int             # epoch budget the prediction targets

    @property
    def lo(self) -> float:
        return self.predicted_final - self.band

    @property
    def hi(self) -> float:
        return self.predicted_final + self.band

    def to_record(self) -> dict:
        """Journal-ready slice (audit.record_predict and friends)."""
        return {
            "family": self.family,
            "decay": round(self.decay, 6),
            "n_obs": self.n_obs,
            "rmse": round(self.rmse, 9),
            "predicted": round(self.predicted_final, 9),
            "band": round(self.band, 9),
            "lo": round(self.lo, 9),
            "hi": round(self.hi, 9),
            "horizon": self.horizon,
        }


def _basis(epochs: np.ndarray, family: str, c: float) -> np.ndarray:
    if family == "pow":
        return np.power(epochs + 1.0, -c)
    return np.exp(-c * epochs)


def fit_curve(points: Sequence[Tuple[int, float]],
              horizon: int) -> Optional[CurveFit]:
    """Fit the saturating family on (epoch, score) points and
    extrapolate to ``horizon`` epochs. Returns None below 2 points
    (nothing to extrapolate from). Deterministic: same points + horizon
    → bit-identical fit."""
    pts = sorted((int(e), float(s)) for e, s in points
                 if s is not None and math.isfinite(float(s)))
    if len(pts) < 2:
        return None
    e = np.asarray([p[0] for p in pts], dtype=np.float64)
    s = np.asarray([p[1] for p in pts], dtype=np.float64)
    horizon = max(int(horizon), int(e[-1]) + 1)
    best: Optional[CurveFit] = None
    for family in ("pow", "exp"):
        for c in _DECAY_GRID:
            g = _basis(e, family, c)
            # s ≈ a - b*g: linear LSQ in (a, b).
            A = np.column_stack([np.ones_like(g), -g])
            coef, *_ = np.linalg.lstsq(A, s, rcond=None)
            a, b = float(coef[0]), float(coef[1])
            resid = s - (a - b * g)
            rmse = float(np.sqrt(np.mean(resid * resid)))
            if best is not None and rmse >= best.rmse:
                continue
            gT = float(_basis(np.asarray([horizon - 1.0]), family, c)[0])
            pred = a - b * gT
            # Conservative band: residual scale, floored so a perfect
            # 2-point fit never claims certainty, inflated at small n
            # (4/n term) — the kill rule errs toward keeping trials.
            band = max(rmse, 1e-3) * (1.0 + 4.0 / len(pts))
            best = CurveFit(family=family, decay=c, n_obs=len(pts),
                            rmse=rmse, predicted_final=float(pred),
                            band=float(band), horizon=horizon)
    return best


def predict_points(fit: CurveFit,
                   points: Sequence[Tuple[int, float]]) -> List[Tuple[int, float]]:
    """The fitted curve re-evaluated at the observed epochs plus the
    horizon — what ``obs curves --predicted`` overlays."""
    pts = sorted(int(e) for e, _ in points)
    epochs = sorted(set(pts + [fit.horizon - 1]))
    e = np.asarray(epochs, dtype=np.float64)
    g = _basis(e, fit.family, fit.decay)
    # Re-derive (a, b) from prediction identities instead of carrying
    # them: a - b*g(h-1) = predicted_final and the fit minimised rmse,
    # so store both on the record? Cheaper to refit — the grid point is
    # pinned, one lstsq.
    obs = sorted((int(pe), float(ps)) for pe, ps in points)
    eo = np.asarray([p[0] for p in obs], dtype=np.float64)
    so = np.asarray([p[1] for p in obs], dtype=np.float64)
    go = _basis(eo, fit.family, fit.decay)
    A = np.column_stack([np.ones_like(go), -go])
    coef, *_ = np.linalg.lstsq(A, so, rcond=None)
    a, b = float(coef[0]), float(coef[1])
    return [(int(ep), float(a - b * gv)) for ep, gv in zip(epochs, g)]


@dataclasses.dataclass(frozen=True)
class KillConfig:
    """Early-kill rule knobs (``RAFIKI_CURVE_KILL*``, docs/early_kill.md).

    A trial dies at an epoch boundary iff ALL hold:
      * at least ``warmup_epochs`` epochs completed,
      * at least ``min_obs`` curve points observed,
      * a best-so-far score exists, and
      * the fit's UPPER band is below ``best - margin``.
    """

    enabled: bool = False
    warmup_epochs: int = 2
    margin: float = 0.02
    min_obs: int = 3
    speculate: bool = False

    @classmethod
    def from_env(cls) -> "KillConfig":
        enabled = os.environ.get(ENV_KILL, "0") not in ("", "0", "false")
        speculate = os.environ.get(ENV_SPECULATE, "0") not in ("", "0",
                                                               "false")
        return cls(
            enabled=enabled,
            warmup_epochs=int(os.environ.get(ENV_KILL_WARMUP, "2")),
            margin=float(os.environ.get(ENV_KILL_MARGIN, "0.02")),
            min_obs=int(os.environ.get(ENV_KILL_MIN_OBS, "3")),
            speculate=speculate,
        )

    def should_kill(self, fit: Optional[CurveFit], epoch: int,
                    best_so_far: Optional[float]) -> bool:
        if fit is None or best_so_far is None:
            return False
        if epoch + 1 < self.warmup_epochs or fit.n_obs < self.min_obs:
            return False
        return fit.hi < best_so_far - self.margin
