"""Tree-structured Parzen Estimator (TPE) advisor.

Second first-party engine beside the GP (the reference likewise
shipped more than one tuner — BTB ``GP`` and an skopt variant,
SURVEY.md §2 advisor row). TPE models p(x | good) and p(x | bad) with
kernel density estimates over the encoded knob space and proposes the
candidate maximising the density ratio l(x)/g(x) — equivalent to
expected improvement under the TPE factorisation (Bergstra et al.,
NeurIPS 2011, "Algorithms for Hyper-Parameter Optimization").

Where it beats the GP: sharply non-Gaussian or multi-modal objectives,
and it is O(n) per proposal (no O(n^3) fit), so it stays cheap past a
few hundred observations. Ask/tell semantics and thread safety come
from BaseAdvisor; the constant-liar pending set mirrors gp.py so
concurrent workers spread out.
"""

from __future__ import annotations

from typing import List

import numpy as np

from rafiki_tpu.advisor.base import BaseAdvisor
from rafiki_tpu.model.knobs import KnobConfig, Knobs
from rafiki_tpu.obs.search import audit


class TpeAdvisor(BaseAdvisor):
    engine = "tpe"

    def __init__(self, knob_config: KnobConfig, seed: int = 0,
                 n_initial: int = 8, n_candidates: int = 64,
                 gamma: float = 0.25, epsilon: float = 0.1):
        super().__init__(knob_config, seed=seed)
        self.n_initial = n_initial
        self.n_candidates = n_candidates
        self.gamma = gamma  # top fraction modelled as "good"
        self.epsilon = epsilon  # fraction of pure-random proposals
        self._X: List[np.ndarray] = []
        self._y: List[float] = []

    def _dim_kinds(self, span):
        from rafiki_tpu.model.knobs import CategoricalKnob, IntegerKnob

        cat, cont, sizes, floors = [], [], {}, []
        for i, (name, k) in enumerate(self.space.dims):
            if isinstance(k, CategoricalKnob):
                cat.append(i)
                sizes[i] = len(k.values)
            else:
                cont.append(i)
                f = 0.05 * span[i]
                if isinstance(k, IntegerKnob):
                    # Floor at one integer step in ENCODED units: when
                    # the whole good set shares one value (std 0) at a
                    # range boundary, a sub-step bandwidth can never
                    # sample the neighbor and the dim locks up. For
                    # is_exp dims the widest encoded step is at the low
                    # boundary: log(min+1) - log(min).
                    import math

                    step = (math.log(k.value_min + 1) - math.log(k.value_min)
                            if k.is_exp else 1.0)
                    f = max(f, step)
                floors.append(f)
        return cat, cont, sizes, np.asarray(floors)

    def _propose(self) -> Knobs:
        if self.space.d == 0:
            knobs = dict(self.space.fixed)
            audit.record_propose(self, knobs, {"phase": "fixed"})
            return knobs
        # Short-circuit order matters for RNG-stream parity with the
        # pre-audit code: the epsilon draw only happens past warmup.
        if (len(self._X) < max(2, self.n_initial)
                or self._rng.random() < self.epsilon):
            # Warmup (>=2 observations or the good/bad split is
            # degenerate) — or epsilon-exploration: the density-ratio
            # model can only believe what it has sampled, so a value
            # never proposed (e.g. a categorical choice absent from the
            # good set) would stay unproposed forever without this.
            phase = ("warmup" if len(self._X) < max(2, self.n_initial)
                     else "epsilon")
            knobs = self.space.sample(self._rng)
            self._pending_add(self.space.encode(knobs))
            audit.record_propose(self, knobs, {
                "phase": phase, "n_initial": self.n_initial,
                "epsilon": self.epsilon})
            return knobs

        b = self.space.bounds()
        span = np.maximum(b[:, 1] - b[:, 0], 1e-12)
        X = np.vstack(self._X)
        y = np.asarray(self._y)
        n_good = max(2, int(np.ceil(self.gamma * len(y))))
        order = np.argsort(-y)  # maximise score
        good, bad = X[order[:n_good]], X[order[n_good:]]
        if len(bad) < 2:
            bad = X  # degenerate early split: contrast against everything
        cat_idx, cont_idx, cat_sizes, floors = self._dim_kinds(span)

        n_cand = self.n_candidates + max(4, self.n_candidates // 8)
        cand = np.empty((n_cand, self.space.d))
        score = np.zeros(n_cand)

        if cont_idx:
            gc, bc = good[:, cont_idx], bad[:, cont_idx]
            # Scott-ish per-dim bandwidths, floored so early narrow
            # splits don't collapse the sampler (integer dims floor at
            # one step — see _dim_kinds).
            bw_g = np.maximum(gc.std(axis=0) * len(gc) ** (-1 / (len(cont_idx) + 4)),
                              floors)
            bw_b = np.maximum(bc.std(axis=0) * len(bc) ** (-1 / (len(cont_idx) + 4)),
                              floors)
            centers = gc[self._rng.integers(0, len(gc), size=self.n_candidates)]
            drawn = centers + self._rng.normal(0.0, bw_g, size=centers.shape)
            uniform = self._rng.uniform(b[cont_idx, 0], b[cont_idx, 1],
                                        size=(n_cand - self.n_candidates, len(cont_idx)))
            cc = np.clip(np.vstack([drawn, uniform]), b[cont_idx, 0], b[cont_idx, 1])
            cand[:, cont_idx] = cc
            score += self._log_kde(cc, gc, bw_g) - self._log_kde(cc, bc, bw_b)

        # Categorical dims: a KDE over category indices collapses onto
        # whatever the good set happens to contain (std 0 -> no mass on
        # unseen values). Model them as add-one-smoothed frequency
        # distributions instead: sampling keeps every category
        # reachable, and scoring is the smoothed log-probability ratio.
        for i in cat_idx:
            k = cat_sizes[i]
            cg = np.bincount(good[:, i].astype(int), minlength=k) + 1.0
            cb = np.bincount(bad[:, i].astype(int), minlength=k) + 1.0
            pg, pb = cg / cg.sum(), cb / cb.sum()
            draws = self._rng.choice(k, size=n_cand, p=pg)
            cand[:, i] = draws
            score += np.log(pg[draws]) - np.log(pb[draws])

        # Constant-liar: damp candidates near pending proposals
        # (bookkeeping in BaseAdvisor; only the damping shape here).
        for dist in self._pending_dists(cand, span):
            score = score - 4.0 * np.exp(-(dist / 0.05) ** 2)
        i = int(np.argmax(score))
        x = cand[i]
        knobs = self.space.decode(x)
        self._pending_add(self.space.encode(knobs))
        audit.record_propose(self, knobs, {
            "phase": "tpe",
            "log_ratio": round(float(score[i]), 6),
            "pool": int(n_cand),
            "n_good": int(n_good),
            "gamma": self.gamma,
        })
        return knobs

    def _feedback(self, score: float, knobs: Knobs) -> None:
        x = self.space.encode(knobs)
        self._X.append(x)
        self._y.append(score)
        audit.record_feedback(self, score, knobs)

    @staticmethod
    def _log_kde(cand: np.ndarray, pts: np.ndarray, bw: np.ndarray) -> np.ndarray:
        """log mean_k N(cand; pts_k, diag(bw^2)), up to a shared const."""
        d2 = ((cand[:, None, :] - pts[None, :, :]) / bw) ** 2  # (c, k, d)
        logp = -0.5 * d2.sum(-1)  # (c, k)
        m = logp.max(axis=1, keepdims=True)
        return (m[:, 0] + np.log(np.exp(logp - m).mean(axis=1) + 1e-300)
                - np.log(bw).sum())
