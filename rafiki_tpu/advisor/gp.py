"""Gaussian-process Bayesian optimisation (expected improvement).

Reference parity: rafiki/advisor/btb_gp_advisor.py (BTB ``GP`` tuner)
and/or the skopt ``Optimizer`` variant (unverified — see SURVEY.md).
Neither btb nor skopt exists in this environment, so the engine is
first-party: sklearn ``GaussianProcessRegressor`` (Matérn 5/2 +
white noise) over the encoded knob space, maximising expected
improvement over a random candidate set — the same ask/tell semantics
and proposal quality class as the reference's tuners.

Startup behaviour matches skopt's: the first ``n_initial`` proposals
are quasi-random exploration; after that, EI argmax.
"""

from __future__ import annotations

import math
import time
import warnings
from typing import Dict, List, Optional

import numpy as np

from rafiki_tpu.advisor.base import BaseAdvisor
from rafiki_tpu.model.knobs import KnobConfig, Knobs
from rafiki_tpu.obs.search import audit


class GpAdvisor(BaseAdvisor):
    engine = "gp"

    def __init__(self, knob_config: KnobConfig, seed: int = 0,
                 n_initial: int = 8, n_candidates: int = 512, xi: float = 0.01):
        super().__init__(knob_config, seed=seed)
        self.n_initial = n_initial
        self.n_candidates = n_candidates
        self.xi = xi
        self._X: List[np.ndarray] = []
        self._y: List[float] = []
        self._gp = None
        self._last_fit_s: Optional[float] = None
        # Speculative training rows: knobs_hash -> index into _X/_y.
        # Safe against the tail appends/deletes of _feedback and the
        # constant-liar batch because speculative rows are never at
        # the tail when those run (everything here happens under the
        # base lock) and corrections replace y in place.
        self._spec_idx: Dict[str, int] = {}

    def _propose(self) -> Knobs:
        if self.space.d == 0:
            knobs = dict(self.space.fixed)
            audit.record_propose(self, knobs, {"phase": "fixed"})
            return knobs
        if len(self._X) < self.n_initial or self._gp is None:
            knobs = self.space.sample(self._rng)
            self._pending_add(self.space.encode(knobs))
            audit.record_propose(self, knobs, {
                "phase": "warmup", "n_initial": self.n_initial})
            return knobs
        b = self.space.bounds()
        cand = self._rng.uniform(b[:, 0], b[:, 1], size=(self.n_candidates, self.space.d))
        # Refine a slice of candidates around the incumbent (local search)
        best_x = self._X[int(np.argmax(self._y))]
        local = best_x[None, :] + self._rng.normal(
            0.0, 0.1 * (b[:, 1] - b[:, 0]), size=(self.n_candidates // 4, self.space.d))
        cand = np.clip(np.vstack([cand, local]), b[:, 0], b[:, 1])
        ei, mu, sigma = self._expected_improvement(cand)
        # Penalise candidates near pending (liar) points so concurrent
        # workers don't all get the same proposal (bookkeeping lives in
        # BaseAdvisor; only the damping shape is engine-specific).
        span = np.maximum(b[:, 1] - b[:, 0], 1e-12)
        ei_damped = ei
        for dist in self._pending_dists(cand, span):
            ei_damped = ei_damped * (1.0 - np.exp(-(dist / 0.05) ** 2))
        i = int(np.argmax(ei_damped))
        x = cand[i]
        knobs = self.space.decode(x)
        # Store the *re-encoded* point: decode rounds integer/categorical
        # dims, and the feedback drain removes by encode(knobs) —
        # appending raw x would leave the pending point stuck forever.
        self._pending_add(self.space.encode(knobs))
        audit.record_propose(self, knobs, {
            "phase": "ei",
            "ei": round(float(ei[i]), 9),
            "ei_damped": round(float(ei_damped[i]), 9),
            "mu": round(float(mu[i]), 6),
            "sigma": round(float(sigma[i]), 6),
            "pool": int(len(cand)),
            "fit_s": self._last_fit_s,
        })
        return knobs

    def _propose_batch(self, n: int) -> List[Knobs]:
        """q-batch via the constant-liar(min) strategy: after each pick,
        pretend it scored the worst value seen and refit, so the EI
        surface collapses around it and the next pick explores
        elsewhere — the k knob sets of one trial pack aren't
        near-duplicates. The lies are transient: popped (and the GP
        refit on real data) before returning."""
        if self.space.d == 0 or self._gp is None or len(self._X) < self.n_initial:
            return super()._propose_batch(n)  # still exploring randomly
        out: List[Knobs] = []
        lies = 0
        lie = float(min(self._y))
        try:
            for _ in range(n):
                knobs = self._propose()
                out.append(knobs)
                self._X.append(self.space.encode(knobs))
                self._y.append(lie)
                lies += 1
                self._fit()
        finally:
            if lies:
                del self._X[-lies:]
                del self._y[-lies:]
                self._fit()
        audit.record_propose_batch(
            self, n, out, strategy="constant_liar_min",
            liar={"lie": round(lie, 6), "lies_planted": len(out)})
        return out

    def _feedback(self, score: float, knobs: Knobs) -> None:
        x = self.space.encode(knobs)
        self._X.append(x)
        self._y.append(score)
        if len(self._X) >= max(2, min(self.n_initial, 4)):
            self._fit()
        audit.record_feedback(self, score, knobs)

    def _speculate(self, score: float, knobs: Knobs) -> None:
        """Predicted score for a still-running trial enters the
        training set as a provisional row (advisor/speculative.py);
        ``_correct`` replaces its y in place when the truth lands. One
        append + one conditional fit — the exact op shape of
        ``_feedback`` — so a rehydration that replays speculations
        after real observations lands on the same rng position as a
        fresh advisor fed the same sequence (the byte-identity
        contract, docs/early_kill.md)."""
        self._spec_idx[audit.knobs_hash(knobs)] = len(self._X)
        self._X.append(self.space.encode(knobs))
        self._y.append(score)
        if len(self._X) >= max(2, min(self.n_initial, 4)):
            self._fit()

    def _correct(self, score: float, knobs: Knobs,
                 predicted: float) -> None:
        """True score replaces the speculative row and the GP refits.
        Journals both the correction (prediction error) and the
        normal feedback record (closes the ledger meter)."""
        idx = self._spec_idx.pop(audit.knobs_hash(knobs), None)
        if idx is None:
            # Speculation known to the base but never absorbed here
            # (engine swapped mid-flight); degrade to a plain append.
            audit.record_correct(self, knobs, predicted, score)
            self._feedback(score, knobs)
            return
        self._y[idx] = score
        if len(self._X) >= max(2, min(self.n_initial, 4)):
            self._fit()
        audit.record_correct(self, knobs, predicted, score)
        audit.record_feedback(self, score, knobs)

    def _fit(self) -> None:
        from sklearn.gaussian_process import GaussianProcessRegressor
        from sklearn.gaussian_process.kernels import ConstantKernel, Matern, WhiteKernel

        t0 = time.monotonic()
        X = np.vstack(self._X)
        y = np.asarray(self._y)
        # Canonical row order before fitting: the GP posterior is
        # mathematically permutation-invariant but the Cholesky is not
        # bit-level so — and crash-resume rehydration replays the same
        # observation SET in a different arrival order. Sorting makes
        # "same observations + same rng position" imply byte-identical
        # proposals, which is the advisor-rehydration equivalence
        # contract docs/recovery.md tests pin.
        if len(y) > 1:
            order = np.lexsort(np.concatenate([X, y[:, None]], axis=1).T[::-1])
            X = X[order]
            y = y[order]
        b = self.space.bounds()
        span = np.maximum(b[:, 1] - b[:, 0], 1e-12)
        kernel = (ConstantKernel(1.0) * Matern(length_scale=0.25 * span, nu=2.5)
                  + WhiteKernel(noise_level=1e-4))
        gp = GaussianProcessRegressor(kernel=kernel, normalize_y=True,
                                      n_restarts_optimizer=1,
                                      random_state=int(self._rng.integers(1 << 31)))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            gp.fit(X, y)
        self._gp = gp
        # Fit wall-time rides the next propose record's acquisition
        # block — the cost side of the O(n^3) GP refit story.
        self._last_fit_s = round(time.monotonic() - t0, 6)

    def _expected_improvement(self, cand: np.ndarray):
        """EI per candidate, plus the posterior mean/std it was computed
        from (the audit record carries all three for the chosen one)."""
        mu, sigma = self._gp.predict(cand, return_std=True)
        sigma = np.maximum(sigma, 1e-9)
        best = max(self._y)
        z = (mu - best - self.xi) / sigma
        from scipy.stats import norm

        ei = (mu - best - self.xi) * norm.cdf(z) + sigma * norm.pdf(z)
        return ei, mu, sigma
