"""Advisor over HTTP: service app + client handle.

Reference parity: rafiki/advisor/app.py (unverified — SURVEY.md §2):
a small Flask app exposing propose / feedback so train workers in
other processes (the reference: other containers) share one
optimisation state. Here: a werkzeug WSGI app the ProcessScheduler
runs on a loopback port, guarded by a shared secret header (the
reference used its service network for isolation; loopback + secret is
the host-local equivalent).
"""

from __future__ import annotations

import json
import hmac
from typing import Optional

from werkzeug.routing import Map, Rule
from werkzeug.wrappers import Request, Response

from rafiki_tpu.advisor.service import AdvisorService

_SECRET_HEADER = "X-Rafiki-Advisor-Secret"


class AdvisorApp:
    def __init__(self, service: AdvisorService, secret: Optional[str] = None):
        self.service = service
        self.secret = secret
        self.url_map = Map([
            Rule("/healthz", endpoint="healthz", methods=["GET"]),
            Rule("/advisors/<advisor_id>/propose", endpoint="propose",
                 methods=["GET"]),
            Rule("/advisors/<advisor_id>/propose_batch",
                 endpoint="propose_batch", methods=["POST"]),
            Rule("/advisors/<advisor_id>/feedback", endpoint="feedback",
                 methods=["POST"]),
        ])

    def __call__(self, environ, start_response):
        from werkzeug.exceptions import HTTPException

        request = Request(environ)
        try:
            adapter = self.url_map.bind_to_environ(environ)
            endpoint, args = adapter.match()
            if endpoint != "healthz" and self.secret is not None:
                given = request.headers.get(_SECRET_HEADER, "")
                if not hmac.compare_digest(given, self.secret):
                    raise PermissionError("Bad advisor secret")
            response = getattr(self, f"ep_{endpoint}")(request, **args)
        except HTTPException as e:  # unknown route / wrong method → 404/405
            response = self._json({"error": e.description}, e.code or 500)
        except PermissionError as e:
            response = self._json({"error": str(e)}, 401)
        except KeyError as e:
            response = self._json({"error": str(e)}, 404)
        except Exception as e:
            response = self._json({"error": f"{type(e).__name__}: {e}"}, 500)
        return response(environ, start_response)

    @staticmethod
    def _json(data, status: int = 200) -> Response:
        return Response(json.dumps(data), status=status,
                        mimetype="application/json")

    def ep_healthz(self, request: Request) -> Response:
        return self._json({"status": "ok"})

    def ep_propose(self, request: Request, advisor_id: str) -> Response:
        return self._json({"knobs": self.service.propose(advisor_id)})

    def ep_propose_batch(self, request: Request,
                         advisor_id: str) -> Response:
        """q-batch drafting for remote sweeps. Unlike the in-proc path
        (which clamps), a remote caller asking for n<1 is a protocol
        error — 400, not a silent 1. The advisor engine journals the
        advisor/propose_batch record exactly as in-proc."""
        from werkzeug.exceptions import BadRequest

        body = request.get_json(force=True, silent=True) or {}
        try:
            n = int(body.get("n"))
        except (TypeError, ValueError):
            raise BadRequest("propose_batch requires an integer 'n'")
        if n < 1:
            raise BadRequest(f"propose_batch n must be >= 1, got {n}")
        return self._json(
            {"knobs_list": self.service.propose_batch(advisor_id, n)})

    def ep_feedback(self, request: Request, advisor_id: str) -> Response:
        body = request.get_json(force=True)
        self.service.feedback(advisor_id, float(body["score"]), body["knobs"])
        return self._json({"ok": True})


class HttpAdvisorHandle:
    """Worker-side AdvisorHandle speaking to an AdvisorApp.

    propose() blocks through transient connection errors (the advisor
    server may come up a beat after the worker process) with bounded
    retries.
    """

    def __init__(self, base_url: str, advisor_id: str,
                 secret: Optional[str] = None, retries: int = 10,
                 retry_delay_s: float = 0.3):
        import requests

        self._requests = requests
        self._base = base_url.rstrip("/")
        self._id = advisor_id
        self._headers = {_SECRET_HEADER: secret} if secret else {}
        self._retries = retries
        self._retry_delay_s = retry_delay_s

    def _call(self, method: str, path: str, **kwargs):
        import time

        last = None
        for _ in range(self._retries):
            try:
                resp = self._requests.request(
                    method, self._base + path, headers=self._headers,
                    timeout=30.0, **kwargs)
                if resp.status_code >= 400:
                    raise RuntimeError(f"advisor HTTP {resp.status_code}: "
                                       f"{resp.text[:200]}")
                return resp.json()
            except (self._requests.ConnectionError, self._requests.Timeout) as e:
                last = e
                time.sleep(self._retry_delay_s)
        raise RuntimeError(f"advisor unreachable at {self._base}: {last}")

    def propose(self):
        return self._call("GET", f"/advisors/{self._id}/propose")["knobs"]

    def propose_batch(self, n: int):
        """q proposals in one round-trip (the server clamps nothing:
        n < 1 is a 400 — surface the caller's bug, don't paper it)."""
        return self._call("POST", f"/advisors/{self._id}/propose_batch",
                          json={"n": int(n)})["knobs_list"]

    def feedback(self, score: float, knobs) -> None:
        self._call("POST", f"/advisors/{self._id}/feedback",
                   json={"score": float(score), "knobs": knobs})
