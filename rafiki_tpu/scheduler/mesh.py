"""Mesh sweep scheduler: k packed trials per chip × N chips, elastic.

The paper's train plane is "one trial per GPU" on a fixed 8-GPU box;
this module drives the whole 8-chip mesh as ONE sweep: a single
``Advisor.propose_batch(N*k)`` drafts every slot up front, rows are
budget-claimed atomically, and each chip trains its share as one
vmapped pack (docs/trial_packing.md). Robustness is the headline
(docs/mesh_sweep.md):

  * **Elastic re-packing** — a chip lost mid-sweep (the supervisor's
    ``scheduler.preempt`` chaos probe, or a runner thread dying) leaves
    its trials RUNNING, never errored; the supervisor slices them off
    the dead chip and re-assigns them round-robin to surviving chips,
    where each resumes serially from its newest per-epoch packed
    checkpoint (fresh rerun when none exists — both bit-match an
    unfaulted serial run).
  * **Collective-init retry** — mesh formation retries with exponential
    backoff inside a bounded grace window (``RAFIKI_MESH_INIT_RETRIES``
    / ``RAFIKI_MESH_INIT_BACKOFF_S`` / ``RAFIKI_MESH_FORM_GRACE_S``),
    with the ``collective.init`` chaos site armed per attempt.
  * **Bounded-grace degradation** — when the mesh cannot form inside
    the grace window, the sweep degrades to single-chip mode instead of
    failing: same trials, one chip, and a ``mesh_degraded`` event +
    journal record so the downgrade is reconstructible after the fact.
  * **Sharded lane** — a proposal whose plan wants ``width > 1`` chips
    forks onto a :class:`GroupHandle` instead of a pack: one trial
    sharded FSDP-style across a chip group, member loss handled by
    re-forming at reduced width and resuming via reshard-on-restore
    (docs/sharding.md).

The per-chip worker is the ordinary :class:`TrainWorker` — every
per-trial contract (store rows, scores, feedback, logs, params,
events) is exactly the serial one; only placement and recovery are
mesh-level concerns.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional

from rafiki_tpu import chaos, telemetry
from rafiki_tpu.advisor import AdvisorService
from rafiki_tpu.constants import (BudgetType, ServiceStatus, ServiceType,
                                  TrainJobStatus, TrialStatus)
from rafiki_tpu.model.base import load_model_class
from rafiki_tpu.model.knobs import knob_config_signature
from rafiki_tpu.obs.journal import journal as _journal
from rafiki_tpu.obs.ledger import ledger
from rafiki_tpu.obs.search.audit import knobs_hash as _knobs_hash
from rafiki_tpu.parallel.mesh import local_devices
from rafiki_tpu.scheduler.local import TrainJobResult
from rafiki_tpu.scheduler.wal import SweepWal
from rafiki_tpu.store import MetaStore, ParamsStore
from rafiki_tpu.utils.events import events
from rafiki_tpu.worker.train import (InProcAdvisorHandle, PackAborted,
                                     PackedTrialRunner, TrainWorker)


class _WalAdvisorHandle:
    """Durability wrapper around the advisor handle: every ``feedback``
    is intent/commit-bracketed in the sweep WAL before it mutates the
    in-memory posterior, so ``resume_sweep`` knows exactly which scores
    the dead advisor had absorbed (docs/recovery.md). Proposals need no
    WAL record — an unscored proposal is reproducible from the advisor
    audit journal and claims nothing."""

    def __init__(self, inner, wal: SweepWal):
        self._inner = inner
        self._wal = wal

    def propose(self):
        return self._inner.propose()

    def propose_batch(self, n: int):
        return self._inner.propose_batch(n)

    def feedback(self, score: float, knobs) -> None:
        txn = self._wal.intent("advisor_feedback", score=float(score),
                               knobs_hash=_knobs_hash(knobs))
        self._inner.feedback(score, knobs)
        self._wal.commit(txn, "advisor_feedback")

    def speculate(self, score: float, knobs, fit=None) -> None:
        # Like proposals, speculations need no WAL record: an
        # uncorrected speculation is reproducible from its
        # ``advisor/speculate`` audit journal (rehydrate_advisor
        # replays them), and the correction rides the normal feedback
        # path above — which IS bracketed (docs/early_kill.md).
        self._inner.speculate(score, knobs, fit=fit)


class ElasticHandle:
    """Runtime grow/shrink surface for a live sweep (docs/autoscale.md).

    The autoscale controller's sweep lane requests chip-count deltas
    here (through ``autoscale.actuators.SweepChipLane`` — RF012 keeps
    other callers out); the supervisor applies them at its next poll
    with the machinery that already exists: shrink aborts the
    highest-index runner at its next epoch boundary and re-packs its
    rows onto survivors (the chip-loss path, minus the downtime
    charge), grow spawns a fresh ``_ChipRunner`` into the sweep.
    Asynchronous by design — ``desired()`` reports live + pending so
    the controller never double-requests between polls."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._pending = 0
        self._live = 0
        self.applied: List[Dict[str, Any]] = []

    def request(self, delta: int) -> None:
        with self._lock:
            self._pending += int(delta)

    def desired(self) -> int:
        with self._lock:
            return max(0, self._live + self._pending)

    def live(self) -> int:
        with self._lock:
            return self._live

    def _set_live(self, n: int) -> None:
        with self._lock:
            self._live = int(n)

    def _take(self) -> int:
        """Consume the pending delta (supervisor poll)."""
        with self._lock:
            delta, self._pending = self._pending, 0
            return delta


class _ChipRunner:
    """One chip's worker thread + task queue. Tasks are ``("pack",
    rows)`` (train a claimed row set as one pack) or ``("resume",
    trial_id)`` (serially resume a trial re-packed off a dead chip);
    ``("stop", None)`` ends the thread. ``abort`` is the chip-loss
    signal: the in-flight pack raises :class:`PackAborted` at its next
    epoch boundary and the runner marks itself dead."""

    def __init__(self, index: int, device: Any, worker: TrainWorker,
                 pack: int, errors: List[str],
                 budget_max: Optional[int] = None):
        self.index = index
        self.device = device
        self.worker = worker
        self.budget_max = budget_max
        self.runner = PackedTrialRunner(worker, pack)
        self.tasks: "queue.Queue" = queue.Queue()
        self.abort = threading.Event()
        self.dead = False        # chip lost: no further tasks run here
        self.reaped = False      # supervisor already re-packed its rows
        self.scaled_down = False  # voluntary shrink, not a loss
        self.busy = False
        self._errors = errors
        self.thread = threading.Thread(target=self._loop,
                                       name=f"mesh-chip-{index}", daemon=True)

    @property
    def service_id(self) -> Optional[str]:
        return self.worker.service_id

    def idle(self) -> bool:
        # unfinished_tasks increments at put() and only decrements at
        # task_done() — unlike empty()+busy there is no window where an
        # assigned-but-not-yet-started task reads as idle.
        return self.tasks.unfinished_tasks == 0

    def alive(self) -> bool:
        return not self.dead and self.thread.is_alive()

    def _loop(self) -> None:
        # Leader/follower start skew: a delay-mode fault here staggers
        # this chip's entry into the sweep (the mesh.skew chaos site).
        chaos.hook("mesh.skew", key=f"chip{self.index}")
        while True:
            try:
                kind, payload = self.tasks.get(timeout=0.05)
            except queue.Empty:
                if self.abort.is_set():
                    self.dead = True
                    return
                continue
            if kind == "stop":
                self.tasks.task_done()
                return
            if self.abort.is_set():
                # Lost/stopping chip: don't START queued work — its rows
                # stay RUNNING bound to this chip's service, so the
                # supervisor's reap finds and re-packs them.
                self.dead = True
                self.tasks.task_done()
                return
            self.busy = True
            try:
                if kind == "pack":
                    # budget_max keeps the mid-pack backfill closure on
                    # the atomic slot-claim path: without it, backfilled
                    # trials bypass MODEL_TRIAL_COUNT and the pack never
                    # drains.
                    self.runner.run_assigned(payload,
                                             budget_max=self.budget_max,
                                             abort=self.abort)
                else:  # "resume"
                    self.worker.resume_trial(payload)
            except PackAborted:
                # Chip lost mid-pack: rows are still RUNNING; the
                # supervisor re-packs them onto surviving chips.
                self.dead = True
                return  # the finally below still runs task_done()
            except Exception as e:
                # A task failure is contained (its trials are already
                # marked errored by the worker); the chip lives on.
                self._errors.append(f"chip {self.index}: {e!r}")
            finally:
                self.busy = False
                if kind != "stop":
                    self.tasks.task_done()


class GroupHandle:
    """One chip group running group-sharded trials (docs/sharding.md).

    The sharded-lane analog of a :class:`_ChipRunner`: ``width`` chips
    form a ``("shard",)`` mesh and train ONE trial at a time via
    :func:`rafiki_tpu.shard.train_sharded`, checkpointing per-shard
    chunk manifests every ``RAFIKI_CHECKPOINT_EVERY`` epochs. Member
    loss — the same ``scheduler.preempt`` chaos probe the supervisor
    polls for single chips, keyed ``chip<i>`` over this group's member
    indices — aborts the in-flight trial at its next epoch boundary
    (that epoch's checkpoint durable FIRST), re-forms the group at
    reduced width on the survivors, and resumes the trial from its
    manifest via reshard-on-restore. The group survives while at least
    one member lives; re-formations journal ``shard/group_formed``
    again, so the journal stream alone reconstructs the width history.
    """

    def __init__(self, gi: int, job: dict, sub: dict, model_cls: type,
                 handle, store: MetaStore, params_store: ParamsStore,
                 member_indices: List[int], devices: List[Any],
                 errors: List[str], stop_event: threading.Event):
        self.gi = gi
        self.job = job
        self.sub = sub
        self.model_cls = model_cls
        self.handle = handle
        self.store = store
        self.params_store = params_store
        self.members = list(member_indices)
        self.devices = list(devices)
        self.rows: List[tuple] = []  # (trial_id, knobs), trained in order
        self.errors = errors
        self.stop_event = stop_event
        self.worker_id = f"{job['id'][:8]}-shard-g{gi}"
        self.abort = threading.Event()   # member-loss / stop signal
        self.lost: set = set()           # member indices the probe took
        self.done = threading.Event()
        service = store.create_service(
            ServiceType.TRAIN_WORKER.value, job_id=job["id"],
            worker_index=self.members[0], devices=[str(d) for d in devices])
        store.update_service(service["id"],
                             status=ServiceStatus.RUNNING.value)
        self.service_id = service["id"]
        self.thread = threading.Thread(target=self._run,
                                       name=f"shard-group-{gi}", daemon=True)
        self._poller = threading.Thread(target=self._poll,
                                        name=f"shard-group-{gi}-probe",
                                        daemon=True)

    def start(self) -> None:
        self.thread.start()
        self._poller.start()

    def _poll(self) -> None:
        """Member-loss probe, same site + key scheme as the single-chip
        supervisor: a ``scheduler.preempt`` kill against any live
        member flags it lost and trips the group abort (the epoch loop
        raises GroupAborted AFTER the boundary checkpoint)."""
        while not self.done.is_set():
            for i in list(self.members):
                if i in self.lost:
                    continue
                decision = chaos.decide("scheduler.preempt", key=f"chip{i}")
                if decision is not None and decision.mode in (
                        "kill", "term", "preempt"):
                    self.lost.add(i)
                    self.abort.set()
            if self.stop_event.is_set():
                self.abort.set()
            time.sleep(0.02)

    def _run(self) -> None:
        try:
            for tid, kn in self.rows:
                if self.stop_event.is_set():
                    return
                self._run_trial(tid, kn)
        finally:
            self.done.set()
            self.store.update_service(self.service_id,
                                      status=ServiceStatus.STOPPED.value)

    def _run_trial(self, tid: str, kn: dict) -> None:
        from rafiki_tpu.shard import GroupAborted, ShardPlan, train_sharded

        job_id = self.job["id"]
        every = int(os.environ.get("RAFIKI_CHECKPOINT_EVERY", "0"))
        attempt = 0
        while True:
            width = len(self.devices)
            model = self.model_cls(**kn)
            self.store.mark_trial_as_running(
                tid, service_id=self.service_id, worker_id=self.worker_id)
            plan = ShardPlan(width=width, family=self.model_cls.__name__)
            plan.note()
            telemetry.inc("shard.groups_formed")
            telemetry.set_gauge("shard.group_width", width)
            _journal.record("shard", "group_formed", job_id=job_id,
                            trial_id=tid, width=width, members=self.members,
                            attempt=attempt)

            def sink(epoch: int, loop, _tid=tid) -> None:
                if every > 0 and (epoch + 1) % every == 0:
                    t0 = time.monotonic()
                    try:
                        from rafiki_tpu.shard import save_sharded

                        save_sharded(self.params_store, _tid, epoch,
                                     loop.state, loop.width)
                        events.emit("checkpoint_written", trial_id=_tid,
                                    epoch=epoch, worker_id=self.worker_id)
                    except Exception:
                        # Same contract as the serial sink: a failed
                        # checkpoint costs resumability, not the trial.
                        telemetry.inc("worker.checkpoint_write_failed")
                    finally:
                        # lint: disable=RF007 — checkpoint_s ledger charge, not a span
                        ledger.add("checkpoint_s", time.monotonic() - t0,
                                   entity=f"trial:{_tid}")
                self.store.update_service(self.service_id, heartbeat=True)
                # AFTER the write, same ordering as the serial path: a
                # kill-at-epoch-N fault lands with epoch N durable.
                chaos.hook("worker.epoch", key=self.worker_id)

            try:
                train_sharded(model, self.job["train_dataset_uri"],
                              self.devices, plan=plan, checkpoint_sink=sink,
                              abort=self.abort,
                              resume_from=(self.params_store, tid))
            except GroupAborted:
                survivors = [i for i in self.members if i not in self.lost]
                gone = [i for i in self.members if i in self.lost]
                if self.stop_event.is_set():
                    return  # stop, not loss: row stays RUNNING
                telemetry.inc("mesh.chips_lost", max(1, len(gone)))
                _journal.record("shard", "member_lost", job_id=job_id,
                                trial_id=tid, lost=gone, survivors=survivors)
                events.emit("shard_member_lost", job_id=job_id,
                            trial_id=tid, lost=gone)
                self.devices = [d for i, d in zip(self.members, self.devices)
                                if i not in self.lost]
                self.members = survivors
                self.abort.clear()
                if not self.members:
                    self.store.mark_trial_as_errored(
                        tid, "sharded group lost every chip")
                    events.emit("trial_errored", trial_id=tid,
                                worker_id=self.worker_id,
                                error="sharded group lost every chip")
                    return
                attempt += 1
                continue  # re-form on the survivors; the resume path
                # reshards the last durable manifest to the new width.
            except Exception as e:
                self.errors.append(f"shard group {self.gi}: {e!r}")
                self.store.mark_trial_as_errored(tid, repr(e))
                events.emit("trial_errored", trial_id=tid,
                            worker_id=self.worker_id, error=repr(e))
                return
            # Completion: identical bookkeeping to TrainWorker._persist
            # (the detached serial loop train_sharded installed makes
            # evaluate/dump_parameters behave exactly post-serial-train).
            try:
                score = float(model.evaluate(self.job["val_dataset_uri"]))
                blob = model.dump_parameters()
                params_id = self.params_store.save(blob)
                self.store.mark_trial_as_completed(tid, score, params_id)
                self.params_store.delete_checkpoints(tid)  # superseded
                events.emit("trial_completed", trial_id=tid, score=score,
                            worker_id=self.worker_id)
            except Exception as e:
                self.errors.append(f"shard group {self.gi} persist: {e!r}")
                self.store.mark_trial_as_errored(
                    tid, f"params persist failed: {e!r}")
                events.emit("trial_errored", trial_id=tid,
                            worker_id=self.worker_id,
                            error="params persist failed")
                return
            try:
                self.handle.feedback(score, kn)
            except Exception:
                pass
            return


class MeshSweepScheduler:
    """Drives one train job as an elastic k-trials-per-chip × N-chip
    sweep (docs/mesh_sweep.md). Blocking, in-process: one thread per
    chip, a supervisor polling for chip loss and completion."""

    def __init__(self, store: MetaStore, params_store: ParamsStore,
                 advisor_service: Optional[AdvisorService] = None):
        self.store = store
        self.params_store = params_store
        self.advisors = advisor_service or AdvisorService()
        self._wal: Optional[SweepWal] = None
        self._generation = 0
        self._sup_service_id: Optional[str] = None

    # -- mesh formation ------------------------------------------------------

    def _form_mesh(self, want: int) -> "tuple[List[Any], bool]":
        """Gather ``want`` devices, retrying collective initialization
        with exponential backoff inside a bounded grace window. Returns
        (devices, degraded): on exhaustion the sweep DEGRADES to
        single-chip mode rather than failing — the trials all still
        run, just without mesh parallelism."""
        retries = int(os.environ.get("RAFIKI_MESH_INIT_RETRIES", "3"))
        backoff = float(os.environ.get("RAFIKI_MESH_INIT_BACKOFF_S", "0.05"))
        grace = float(os.environ.get("RAFIKI_MESH_FORM_GRACE_S", "30"))
        deadline = time.monotonic() + grace
        last: Optional[BaseException] = None
        for attempt in range(retries + 1):
            try:
                # The collective.init chaos site is armed once per
                # attempt (error mode = injected init failure).
                chaos.hook("collective.init", key=f"attempt{attempt}")
                devs = local_devices()
                if len(devs) < want:
                    raise RuntimeError(
                        f"{len(devs)} device(s) visible, want {want}")
                return devs[:want], False
            except Exception as e:
                last = e
                if attempt >= retries or time.monotonic() >= deadline:
                    break
                telemetry.inc("mesh.init_retries")
                events.emit("collective_init_retry", attempt=attempt,
                            error=str(e))
                time.sleep(max(0.0, min(backoff * (2 ** attempt),
                                        deadline - time.monotonic())))
        telemetry.inc("mesh.degraded_single_chip")
        _journal.record("mesh", "degraded", want=want, error=str(last))
        events.emit("mesh_degraded", want=want, error=str(last))
        # The degraded fallback may itself fail (device runtime down,
        # zero devices visible) — return [] and let run_sweep fail the
        # job cleanly instead of propagating with the job left RUNNING.
        try:
            devs = local_devices()[:1]
        except Exception as e:
            last = e
            devs = []
        if not devs:
            _journal.record("mesh", "no_devices", want=want,
                            error=str(last))
            events.emit("mesh_no_devices", want=want, error=str(last))
        return devs, True

    # -- the sweep -----------------------------------------------------------

    def run_sweep(
        self,
        job_id: str,
        chips: Optional[int] = None,
        trials_per_chip: int = 2,
        advisor_kind: str = "gp",
        stop_event: Optional[threading.Event] = None,
        elastic: Optional[ElasticHandle] = None,
        generation: int = 0,
        advisor_kwargs: Optional[Dict[str, Any]] = None,
    ) -> TrainJobResult:
        """Run a train job as one mesh sweep to budget exhaustion.
        ``elastic``, when given, lets the autoscale controller grow and
        shrink the chip count while the sweep runs. ``generation``
        distinguishes supervisor incarnations of the same job (0 = the
        original; ``resume_sweep`` runs at generation+1) — it tags the
        WAL records and the ``supervisor.tick``/``host.loss`` chaos keys
        so a kill fault can be scoped to one incarnation."""
        t0 = time.monotonic()
        job = self.store.get_train_job(job_id)
        if job is None:
            raise KeyError(f"No train job {job_id!r}")
        self.store.update_train_job_status(job_id, TrainJobStatus.RUNNING.value)
        events.emit("train_job_started", job_id=job_id, app=job["app"],
                    budget=job["budget"], scheduler="mesh")
        stop_event = stop_event or threading.Event()

        budget = dict(job["budget"])
        chip_budget = budget.get("CHIP_COUNT") or budget.get("GPU_COUNT")
        want = int(chips or chip_budget or 8)

        # Durable control-plane log + the supervisor's liveness lease:
        # both must exist BEFORE any budget mutation, so a resumer can
        # (a) find the sweep's config without this process and (b) tell
        # a dead supervisor from a slow one (docs/recovery.md).
        self._generation = int(generation)
        self._wal = SweepWal.for_job(self.store, job_id,
                                     generation=self._generation)
        self._wal.note("sweep_config", job_id=job_id,
                       advisor_kind=advisor_kind,
                       advisor_kwargs=advisor_kwargs or {},
                       chips=want, trials_per_chip=int(trials_per_chip))
        sup = self.store.create_service(ServiceType.SUPERVISOR.value,
                                        job_id=job_id,
                                        worker_index=self._generation)
        self._sup_service_id = sup["id"]
        self.store.update_service(sup["id"],
                                  status=ServiceStatus.RUNNING.value,
                                  heartbeat=True)
        _journal.record("mesh", "supervisor_started", job_id=job_id,
                        generation=self._generation, service_id=sup["id"])

        devices, degraded = self._form_mesh(want)
        if not devices:
            self.store.update_service(sup["id"],
                                      status=ServiceStatus.STOPPED.value)
            self._wal.close()
            self.store.update_train_job_status(job_id,
                                               TrainJobStatus.ERRORED.value)
            for sub in self.store.get_sub_train_jobs(job_id):
                self.store.update_sub_train_job(
                    sub["id"], status=TrainJobStatus.ERRORED.value)
            # lint: disable=RF007 — job duration emitted into the event/result below
            dur_s = time.monotonic() - t0
            events.emit("train_job_finished", job_id=job_id,
                        status=TrainJobStatus.ERRORED.value,
                        duration_s=round(dur_s, 3),
                        degraded=True)
            return TrainJobResult(
                job_id=job_id,
                status=TrainJobStatus.ERRORED.value,
                trials=[],
                best_trials=[],
                duration_s=dur_s,
                errors=["mesh sweep: no device obtainable"],
            )
        k = max(1, int(trials_per_chip))

        # Twin placement consultation (docs/twin.md): with
        # RAFIKI_TWIN_PLACEMENT set, ask the calibrated train twin for
        # a pack/split recommendation at admission — BEFORE any budget
        # slot is claimed. Advisory-only by contract: the answer is
        # journaled as twin/placement and never changes this sweep;
        # a missing/stale calibration records the error and moves on.
        if os.environ.get("RAFIKI_TWIN_PLACEMENT"):
            try:
                from rafiki_tpu.obs.twin.train import placement as _placement

                _placement.consult(job_id=job_id, chips=len(devices), k=k,
                                   budget=budget)
            except Exception as e:
                _journal.record("twin", "placement", job_id=job_id,
                                advisory=True, error=str(e))

        errors: List[str] = []
        subs = self.store.get_sub_train_jobs(job_id)
        if not subs:
            raise ValueError(f"Train job {job_id} has no sub jobs (no models attached)")

        for sub in subs:
            if stop_event.is_set():
                self.store.update_sub_train_job(
                    sub["id"], status=TrainJobStatus.STOPPED.value)
                continue
            model_row = self.store.get_model(sub["model_id"])
            try:
                model_cls = load_model_class(model_row["model_file"],
                                             model_row["model_class"])
            except Exception as e:
                self.store.update_sub_train_job(
                    sub["id"], status=TrainJobStatus.ERRORED.value)
                errors.append(f"model {model_row['name']}: {e}")
                continue
            advisor_id = self.advisors.create_advisor(
                model_cls.get_knob_config(), kind=advisor_kind,
                advisor_id=sub.get("advisor_id") or None,
                engine_kwargs=advisor_kwargs)
            try:
                # Stamp the job onto the engine so its advisor/*
                # journal records answer `obs sweep <job>` directly.
                self.advisors.get(advisor_id).job_id = job_id
            except KeyError:
                pass
            self.store.update_sub_train_job(sub["id"], advisor_id=advisor_id,
                                            status=TrainJobStatus.RUNNING.value)
            handle = _WalAdvisorHandle(
                InProcAdvisorHandle(self.advisors, advisor_id), self._wal)

            self._run_sub(job, sub, model_cls, handle, devices, k,
                          budget, errors, stop_event, elastic=elastic)

            trials = self.store.get_trials_of_sub_train_job(sub["id"])
            if stop_event.is_set():
                sub_status = TrainJobStatus.STOPPED.value
            elif trials and all(t["status"] == TrialStatus.ERRORED.value
                                for t in trials):
                sub_status = TrainJobStatus.ERRORED.value
            else:
                sub_status = TrainJobStatus.COMPLETED.value
            self.store.update_sub_train_job(sub["id"], status=sub_status)
            self.advisors.delete_advisor(advisor_id)

        subs_after = self.store.get_sub_train_jobs(job_id)
        if stop_event.is_set():
            status = TrainJobStatus.STOPPED.value
        elif subs_after and all(s["status"] == TrainJobStatus.ERRORED.value
                                for s in subs_after):
            status = TrainJobStatus.ERRORED.value
        else:
            status = TrainJobStatus.COMPLETED.value
        self.store.update_train_job_status(job_id, status)
        # Clean shutdown: release the liveness lease and the WAL handle.
        # On a crash neither line runs — exactly the signal the resume
        # reaper keys on (stale SUPERVISOR heartbeat + RUNNING job).
        self.store.update_service(sup["id"],
                                  status=ServiceStatus.STOPPED.value)
        self._wal.close()
        telemetry.inc("scheduler.train_jobs_finished")
        # lint: disable=RF007 — job duration observed into train_job_s right here
        dur_s = time.monotonic() - t0
        telemetry.observe("scheduler.train_job_s", dur_s)
        events.emit("train_job_finished", job_id=job_id, status=status,
                    duration_s=round(dur_s, 3),
                    degraded=degraded)
        return TrainJobResult(
            job_id=job_id,
            status=status,
            trials=self.store.get_trials_of_train_job(job_id),
            best_trials=self.store.get_best_trials_of_train_job(job_id, limit=2),
            duration_s=dur_s,
            errors=errors,
        )

    def _run_sub(self, job: dict, sub: dict, model_cls: type, handle,
                 devices: List[Any], k: int, budget: Dict[str, Any],
                 errors: List[str], stop_event: threading.Event,
                 elastic: Optional[ElasticHandle] = None) -> None:
        """One sub-job's sweep: draft, claim, distribute, supervise."""
        job_id = job["id"]
        n_chips = len(devices)
        assert n_chips >= 1, "mesh sweep needs at least one device"
        max_trials = budget.get(BudgetType.MODEL_TRIAL_COUNT.value)
        budget_max = int(max_trials) if max_trials is not None else None
        n_slots = n_chips * k
        if budget_max is not None:
            n_slots = min(n_slots, budget_max)

        # ONE batched draft for the whole mesh — the paper's per-GPU
        # propose loop collapses into a single call.
        with telemetry.span("mesh.advisor_propose", job_id=job_id, n=n_slots):
            batch = getattr(handle, "propose_batch", None)
            proposals = (batch(n_slots) if batch is not None
                         else [handle.propose() for _ in range(n_slots)])

        knob_config = model_cls.get_knob_config()

        # Claim every row up front (atomic budget slots), bucketed by
        # packing key — only same-key rows may share a pack — then
        # round-robin each bucket across chips. Each claim is WAL
        # intent/commit-bracketed: a resumer reconciles these records
        # against the trial rows to prove every budget slot was claimed
        # exactly once (docs/recovery.md).
        #
        # Sharded lane fork (docs/sharding.md): a proposal whose
        # ``shard_plan`` solves a width > 1 doesn't fit one chip — it
        # buckets under the ``("sharded", family, width)`` key variant
        # instead of its packing key, and its bucket gets a chip GROUP
        # (GroupHandle, carved from the tail of the device list) rather
        # than a k-wide pack slot. Claiming happens BEFORE runner
        # creation so group devices never host a _ChipRunner.
        wal = self._wal
        buckets: Dict[str, List[tuple]] = {}
        order: List[str] = []
        bucket_epochs: Dict[str, Optional[int]] = {}
        group_buckets: Dict[int, List[tuple]] = {}  # width -> rows
        group_order: List[int] = []
        for kn in proposals:
            width = 1
            try:
                m = model_cls(**kn)
                ds = m._prepared_dataset(job["train_dataset_uri"])
                sp = getattr(m, "shard_plan", None)
                sp = sp(ds) if callable(sp) else None
                width = max(1, int(getattr(sp, "width", 1) or 1))
                if width > 1:
                    key = repr(("sharded", model_cls.__name__, width))
                else:
                    key = repr(m.packing_key(ds))
                epochs = int(getattr(m, "epochs", 0)) or None
            except Exception:
                width = 1
                key = f"unpackable:{id(kn)}"  # its own singleton pack
                epochs = None
            bucket_epochs.setdefault(key, epochs)
            txn = wal.intent("budget_claim", sub_id=sub["id"],
                             knobs_hash=_knobs_hash(kn))
            trial = self.store.create_trial(
                sub["id"], model_cls.__name__, kn,
                shape_sig=knob_config_signature(knob_config, kn),
                budget_max=budget_max)
            if trial is None:
                wal.commit(txn, "budget_claim", denied=True)
                break  # budget drained under us
            wal.commit(txn, "budget_claim", trial_id=trial["id"])
            if width > 1:
                if width not in group_buckets:
                    group_order.append(width)
                    group_buckets[width] = []
                group_buckets[width].append((trial["id"], kn))
                continue
            if key not in buckets:
                order.append(key)
                buckets[key] = []
            buckets[key].append((trial["id"], kn))

        # Carve group devices from the TAIL of the device list so the
        # packed lane keeps the low indices; one GroupHandle per
        # distinct width, training its rows sequentially. The width is
        # clamped to what the mesh can actually give (always leaving
        # one chip for the packed lane while it has rows).
        avail = list(devices)
        reserve = 1 if any(buckets.values()) else 0
        groups: List[GroupHandle] = []
        for gi, width in enumerate(group_order):
            take = min(width, len(avail) - reserve)
            if take >= 1:
                member_devs = avail[len(avail) - take:]
                del avail[len(avail) - take:]
                member_idx = list(range(len(avail),
                                        len(avail) + take))
            else:
                # Degenerate mesh (packed rows + a group, one device):
                # share the device at width 1, under a member index
                # past every real chip so preempt keys never collide.
                member_devs = [avail[0]]
                member_idx = [n_chips + gi]
            g = GroupHandle(gi, job, sub, model_cls, handle, self.store,
                            self.params_store, member_idx, member_devs,
                            errors, stop_event)
            g.rows = group_buckets[width]
            groups.append(g)
        n_regular = len(avail)

        # Services + workers, one per (packed-lane) chip. Sync
        # persistence: the supervisor reads row statuses for completion
        # tracking, so scores must be durable when a pack returns.
        # ONE curve coordinator for the whole mesh (None when the
        # RAFIKI_CURVE_* knobs are off): chips share best-so-far, so a
        # kill on chip 0 raises the bar for chip 3's stragglers, and a
        # backfill on any chip can speculate every in-flight trial
        # fleet-wide (docs/early_kill.md).
        from rafiki_tpu.advisor.speculative import CurveCoordinator
        curve = CurveCoordinator.from_env()
        runners: List[_ChipRunner] = []
        if any(buckets.values()):
            for i, dev in enumerate(avail):
                service = self.store.create_service(
                    ServiceType.TRAIN_WORKER.value, job_id=job_id,
                    worker_index=i, devices=[str(dev)])
                self.store.update_service(service["id"],
                                          status=ServiceStatus.RUNNING.value)
                worker = TrainWorker(
                    self.store, self.params_store, sub["id"], model_cls, handle,
                    job["train_dataset_uri"], job["val_dataset_uri"], budget,
                    worker_id=f"{job_id[:8]}-mesh-c{i}", devices=[dev],
                    job_created_at=job["created_at"], service_id=service["id"],
                    stop_event=stop_event, async_persist=False,
                )
                # The mid-pack backfill closure claims budget slots from
                # inside the worker — hand it the WAL so those claims are
                # intent/commit-bracketed like the up-front ones.
                worker.wal = self._wal
                worker.curve = curve
                runners.append(_ChipRunner(i, dev, worker, k, errors,
                                           budget_max=budget_max))
        assign: List[List[List[tuple]]] = [[[] for _ in order]
                                           for _ in runners]
        # Global round-robin cursor: restarting at chip 0 per bucket
        # would pile every singleton bucket onto chip 0.
        cursor = 0
        for b, key in enumerate(order):
            for row in buckets[key]:
                assign[cursor % max(1, len(runners))][b].append(row)
                cursor += 1
        for r, per_bucket in zip(runners, assign):
            for b, rows in enumerate(per_bucket):
                if rows:
                    txn = wal.intent("pack_assign", chip=r.index,
                                     trial_ids=[tid for tid, _kn in rows])
                    # Bind the rows to their chip's service so a later
                    # chip loss can find exactly this chip's orphans.
                    for tid, _kn in rows:
                        self.store.mark_trial_as_running(
                            tid, service_id=r.service_id,
                            worker_id=r.worker.worker_id)
                    r.tasks.put(("pack", rows))
                    wal.commit(txn, "pack_assign")
                    # First-class pack-composition record: the train
                    # twin's calibrator reads these directly instead of
                    # inferring composition from the fill-ratio gauge
                    # (docs/twin.md).
                    _journal.record(
                        "mesh", "pack_formed", job_id=job_id,
                        chip=r.index, packing_key=order[b],
                        k=len(rows), fill_ratio=round(len(rows) / float(k), 4),
                        epochs=bucket_epochs.get(order[b]),
                        trial_ids=[tid for tid, _kn in rows],
                        knobs_hashes=[_knobs_hash(kn) for _tid, kn in rows])
        _journal.record("mesh", "sweep_started", job_id=job_id,
                        chips=n_regular, trials_per_chip=k,
                        n_trials=(sum(len(v) for v in buckets.values())
                                  + sum(len(v) for v in
                                        group_buckets.values())),
                        groups=[{"width": len(g.devices),
                                 "members": g.members,
                                 "trials": len(g.rows)} for g in groups]
                        or None)
        for g in groups:
            g.start()
        for r in runners:
            r.thread.start()

        chip_seq = [n_chips]  # next chip index for elastic grow
        # (n_chips counts EVERY formed device, group members included,
        # so an elastic grow can never mint an index colliding with a
        # group member's scheduler.preempt key.)

        def spawn_chip() -> _ChipRunner:
            """Elastic grow: one more chip joins the live sweep. A
            spare device is used when visible; otherwise the new runner
            shares a device (thread-level chips — the CPU test
            topology). The runner starts idle and picks up re-packed
            resumes like any survivor."""
            i = chip_seq[0]
            chip_seq[0] += 1
            try:
                devs = local_devices()
            except Exception:
                devs = []
            dev = devs[i % len(devs)] if devs else devices[0]
            service = self.store.create_service(
                ServiceType.TRAIN_WORKER.value, job_id=job_id,
                worker_index=i, devices=[str(dev)])
            self.store.update_service(service["id"],
                                      status=ServiceStatus.RUNNING.value)
            worker = TrainWorker(
                self.store, self.params_store, sub["id"], model_cls, handle,
                job["train_dataset_uri"], job["val_dataset_uri"], budget,
                worker_id=f"{job_id[:8]}-mesh-c{i}", devices=[dev],
                job_created_at=job["created_at"], service_id=service["id"],
                stop_event=stop_event, async_persist=False,
            )
            worker.wal = self._wal
            worker.curve = curve
            r = _ChipRunner(i, dev, worker, k, errors,
                            budget_max=budget_max)
            r.thread.start()
            return r

        self._supervise(job_id, sub["id"], runners, stop_event,
                        elastic=elastic, spawn_chip=spawn_chip)

        # The packed lane has drained (or the sweep was stopped); wait
        # for the sharded groups. Their member-loss probe runs in each
        # group's own poller thread, so the only supervision left here
        # is the liveness lease and the stop signal.
        hb_s = float(os.environ.get("RAFIKI_SUPERVISOR_HEARTBEAT_S", "5"))
        last_beat = time.monotonic()
        for g in groups:
            while not g.done.wait(timeout=0.05):
                if stop_event.is_set():
                    g.abort.set()
                now = time.monotonic()
                if (self._sup_service_id
                        and now - last_beat >= hb_s / 2.0):
                    last_beat = now
                    self.store.update_service(self._sup_service_id,
                                              heartbeat=True)
            g.thread.join(timeout=30.0)

        for r in runners:
            if r.worker._saver is not None:
                r.worker._saver.close()
            self.store.update_service(r.service_id,
                                      status=ServiceStatus.STOPPED.value)

    def _supervise(self, job_id: str, sub_id: str,
                   runners: List[_ChipRunner],
                   stop_event: threading.Event,
                   elastic: Optional[ElasticHandle] = None,
                   spawn_chip=None) -> None:
        """Poll for chip loss (the ``scheduler.preempt`` chaos probe —
        the same site the process scheduler consults, keyed
        ``chip<i>``), re-pack dead chips' trials onto survivors, apply
        elastic grow/shrink requests, and stop every runner once the
        sweep is drained."""
        lost_at: Dict[int, float] = {}
        rr = 0  # round-robin cursor over survivors for re-packed rows
        gen = self._generation
        hb_s = float(os.environ.get("RAFIKI_SUPERVISOR_HEARTBEAT_S", "5"))
        last_beat = time.monotonic()
        # Simulated host topology: with RAFIKI_MESH_CHIPS_PER_HOST=n,
        # chips i//n share a "host"; host 0 also carries the supervisor.
        # The host.loss chaos site kills whole groups at once — host 0
        # via self-directed hook() (supervisor dies with its chips, the
        # resume path takes over), others via decide() + group abort
        # (survivors re-pack: the chip-loss path at host granularity).
        per_host = int(os.environ.get("RAFIKI_MESH_CHIPS_PER_HOST", "0") or 0)
        while True:
            # supervisor.tick: the kill-the-supervisor injection point
            # (SIGKILL of this whole process, chip threads included).
            chaos.hook("supervisor.tick", key=f"g{gen}")
            now = time.monotonic()
            if self._sup_service_id and now - last_beat >= hb_s / 2.0:
                last_beat = now
                self.store.update_service(self._sup_service_id,
                                          heartbeat=True)
            if per_host > 0:
                hosts = sorted({r.index // per_host for r in runners
                                if r.alive()})
                for h in hosts:
                    if h == 0:
                        chaos.hook("host.loss", key=f"g{gen}h0")
                        continue
                    decision = chaos.decide("host.loss", key=f"g{gen}h{h}")
                    if decision is not None and decision.mode in (
                            "kill", "term", "preempt"):
                        victims = [r for r in runners if r.alive()
                                   and r.index // per_host == h]
                        for r in victims:
                            r.abort.set()
                            lost_at[r.index] = time.monotonic()
                        _journal.record("mesh", "host_lost", job_id=job_id,
                                        host=h,
                                        chips=[r.index for r in victims])
                        events.emit("mesh_host_lost", job_id=job_id,
                                    host=h,
                                    chips=[r.index for r in victims])
            if elastic is not None:
                elastic._set_live(sum(1 for r in runners if r.alive()))
                delta = elastic._take()
                if delta > 0 and spawn_chip is not None:
                    for _ in range(delta):
                        nr = spawn_chip()
                        runners.append(nr)
                        telemetry.inc("mesh.chips_scaled_up")
                        _journal.record("mesh", "scale_up", job_id=job_id,
                                        chip=nr.index)
                        events.emit("mesh_chip_added", job_id=job_id,
                                    chip=nr.index,
                                    worker_id=nr.worker.worker_id)
                        elastic.applied.append(
                            {"dir": "up", "chip": nr.index})
                elif delta < 0:
                    # Shrink newest-first, never below one live chip;
                    # the abort unwinds the pack at its next epoch
                    # boundary and the reap below re-packs its rows —
                    # the chip-loss machinery, minus the downtime
                    # charge (a voluntary shrink is not an outage).
                    candidates = sorted(
                        (r for r in runners
                         if r.alive() and not r.abort.is_set()),
                        key=lambda r: -r.index)
                    for r in candidates[:max(0, min(-delta,
                                                    len(candidates) - 1))]:
                        r.scaled_down = True
                        r.abort.set()
                        telemetry.inc("mesh.chips_scaled_down")
                        _journal.record("mesh", "scale_down",
                                        job_id=job_id, chip=r.index)
                        events.emit("mesh_chip_removed", job_id=job_id,
                                    chip=r.index,
                                    worker_id=r.worker.worker_id)
                        elastic.applied.append(
                            {"dir": "down", "chip": r.index})
            for r in runners:
                if not r.alive():
                    continue
                decision = chaos.decide("scheduler.preempt",
                                        key=f"chip{r.index}")
                if decision is not None and decision.mode in (
                        "kill", "term", "preempt"):
                    # Chip loss: the in-flight pack aborts at its next
                    # epoch boundary (checkpoints durable first).
                    r.abort.set()
                    lost_at[r.index] = time.monotonic()

            for r in runners:
                if r.reaped or r.alive():
                    continue
                r.reaped = True
                r.dead = True
                if r.scaled_down:
                    # Voluntary shrink: already journaled as
                    # mesh/scale_down — not a loss, no downtime charge;
                    # its rows still re-pack below like any orphan set.
                    _journal.record("mesh", "scale_down_drained",
                                    job_id=job_id, chip=r.index)
                else:
                    telemetry.inc("mesh.chips_lost")
                    events.emit("mesh_chip_lost", job_id=job_id,
                                chip=r.index, worker_id=r.worker.worker_id)
                    _journal.record("mesh", "chip_lost", job_id=job_id,
                                    chip=r.index)
                orphans = [t["id"] for t in
                           self.store.get_trials_of_sub_train_job(sub_id)
                           if t["status"] == TrialStatus.RUNNING.value
                           and t.get("service_id") == r.service_id]
                survivors = [s for s in runners if s.alive()]
                if not survivors:
                    for tid in orphans:
                        self.store.mark_trial_as_errored(
                            tid, "mesh sweep lost every chip")
                        # Close the journal lineage too: without this
                        # event the trial reads as an orphaned
                        # incarnation in `obs lineage --check` even
                        # though the store knows its fate.
                        events.emit("trial_errored", trial_id=tid,
                                    worker_id=r.worker.worker_id,
                                    error="mesh sweep lost every chip")
                    _journal.record("mesh", "repack_failed", job_id=job_id,
                                    chip=r.index, orphans=orphans)
                    continue
                for tid in orphans:
                    target = survivors[rr % len(survivors)]
                    rr += 1
                    txn = self._wal.intent("pack_assign",
                                           chip=target.index,
                                           trial_ids=[tid], repack=True)
                    # Re-bind BEFORE enqueueing: if the target chip
                    # dies with this resume still queued, the next
                    # reap's orphan query must find the row under the
                    # target's service, not the already-reaped one's.
                    self.store.mark_trial_as_running(
                        tid, service_id=target.service_id,
                        worker_id=target.worker.worker_id)
                    target.tasks.put(("resume", tid))
                    self._wal.commit(txn, "pack_assign")
                _journal.record("mesh", "repack", job_id=job_id,
                                chip=r.index, moved=orphans,
                                survivors=[s.index for s in survivors])
                # Downtime: wall-clock from the loss signal to re-pack,
                # charged to the sweep's mesh entity so the goodput
                # report shows recovery cost (docs/observability.md).
                t_lost = lost_at.get(r.index)
                if t_lost is not None:
                    # lint: disable=RF007 — downtime_s ledger charge, not a span
                    ledger.add("downtime_s", time.monotonic() - t_lost,
                               entity=f"mesh:{job_id}")

            live = [r for r in runners if r.alive()]
            pending_reap = [r for r in runners
                            if not r.alive() and not r.reaped]
            if stop_event.is_set():
                # Abort every live runner so in-flight packs unwind at
                # their next epoch boundary (rows stay RUNNING, same as
                # the chip-loss path) instead of daemon threads training
                # past the join timeout and writing to the store after
                # the STOPPED result is returned.
                for r in live:
                    r.abort.set()
                break
            if not pending_reap and (not live or all(r.idle() for r in live)):
                break
            # SLO tick from the supervision loop: the mesh downtime
            # budget burns here even when no epoch/request path is
            # active to tick it (docs/perf.md).
            from rafiki_tpu.obs.perf import slo as _slo

            _slo.maybe_tick()
            time.sleep(0.02)

        for r in runners:
            if r.alive():
                r.tasks.put(("stop", None))
        for r in runners:
            r.thread.join(timeout=30.0)
