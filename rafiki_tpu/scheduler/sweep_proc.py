"""Sweep-supervisor process entrypoint:
``python -m rafiki_tpu.scheduler.sweep_proc run|resume ...``.

The mesh sweep normally runs in the caller's process. Crash-safety
testing needs it in a process OF ITS OWN, so a chaos fault (the
``supervisor.tick`` kill site, a whole-host loss) can SIGKILL the
supervisor without taking the test harness down with it — and so
``resume_sweep`` can then prove a genuinely fresh process (no shared
memory, only the MetaStore + sweep WAL + journals) adopts the job.
The chaos scenarios (chaos/scenarios.py) and scripts/resume_smoke.py
drive sweeps through this module; it is equally usable as a manual
supervisor launcher.

Modes::

    run     --db X --params Y --job J [--chips N] [--trials-per-chip K]
            [--advisor KIND] [--advisor-kwargs JSON]
    resume  --db X --params Y --job J [--chips N] [--trials-per-chip K]
            [--stale-after-s S]

Chaos/observability propagation is by environment, same contract as
every other subprocess in the tree: ``RAFIKI_CHAOS`` self-installs at
import, ``RAFIKI_LOG_DIR`` points the journal, ``RAFIKI_EVENTS_DIR``
the event sink. Exit codes: 0 = job COMPLETED, 2 = any other terminal
status, 1 = crash (including a WAL reconcile refusal on resume).

The final line on stdout is a JSON summary (status, trial count, and
for resume the adopt/salvage accounting) — drivers parse that instead
of scraping the store again.
"""

from __future__ import annotations

import json
import os
import sys


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    import argparse

    ap = argparse.ArgumentParser(prog="rafiki_tpu.scheduler.sweep_proc")
    ap.add_argument("mode", choices=("run", "resume"))
    ap.add_argument("--db", required=True)
    ap.add_argument("--params", required=True)
    ap.add_argument("--job", required=True)
    ap.add_argument("--chips", type=int, default=None)
    ap.add_argument("--trials-per-chip", type=int, default=None)
    ap.add_argument("--advisor", default="gp")
    ap.add_argument("--advisor-kwargs", default=None,
                    help="JSON dict of engine kwargs, e.g. "
                         '\'{"n_initial": 4}\'')
    ap.add_argument("--stale-after-s", type=float, default=None)
    args = ap.parse_args(argv)

    # Platform pinning must precede any jax import (analysis RF001);
    # a CPU run needs enough virtual devices BEFORE the backend
    # initializes, or a --chips 2 sweep silently degrades to one chip.
    from rafiki_tpu.utils.backend import ensure_host_device_count, honor_env_platform

    ensure_host_device_count(max(8, int(args.chips or 0)))
    honor_env_platform()

    from rafiki_tpu.obs import journal as journal_mod
    from rafiki_tpu.store import MetaStore, ParamsStore
    from rafiki_tpu.utils.events import configure_from_env as _events_env

    journal_mod.configure_from_env(role=f"sweep-{args.mode}")
    _events_env()
    store = MetaStore(args.db)
    params = ParamsStore(args.params)

    if args.mode == "run":
        from rafiki_tpu.scheduler.mesh import MeshSweepScheduler

        kwargs = json.loads(args.advisor_kwargs) if args.advisor_kwargs \
            else None
        sched = MeshSweepScheduler(store, params)
        result = sched.run_sweep(
            args.job, chips=args.chips,
            trials_per_chip=int(args.trials_per_chip or 2),
            advisor_kind=args.advisor, advisor_kwargs=kwargs)
        out = {"mode": "run", "job_id": args.job, "status": result.status,
               "n_trials": len(result.trials),
               "errors": result.errors}
        print(json.dumps(out))
        return 0 if result.status == "COMPLETED" else 2

    from rafiki_tpu.scheduler.recovery import resume_sweep

    summary = resume_sweep(
        store, params, args.job, chips=args.chips,
        trials_per_chip=args.trials_per_chip,
        stale_after_s=args.stale_after_s)
    job = store.get_train_job(args.job)
    summary["status"] = None if job is None else job["status"]
    print(json.dumps({"mode": "resume", **summary}, default=str))
    return 0 if summary["status"] == "COMPLETED" else 2


if __name__ == "__main__":
    sys.exit(main())
