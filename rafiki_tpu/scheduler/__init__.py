"""Trial schedulers: one trial per chip.

Reference parity: the reference's "scheduler" is Docker Swarm +
ServicesManager (one train-worker container per GPU — SURVEY.md §2).
TPU-native replacements:

  * LocalScheduler — threads in one process, each worker pinned to a
    device set via ``jax.default_device`` / a dp mesh. Zero setup, used
    by tests and single-host runs; workers share one XLA runtime.
  * ProcessScheduler — one subprocess per worker with
    ``TPU_VISIBLE_CHIPS=<chip>`` (CPU fake: per-process fake chips):
    fully isolated XLA runtimes and compilation caches, the robust
    production shape (SURVEY.md §7 "per-chip trial isolation").
  * MeshSweepScheduler — the whole mesh as ONE sweep: k packed trials
    per chip × N chips from a single ``propose_batch(N*k)``, with
    elastic re-packing on chip loss, collective-init retry and
    bounded-grace degradation to single-chip mode
    (docs/mesh_sweep.md).
"""

from rafiki_tpu.scheduler.local import LocalScheduler, TrainJobResult
from rafiki_tpu.scheduler.mesh import MeshSweepScheduler
from rafiki_tpu.scheduler.process import ProcessScheduler, worker_device_env

__all__ = ["LocalScheduler", "MeshSweepScheduler", "ProcessScheduler",
           "TrainJobResult", "worker_device_env"]
