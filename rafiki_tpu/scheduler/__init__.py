"""Trial schedulers: one trial per chip.

Reference parity: the reference's "scheduler" is Docker Swarm +
ServicesManager (one train-worker container per GPU — SURVEY.md §2).
TPU-native replacements:

  * LocalScheduler — threads in one process, each worker pinned to a
    device set via ``jax.default_device`` / a dp mesh. Zero setup, used
    by tests and single-host runs; workers share one XLA runtime.
  * ProcessScheduler — one subprocess per worker with
    ``JAX_VISIBLE_DEVICES=<chip>``: fully isolated XLA runtimes and
    compilation caches, the robust production shape (SURVEY.md §7
    "per-chip trial isolation").
"""

from rafiki_tpu.scheduler.local import LocalScheduler, TrainJobResult

__all__ = ["LocalScheduler", "TrainJobResult"]
