"""Process-per-chip scheduler: one OS process per worker, one (or k)
chips per process.

This is the production scheduler shape (SURVEY.md §7 "hard parts":
per-chip trial isolation). JAX wants one runtime per process —
concurrent trials in one process contend on compilation locks and
device memory. Spawning each worker as a subprocess whose environment
exposes only its own chip(s) gives the same isolation the reference
got from one-GPU-per-container (CUDA_VISIBLE_DEVICES), with none of
the container overhead:

  * TPU: ``TPU_VISIBLE_CHIPS=<i>`` (+ per-process bounds) pins a
    process to chip i; ``XLA_PYTHON_CLIENT_PREALLOCATE=false`` keeps
    N runtimes from fighting over HBM at startup.
  * CPU (tests / fake pod): each subprocess gets its own
    ``--xla_force_host_platform_device_count=k`` fake chips.

Coordination is exactly the reference's: the meta store (shared
sqlite, atomic trial claiming) is the source of truth and the advisor
is shared over loopback HTTP (reference: advisor container + REST).
"""

from __future__ import annotations

import contextlib
import os
import secrets as _secrets
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from rafiki_tpu import chaos
from rafiki_tpu.obs import context as trace_context
from rafiki_tpu.obs.journal import journal as _journal
from rafiki_tpu.advisor import AdvisorService
from rafiki_tpu.advisor.app import AdvisorApp
from rafiki_tpu.constants import ServiceStatus, ServiceType, TrainJobStatus, TrialStatus
from rafiki_tpu.model.base import load_model_class
from rafiki_tpu.scheduler.local import TrainJobResult
from rafiki_tpu.store import MetaStore, ParamsStore
from rafiki_tpu.utils.events import events


def _free_ports(n: int) -> List[int]:
    """n distinct free loopback ports: all probe sockets are held open
    until every port is chosen, so the OS cannot hand the same port to
    two groups (the residual race against unrelated processes between
    close and the coordinator's bind is inherent and accepted)."""
    import socket

    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def worker_device_env(platform: str, worker_index: int,
                      devices_per_trial: int = 1) -> Dict[str, str]:
    """Env vars that pin a worker subprocess to its own device set.

    Anything that isn't the host platform gets the TPU chip-pinning
    env: PJRT plugins register TPUs under other names (this image:
    "axon"), and the old ``== "tpu"`` gate sent those workers down the
    CPU branch — forcing JAX_PLATFORMS=cpu on a real TPU run.
    """
    if platform != "cpu":
        first = worker_index * devices_per_trial
        chips = ",".join(str(first + j) for j in range(devices_per_trial))
        return {
            "TPU_VISIBLE_CHIPS": chips,
            "TPU_CHIPS_PER_PROCESS_BOUNDS": f"1,{devices_per_trial},1",
            "TPU_PROCESS_BOUNDS": "1,1,1",
            "ALLOW_MULTIPLE_LIBTPU_LOAD": "1",
        }
    # cpu: every subprocess fakes its own `devices_per_trial` chips
    from rafiki_tpu.utils.backend import host_device_count_flag

    return {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": host_device_count_flag(devices_per_trial),
    }


class _WorkerGroup:
    """One worker slot's process set (leader + multihost followers)
    plus its restart bookkeeping. procs[0] is always the leader."""

    def __init__(self, index: int):
        self.index = index
        self.procs: List[subprocess.Popen] = []
        self.out_files: list = []
        self.service: Optional[dict] = None
        self.leader_worker_id = ""
        self.restarts = 0
        self.respawn_at: Optional[float] = None  # monotonic; None = live
        # Monotonic time a follower was first seen exited rc=0 while the
        # leader still ran; None while the group is whole. See state().
        self.partial_exit_at: Optional[float] = None
        # Service rows of every dead predecessor in this slot: the
        # replacement must sweep them ALL — a restart that crashed
        # before adopting leaves the orphan bound to an older corpse.
        self.dead_services: List[str] = []

    # A follower that exits rc=0 mid-trial is just as gone as one that
    # crashed — the leader's next collective will never complete — but
    # a zero rc can also be the harmless tail of a clean group
    # shutdown racing the poll. The grace window separates the two:
    # long enough for the leader's own clean exit to land, far shorter
    # than the collective transport timeout (minutes) that used to be
    # the only thing ending the wedge (round-4 ADVICE d).
    FOLLOWER_EXIT_GRACE_S = 15.0

    def state(self) -> str:
        """'running' | 'ok' | 'failed'. A member dead non-zero while the
        leader hasn't exited cleanly fails the whole group immediately —
        the survivors are inside (or headed into) collectives their dead
        peer will never join, and waiting for the transport timeout to
        tell us so would wedge the job for minutes. A member dead rc=0
        while the leader lives fails the group too, after a bounded
        grace window (see FOLLOWER_EXIT_GRACE_S)."""
        rcs = [p.poll() for p in self.procs]
        if any(rc is None for rc in rcs):
            if any(rc not in (0, None) for rc in rcs) and rcs[0] != 0:
                return "failed"
            if rcs[0] is None and any(rc == 0 for rc in rcs[1:]):
                now = time.monotonic()
                if self.partial_exit_at is None:
                    self.partial_exit_at = now
                elif now - self.partial_exit_at > self._follower_exit_grace_s():
                    return "failed"
            else:
                self.partial_exit_at = None
            return "running"
        self.partial_exit_at = None
        return "ok" if rcs[0] == 0 else "failed"

    def _follower_exit_grace_s(self) -> float:
        return float(os.environ.get("RAFIKI_FOLLOWER_EXIT_GRACE_S",
                                    self.FOLLOWER_EXIT_GRACE_S))

    def terminate(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 10
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    def collect(self, blame=lambda k, rc: rc != 0) -> List[str]:
        """Reap every process and read its output; returns descriptions
        of members the ``blame(member_index, rc)`` predicate selects."""
        msgs = []
        for k, (p, f) in enumerate(zip(self.procs, self.out_files)):
            try:
                rc = p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
                rc = p.wait()
            f.seek(0)
            out = f.read()
            f.close()
            if blame(k, rc):
                label = (f"worker {self.index}" if k == 0
                         else f"worker {self.index} follower {k}")
                msgs.append(f"{label} rc={rc}: {out[-2000:]}")
        self.procs, self.out_files = [], []
        return msgs

    def shutdown(self) -> List[str]:
        """Kill survivors, reap everything; returns the ORIGINAL
        failures only — members this teardown killed are not blamed."""
        original = [p.poll() for p in self.procs]
        self.terminate()
        return self.collect(blame=lambda k, rc: original[k] not in (None, 0))


class ProcessScheduler:
    """Same run_train_job contract as LocalScheduler, subprocess workers."""

    def __init__(self, store: MetaStore, params_store: ParamsStore,
                 db_path: Optional[str] = None,
                 params_dir: Optional[str] = None,
                 advisor_service: Optional[AdvisorService] = None):
        self.store = store
        self.params_store = params_store
        # Subprocesses need filesystem paths, not live objects.
        self.db_path = str(db_path if db_path is not None else store.path)
        self.params_dir = str(params_dir if params_dir is not None
                              else params_store.directory)
        self.advisors = advisor_service or AdvisorService()

    # -- advisor server ------------------------------------------------------

    def _start_advisor_server(self):
        from werkzeug.serving import make_server

        secret = _secrets.token_hex(16)
        app = AdvisorApp(self.advisors, secret=secret)
        server = make_server("127.0.0.1", 0, app, threaded=True)
        thread = threading.Thread(target=server.serve_forever,
                                  name="advisor-http", daemon=True)
        thread.start()
        return server, thread, secret, f"http://127.0.0.1:{server.server_port}"

    # -- the job -------------------------------------------------------------

    def run_train_job(
        self,
        job_id: str,
        n_workers: int = 1,
        devices_per_trial: int = 1,
        advisor_kind: str = "gp",
        platform: Optional[str] = None,
        stop_event: Optional[threading.Event] = None,
        poll_s: float = 0.5,
        multihost_processes: int = 1,
    ) -> TrainJobResult:
        t0 = time.monotonic()
        job = self.store.get_train_job(job_id)
        if job is None:
            raise KeyError(f"No train job {job_id!r}")
        # Job-level trace: scheduler-side records (spawns, deaths,
        # restarts) stitch under one id; each trial still mints its own
        # trace (worker/train.py) and links back via trial_id fields.
        _trace_scope = contextlib.ExitStack()
        _trace_scope.enter_context(
            trace_context.trace(trace_context.new_trace_id()))
        self.store.update_train_job_status(job_id, TrainJobStatus.RUNNING.value)
        events.emit("train_job_started", job_id=job_id, app=job["app"],
                    budget=job["budget"], scheduler="process")
        stop_event = stop_event or threading.Event()
        if platform is None:
            import jax

            platform = jax.default_backend()

        budget = dict(job["budget"])
        chip_budget = budget.get("CHIP_COUNT") or budget.get("GPU_COUNT")
        if chip_budget:
            # Each worker group consumes devices_per_trial chips on EACH
            # of its multihost processes.
            per_group = devices_per_trial * max(1, multihost_processes)
            n_workers = min(n_workers, max(1, int(chip_budget) // per_group))

        server, thread, secret, advisor_url = self._start_advisor_server()
        errors: List[str] = []
        try:
            subs = self.store.get_sub_train_jobs(job_id)
            if not subs:
                raise ValueError(f"Train job {job_id} has no sub jobs")
            for sub in subs:
                if stop_event.is_set():
                    self.store.update_sub_train_job(
                        sub["id"], status=TrainJobStatus.STOPPED.value)
                    continue
                self._run_sub_job(sub, job, n_workers, devices_per_trial,
                                  advisor_kind, platform, advisor_url, secret,
                                  stop_event, poll_s, errors,
                                  multihost_processes=multihost_processes)
        except BaseException:
            # Never leave the job stuck in RUNNING: mark terminal, then
            # re-raise for the caller.
            self.store.update_train_job_status(job_id,
                                               TrainJobStatus.ERRORED.value)
            events.emit("train_job_finished", job_id=job_id,
                        status=TrainJobStatus.ERRORED.value,
                        # lint: disable=RF007 — job duration emitted into the event itself
                        duration_s=round(time.monotonic() - t0, 3))
            raise
        finally:
            server.shutdown()
            thread.join(timeout=5)
            _trace_scope.close()

        subs_after = self.store.get_sub_train_jobs(job_id)
        if stop_event.is_set():
            status = TrainJobStatus.STOPPED.value
        elif subs_after and all(s["status"] == TrainJobStatus.ERRORED.value
                                for s in subs_after):
            status = TrainJobStatus.ERRORED.value
        else:
            status = TrainJobStatus.COMPLETED.value
        self.store.update_train_job_status(job_id, status)
        # lint: disable=RF007 — job duration emitted into the event/result below
        dur_s = time.monotonic() - t0
        events.emit("train_job_finished", job_id=job_id, status=status,
                    duration_s=round(dur_s, 3))
        return TrainJobResult(
            job_id=job_id, status=status,
            trials=self.store.get_trials_of_train_job(job_id),
            best_trials=self.store.get_best_trials_of_train_job(job_id, limit=2),
            duration_s=dur_s, errors=errors)

    def _spawn_group(self, g: _WorkerGroup, ctx: dict,
                     port: Optional[int] = None) -> None:
        """(Re)spawn one worker group: a fresh service row, a fresh
        leader worker id (suffixed -r<attempt> on restarts), and — when
        this is a restart — the adopt hook env pointing at the dead
        predecessor's service row so the new leader resumes its
        orphaned trial."""
        import tempfile

        job, sub = ctx["job"], ctx["sub"]
        platform, mh = ctx["platform"], ctx["multihost"]
        g.partial_exit_at = None  # fresh process set, fresh grace
        service = self.store.create_service(
            ServiceType.TRAIN_WORKER.value, job_id=job["id"],
            worker_index=g.index, devices=[f"{platform}:{g.index}"])
        g.service = service
        # Multi-host dp group: N processes per worker — process 0 leads
        # (control plane), 1..N-1 follow (compute mirror,
        # worker/follower.py) — coordinated via jax.distributed on a
        # per-group loopback port (production pods use the pod's
        # coordinator host; same env contract).
        coordinator = f"127.0.0.1:{port}" if mh > 1 else None
        leader_worker_id = f"{job['id'][:8]}-p{g.index}" + (
            f"-r{g.restarts}" if g.restarts else "")
        g.leader_worker_id = leader_worker_id
        for j in range(mh):
            env = dict(os.environ)
            if platform == "cpu" or mh <= 1:
                env.update(worker_device_env(
                    platform, g.index * mh + j, ctx["devices_per_trial"]))
            # else: a real multi-host TPU group must keep the pod
            # runtime's own topology env (TPU_WORKER_ID etc.) — a
            # flat per-process chip index + single-process bounds
            # would contradict the jax.distributed cluster.
            env.update({
                "RAFIKI_WORKER_DB": self.db_path,
                "RAFIKI_WORKER_PARAMS_DIR": self.params_dir,
                "RAFIKI_WORKER_SUB_JOB_ID": sub["id"],
                "RAFIKI_WORKER_ID": leader_worker_id + (
                    f".{j}" if mh > 1 and j > 0 else ""),
                "RAFIKI_WORKER_SERVICE_ID": service["id"] if j == 0 else "",
                "RAFIKI_WORKER_ADVISOR_URL": ctx["advisor_url"],
                "RAFIKI_WORKER_ADVISOR_ID": ctx["advisor_id"],
                "RAFIKI_WORKER_ADVISOR_SECRET": ctx["secret"],
            })
            if j == 0 and g.dead_services:
                env["RAFIKI_WORKER_ADOPT_SERVICE_ID"] = ",".join(g.dead_services)
            if coordinator is not None:
                env.update({
                    "RAFIKI_COORDINATOR_ADDRESS": coordinator,
                    "RAFIKI_NUM_PROCESSES": str(mh),
                    "RAFIKI_PROCESS_ID": str(j),
                    "RAFIKI_LEADER_WORKER_ID": leader_worker_id,
                    "RAFIKI_LEADER_SERVICE_ID": service["id"],
                })
            if events.path is not None:  # subprocess shares the event sink
                env["RAFIKI_EVENTS_DIR"] = str(events.path.parent)
            # Observability propagation: the child journals into the
            # same log dir and adopts this job's trace as its process
            # default — the spawn edge of cross-process stitching.
            if _journal.configured:
                env["RAFIKI_LOG_DIR"] = str(_journal.log_dir)
            _tid = trace_context.current_trace_id()
            if _tid:
                env["RAFIKI_TRACE_ID"] = _tid
            # Worker output goes to a temp file, not a pipe: a full
            # pipe buffer would block the worker's writes and
            # deadlock the supervise loop.
            out_f = tempfile.TemporaryFile(mode="w+t")
            g.out_files.append(out_f)
            g.procs.append(subprocess.Popen(
                [sys.executable, "-m", "rafiki_tpu.worker.main"],
                env=env, stdout=out_f, stderr=subprocess.STDOUT, text=True))
        self.store.update_service(service["id"],
                                  status=ServiceStatus.RUNNING.value)

    @staticmethod
    def _maybe_preempt(g: _WorkerGroup) -> None:
        """Enact a ``scheduler.preempt`` fault on a running group's
        leader: ``term`` = SIGTERM, ``kill`` = SIGKILL, ``preempt`` =
        SIGTERM now with a SIGKILL follow-up after the fault's
        ``delay`` grace — the maintenance-eviction shape (a real
        preemption notice gives the process a bounded window to die
        cleanly before the host yanks it)."""
        fault = chaos.decide("scheduler.preempt", key=f"w{g.index}")
        if fault is None or not g.procs:
            return
        leader = g.procs[0]
        events.emit("chaos_preempt", worker_index=g.index, mode=fault.mode)
        if fault.mode == "kill":
            leader.kill()
        elif fault.mode in ("term", "preempt"):
            leader.terminate()
            if fault.mode == "preempt":
                def _kill_after(p=leader, grace=fault.delay_s):
                    try:
                        p.wait(timeout=grace)
                    except subprocess.TimeoutExpired:
                        p.kill()

                threading.Thread(target=_kill_after, daemon=True,
                                 name=f"chaos-preempt-w{g.index}").start()

    def _run_sub_job(self, sub: dict, job: dict, n_workers: int,
                     devices_per_trial: int, advisor_kind: str, platform: str,
                     advisor_url: str, secret: str,
                     stop_event: threading.Event, poll_s: float,
                     errors: List[str], multihost_processes: int = 1) -> None:
        sub_errors: List[str] = []  # this sub job's failures only
        model_row = self.store.get_model(sub["model_id"])
        try:  # validate the template before spending processes on it
            model_cls = load_model_class(model_row["model_file"],
                                         model_row["model_class"])
        except Exception as e:
            self.store.update_sub_train_job(sub["id"],
                                            status=TrainJobStatus.ERRORED.value)
            errors.append(f"model {model_row['name']}: {e}")
            return
        advisor_id = self.advisors.create_advisor(
            model_cls.get_knob_config(),
            kind=advisor_kind, advisor_id=sub.get("advisor_id") or None)
        self.store.update_sub_train_job(sub["id"], advisor_id=advisor_id,
                                        status=TrainJobStatus.RUNNING.value)

        ctx = dict(sub=sub, job=job, platform=platform,
                   devices_per_trial=devices_per_trial,
                   multihost=multihost_processes, advisor_url=advisor_url,
                   advisor_id=advisor_id, secret=secret)
        ports = (_free_ports(n_workers) if multihost_processes > 1 else
                 [None] * n_workers)
        groups = []
        for i in range(n_workers):
            g = _WorkerGroup(i)
            self._spawn_group(g, ctx, port=ports[i])
            groups.append(g)

        # Supervise with in-job elasticity (SURVEY.md §5: the analog of
        # the reference's Swarm restart policy, which resurrected
        # crashed worker containers). A group any member of which dies
        # non-zero is torn down AT ONCE — survivors are killed rather
        # than left to stall until the collective transport timeout —
        # and respawned with exponential backoff, up to max_restarts
        # per group; the replacement leader CAS-adopts the dead
        # worker's orphaned RUNNING trial (worker/main.py adopt hook),
        # so the job still completes its full trial budget.
        max_restarts = int(os.environ.get("RAFIKI_WORKER_MAX_RESTARTS", "2"))
        backoff0 = float(os.environ.get("RAFIKI_WORKER_RESTART_BACKOFF_S", "0.5"))
        abandoned_services: set = set()  # corpses with no replacement coming
        while groups:
            if stop_event.is_set():
                for g in groups:
                    g.terminate()
                stopped_services = set()
                for g in groups:
                    g.collect(blame=lambda k, rc: False)
                    if g.respawn_at is None:
                        # Live group: its service row goes STOPPED. A
                        # group caught in its backoff window keeps the
                        # ERRORED corpse row; either way the group's
                        # orphaned trials are terminated below — no
                        # replacement is coming, and leaving one RUNNING
                        # would hand a trial of an explicitly-stopped
                        # job to the periodic recovery sweep.
                        self.store.update_service(
                            g.service["id"],
                            status=ServiceStatus.STOPPED.value)
                    stopped_services.add(g.service["id"])
                    stopped_services.update(g.dead_services)
                for t in self.store.get_trials_of_sub_train_job(sub["id"]):
                    if (t["status"] == TrialStatus.RUNNING.value
                            and t.get("service_id") in stopped_services):
                        self.store.mark_trial_as_terminated(t["id"])
                groups.clear()
                break
            now = time.monotonic()
            for g in list(groups):
                if g.respawn_at is not None:  # waiting out its backoff
                    if now < g.respawn_at:
                        continue
                    g.respawn_at = None
                    port = (_free_ports(1)[0]
                            if multihost_processes > 1 else None)
                    self._spawn_group(g, ctx, port=port)
                    events.emit("worker_restarted", job_id=job["id"],
                                worker_index=g.index, attempt=g.restarts,
                                adopt_service_ids=list(g.dead_services))
                    continue
                state = g.state()
                if state == "running":
                    # Chaos: simulated preemption/eviction of a live
                    # group, keyed w<index>, one hit per supervise poll.
                    # The normal failed→restart→adopt machinery below is
                    # exactly what the fault must exercise.
                    self._maybe_preempt(g)
                    continue
                if state == "ok":
                    # Non-zero follower exits AFTER a clean leader exit
                    # (budget drained) are shutdown noise, not job
                    # failures — recorded as events only.
                    for msg in g.collect():
                        events.emit("worker_exit_noise", job_id=job["id"],
                                    worker_index=g.index, detail=msg[:500])
                    self.store.update_service(
                        g.service["id"], status=ServiceStatus.STOPPED.value)
                    groups.remove(g)
                    continue
                # state == "failed": tear down, then restart or give up.
                failures = g.shutdown()
                if not failures and g.partial_exit_at is not None:
                    # rc=0 exits are never blamed by shutdown(), so the
                    # follower-gone-clean wedge needs its own message.
                    failures = [
                        f"worker {g.index}: follower exited rc=0 mid-trial "
                        f"while the leader lived; group failed after "
                        f"{g._follower_exit_grace_s():.0f}s grace"]
                g.partial_exit_at = None
                self.store.update_service(
                    g.service["id"], status=ServiceStatus.ERRORED.value)
                # Flight record on the dead child's behalf: a SIGKILLed
                # worker gets no in-process hook, so the scheduler — the
                # only survivor that saw the death — dumps what it knows.
                from rafiki_tpu.obs import recorder

                recorder.dump(
                    f"worker_died:{g.leader_worker_id}",
                    extra={"worker_index": g.index,
                           "service_id": g.service["id"],
                           "restarts": g.restarts,
                           "detail": (failures[0][:500] if failures else "")})
                if g.restarts < max_restarts:
                    g.restarts += 1
                    g.dead_services.append(g.service["id"])
                    backoff_s = backoff0 * (2 ** (g.restarts - 1))
                    g.respawn_at = now + backoff_s
                    # The death→respawn gap is capacity the job paid for
                    # and didn't use: charge it to the goodput ledger.
                    from rafiki_tpu.obs.ledger import ledger

                    ledger.add("downtime_s", backoff_s,
                               entity=f"job:{job['id']}")
                    events.emit("worker_died", job_id=job["id"],
                                worker_index=g.index,
                                restart_attempt=g.restarts,
                                max_restarts=max_restarts,
                                detail=(failures[0][:500] if failures else ""))
                else:
                    sub_errors.extend(failures)
                    events.emit("worker_failed_permanently", job_id=job["id"],
                                worker_index=g.index, restarts=g.restarts)
                    abandoned_services.update(g.dead_services)
                    abandoned_services.add(g.service["id"])
                    groups.remove(g)
            if groups:
                time.sleep(poll_s)
        if abandoned_services:
            # No replacement is coming for these corpses: their orphaned
            # RUNNING trials would otherwise hang the sub-job status in
            # limbo (and a later recovery sweep would re-run a trial
            # whose worker slot provably cannot stay alive).
            for t in self.store.get_trials_of_sub_train_job(sub["id"]):
                if (t["status"] == TrialStatus.RUNNING.value
                        and t.get("service_id") in abandoned_services):
                    self.store.mark_trial_as_errored(
                        t["id"], "worker died; restarts exhausted")
        errors.extend(sub_errors)

        trials = self.store.get_trials_of_sub_train_job(sub["id"])
        if stop_event.is_set():
            sub_status = TrainJobStatus.STOPPED.value
        elif trials and all(t["status"] == TrialStatus.ERRORED.value for t in trials):
            sub_status = TrainJobStatus.ERRORED.value
        elif not trials and sub_errors:  # only this sub job's failures count
            sub_status = TrainJobStatus.ERRORED.value
        else:
            sub_status = TrainJobStatus.COMPLETED.value
        self.store.update_sub_train_job(sub["id"], status=sub_status)
        self.advisors.delete_advisor(advisor_id)
