"""Process-per-chip scheduler: one OS process per worker, one (or k)
chips per process.

This is the production scheduler shape (SURVEY.md §7 "hard parts":
per-chip trial isolation). JAX wants one runtime per process —
concurrent trials in one process contend on compilation locks and
device memory. Spawning each worker as a subprocess whose environment
exposes only its own chip(s) gives the same isolation the reference
got from one-GPU-per-container (CUDA_VISIBLE_DEVICES), with none of
the container overhead:

  * TPU: ``TPU_VISIBLE_CHIPS=<i>`` (+ per-process bounds) pins a
    process to chip i; ``XLA_PYTHON_CLIENT_PREALLOCATE=false`` keeps
    N runtimes from fighting over HBM at startup.
  * CPU (tests / fake pod): each subprocess gets its own
    ``--xla_force_host_platform_device_count=k`` fake chips.

Coordination is exactly the reference's: the meta store (shared
sqlite, atomic trial claiming) is the source of truth and the advisor
is shared over loopback HTTP (reference: advisor container + REST).
"""

from __future__ import annotations

import os
import secrets as _secrets
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from rafiki_tpu.advisor import AdvisorService
from rafiki_tpu.advisor.app import AdvisorApp
from rafiki_tpu.constants import ServiceStatus, ServiceType, TrainJobStatus, TrialStatus
from rafiki_tpu.model.base import load_model_class
from rafiki_tpu.scheduler.local import TrainJobResult
from rafiki_tpu.store import MetaStore, ParamsStore
from rafiki_tpu.utils.events import events


def _free_ports(n: int) -> List[int]:
    """n distinct free loopback ports: all probe sockets are held open
    until every port is chosen, so the OS cannot hand the same port to
    two groups (the residual race against unrelated processes between
    close and the coordinator's bind is inherent and accepted)."""
    import socket

    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def worker_device_env(platform: str, worker_index: int,
                      devices_per_trial: int = 1) -> Dict[str, str]:
    """Env vars that pin a worker subprocess to its own device set."""
    if platform == "tpu":
        first = worker_index * devices_per_trial
        chips = ",".join(str(first + j) for j in range(devices_per_trial))
        return {
            "TPU_VISIBLE_CHIPS": chips,
            "TPU_CHIPS_PER_PROCESS_BOUNDS": f"1,{devices_per_trial},1",
            "TPU_PROCESS_BOUNDS": "1,1,1",
            "ALLOW_MULTIPLE_LIBTPU_LOAD": "1",
        }
    # cpu: every subprocess fakes its own `devices_per_trial` chips
    from rafiki_tpu.utils.backend import host_device_count_flag

    return {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": host_device_count_flag(devices_per_trial),
    }


class ProcessScheduler:
    """Same run_train_job contract as LocalScheduler, subprocess workers."""

    def __init__(self, store: MetaStore, params_store: ParamsStore,
                 db_path: Optional[str] = None,
                 params_dir: Optional[str] = None,
                 advisor_service: Optional[AdvisorService] = None):
        self.store = store
        self.params_store = params_store
        # Subprocesses need filesystem paths, not live objects.
        self.db_path = str(db_path if db_path is not None else store.path)
        self.params_dir = str(params_dir if params_dir is not None
                              else params_store.directory)
        self.advisors = advisor_service or AdvisorService()

    # -- advisor server ------------------------------------------------------

    def _start_advisor_server(self):
        from werkzeug.serving import make_server

        secret = _secrets.token_hex(16)
        app = AdvisorApp(self.advisors, secret=secret)
        server = make_server("127.0.0.1", 0, app, threaded=True)
        thread = threading.Thread(target=server.serve_forever,
                                  name="advisor-http", daemon=True)
        thread.start()
        return server, thread, secret, f"http://127.0.0.1:{server.server_port}"

    # -- the job -------------------------------------------------------------

    def run_train_job(
        self,
        job_id: str,
        n_workers: int = 1,
        devices_per_trial: int = 1,
        advisor_kind: str = "gp",
        platform: Optional[str] = None,
        stop_event: Optional[threading.Event] = None,
        poll_s: float = 0.5,
        multihost_processes: int = 1,
    ) -> TrainJobResult:
        t0 = time.time()
        job = self.store.get_train_job(job_id)
        if job is None:
            raise KeyError(f"No train job {job_id!r}")
        self.store.update_train_job_status(job_id, TrainJobStatus.RUNNING.value)
        events.emit("train_job_started", job_id=job_id, app=job["app"],
                    budget=job["budget"], scheduler="process")
        stop_event = stop_event or threading.Event()
        if platform is None:
            import jax

            platform = jax.default_backend()

        budget = dict(job["budget"])
        chip_budget = budget.get("CHIP_COUNT") or budget.get("GPU_COUNT")
        if chip_budget:
            # Each worker group consumes devices_per_trial chips on EACH
            # of its multihost processes.
            per_group = devices_per_trial * max(1, multihost_processes)
            n_workers = min(n_workers, max(1, int(chip_budget) // per_group))

        server, thread, secret, advisor_url = self._start_advisor_server()
        errors: List[str] = []
        try:
            subs = self.store.get_sub_train_jobs(job_id)
            if not subs:
                raise ValueError(f"Train job {job_id} has no sub jobs")
            for sub in subs:
                if stop_event.is_set():
                    self.store.update_sub_train_job(
                        sub["id"], status=TrainJobStatus.STOPPED.value)
                    continue
                self._run_sub_job(sub, job, n_workers, devices_per_trial,
                                  advisor_kind, platform, advisor_url, secret,
                                  stop_event, poll_s, errors,
                                  multihost_processes=multihost_processes)
        except BaseException:
            # Never leave the job stuck in RUNNING: mark terminal, then
            # re-raise for the caller.
            self.store.update_train_job_status(job_id,
                                               TrainJobStatus.ERRORED.value)
            events.emit("train_job_finished", job_id=job_id,
                        status=TrainJobStatus.ERRORED.value,
                        duration_s=round(time.time() - t0, 3))
            raise
        finally:
            server.shutdown()
            thread.join(timeout=5)

        subs_after = self.store.get_sub_train_jobs(job_id)
        if stop_event.is_set():
            status = TrainJobStatus.STOPPED.value
        elif subs_after and all(s["status"] == TrainJobStatus.ERRORED.value
                                for s in subs_after):
            status = TrainJobStatus.ERRORED.value
        else:
            status = TrainJobStatus.COMPLETED.value
        self.store.update_train_job_status(job_id, status)
        events.emit("train_job_finished", job_id=job_id, status=status,
                    duration_s=round(time.time() - t0, 3))
        return TrainJobResult(
            job_id=job_id, status=status,
            trials=self.store.get_trials_of_train_job(job_id),
            best_trials=self.store.get_best_trials_of_train_job(job_id, limit=2),
            duration_s=time.time() - t0, errors=errors)

    def _run_sub_job(self, sub: dict, job: dict, n_workers: int,
                     devices_per_trial: int, advisor_kind: str, platform: str,
                     advisor_url: str, secret: str,
                     stop_event: threading.Event, poll_s: float,
                     errors: List[str], multihost_processes: int = 1) -> None:
        sub_errors: List[str] = []  # this sub job's failures only
        model_row = self.store.get_model(sub["model_id"])
        try:  # validate the template before spending processes on it
            model_cls = load_model_class(model_row["model_file"],
                                         model_row["model_class"])
        except Exception as e:
            self.store.update_sub_train_job(sub["id"],
                                            status=TrainJobStatus.ERRORED.value)
            errors.append(f"model {model_row['name']}: {e}")
            return
        advisor_id = self.advisors.create_advisor(
            model_cls.get_knob_config(),
            kind=advisor_kind, advisor_id=sub.get("advisor_id") or None)
        self.store.update_sub_train_job(sub["id"], advisor_id=advisor_id,
                                        status=TrainJobStatus.RUNNING.value)

        import tempfile

        procs: List[subprocess.Popen] = []
        proc_services: List[Optional[dict]] = []  # leader's service row or None
        out_files = []
        ports = (_free_ports(n_workers) if multihost_processes > 1 else
                 [None] * n_workers)
        for i in range(n_workers):
            service = self.store.create_service(
                ServiceType.TRAIN_WORKER.value, job_id=job["id"],
                worker_index=i, devices=[f"{platform}:{i}"])
            # Multi-host dp group: N processes per worker — process 0
            # leads (control plane), 1..N-1 follow (compute mirror,
            # worker/follower.py) — coordinated via jax.distributed on
            # a per-group loopback port (production pods use the pod's
            # coordinator host; same env contract).
            coordinator = (f"127.0.0.1:{ports[i]}"
                           if multihost_processes > 1 else None)
            leader_worker_id = f"{job['id'][:8]}-p{i}"
            for j in range(multihost_processes):
                env = dict(os.environ)
                if not (platform == "tpu" and multihost_processes > 1):
                    env.update(worker_device_env(
                        platform, i * multihost_processes + j, devices_per_trial))
                # else: a real multi-host TPU group must keep the pod
                # runtime's own topology env (TPU_WORKER_ID etc.) — a
                # flat per-process chip index + single-process bounds
                # would contradict the jax.distributed cluster.
                env.update({
                    "RAFIKI_WORKER_DB": self.db_path,
                    "RAFIKI_WORKER_PARAMS_DIR": self.params_dir,
                    "RAFIKI_WORKER_SUB_JOB_ID": sub["id"],
                    "RAFIKI_WORKER_ID": leader_worker_id + (
                        f".{j}" if multihost_processes > 1 and j > 0 else ""),
                    "RAFIKI_WORKER_SERVICE_ID": service["id"] if j == 0 else "",
                    "RAFIKI_WORKER_ADVISOR_URL": advisor_url,
                    "RAFIKI_WORKER_ADVISOR_ID": advisor_id,
                    "RAFIKI_WORKER_ADVISOR_SECRET": secret,
                })
                if coordinator is not None:
                    env.update({
                        "RAFIKI_COORDINATOR_ADDRESS": coordinator,
                        "RAFIKI_NUM_PROCESSES": str(multihost_processes),
                        "RAFIKI_PROCESS_ID": str(j),
                        "RAFIKI_LEADER_WORKER_ID": leader_worker_id,
                        "RAFIKI_LEADER_SERVICE_ID": service["id"],
                    })
                if events.path is not None:  # subprocess shares the event sink
                    env["RAFIKI_EVENTS_DIR"] = str(events.path.parent)
                # Worker output goes to a temp file, not a pipe: a full
                # pipe buffer would block the worker's writes and
                # deadlock the supervise loop below.
                out_f = tempfile.TemporaryFile(mode="w+t")
                out_files.append(out_f)
                proc = subprocess.Popen(
                    [sys.executable, "-m", "rafiki_tpu.worker.main"],
                    env=env, stdout=out_f, stderr=subprocess.STDOUT, text=True)
                procs.append(proc)
                proc_services.append(service if j == 0 else None)
            self.store.update_service(service["id"],
                                      status=ServiceStatus.RUNNING.value)

        # Supervise: wait for exits; on stop_event, terminate.
        while any(p.poll() is None for p in procs):
            if stop_event.is_set():
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                for p in procs:
                    try:
                        p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        p.kill()
                break
            time.sleep(poll_s)

        for k, (p, svc, out_f) in enumerate(zip(procs, proc_services, out_files)):
            rc = p.wait()
            out_f.seek(0)
            out = out_f.read()
            out_f.close()
            if rc != 0 and not stop_event.is_set():
                label = (f"worker {svc['worker_index']}" if svc is not None
                         else f"follower proc {k}")
                sub_errors.append(f"{label} rc={rc}: {out[-2000:]}")
                if svc is not None:
                    self.store.update_service(svc["id"],
                                              status=ServiceStatus.ERRORED.value)
            elif svc is not None:
                self.store.update_service(svc["id"],
                                          status=ServiceStatus.STOPPED.value)
        errors.extend(sub_errors)

        trials = self.store.get_trials_of_sub_train_job(sub["id"])
        if stop_event.is_set():
            sub_status = TrainJobStatus.STOPPED.value
        elif trials and all(t["status"] == TrialStatus.ERRORED.value for t in trials):
            sub_status = TrainJobStatus.ERRORED.value
        elif not trials and sub_errors:  # only this sub job's failures count
            sub_status = TrainJobStatus.ERRORED.value
        else:
            sub_status = TrainJobStatus.COMPLETED.value
        self.store.update_sub_train_job(sub["id"], status=sub_status)
        self.advisors.delete_advisor(advisor_id)
