"""Durable sweep write-ahead log (docs/recovery.md).

The observability journals (obs/journal.py) are *best effort*: line
buffered, per-process, rotated — perfect for reconstruction, useless
as a correctness substrate because a SIGKILL can eat the tail. The
control-plane decisions of a sweep — budget claims, pack assignments,
backfills, advisor feedback — need the opposite contract: every
mutation is preceded by an fsynced ``intent`` record and followed by
an fsynced ``commit``, so a fresh process adopting a dead supervisor's
job (scheduler/recovery.py ``resume_sweep``) can reconcile exactly
what the dead process was doing against the MetaStore rows that
actually landed.

Record grammar (one JSON object per line)::

    {"lsn": 7, "ts": ..., "pid": ..., "gen": 0,
     "rec": "intent" | "commit" | "note",
     "op":  "budget_claim" | "pack_assign" | "backfill"
          | "advisor_feedback" | "adopt" | "sweep_config" | ...,
     "txn": "w<pid>-<rand>-3",     # intent/commit only; commit refs its intent
     ...op-specific fields}

* ``intent`` — written (and fsynced) BEFORE the mutation executes.
* ``commit`` — written after; carries the outcome (``trial_id`` for a
  claim that landed, ``denied=True`` for an atomic claim the store
  refused because the budget drained).
* ``note`` — durable facts that are not two-phase (the sweep config a
  resumer needs to rehydrate the advisor, adoption markers).

The WAL lives NEXT TO the MetaStore sqlite file (``<db dir>/wal/
sweep-<job_id>.wal``, overridable via ``RAFIKI_WAL_DIR``) — same
durability domain as the rows it journals, discoverable by a resumer
that only knows the store path and the job id. Appends from multiple
processes (the dead supervisor, then its resumer) are safe: the file
is opened O_APPEND and records carry pid + generation.

Reconciliation (``reconcile``) proves the budget invariant "every
slot claimed exactly once": every committed claim must reference an
existing trial row, every trial row must be covered by exactly one
claim (committed, or an in-doubt intent resolved by knobs-hash match
— the MetaStore claim+insert is one sqlite txn, so an intent without
a commit either fully landed or never happened), and the sub's
``claimed`` counter must equal the row count.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional

ENV_WAL_DIR = "RAFIKI_WAL_DIR"

#: ops whose intent/commit pairs claim (or assign) budgeted work.
CLAIM_OPS = ("budget_claim", "backfill")


class WalError(RuntimeError):
    """A structurally broken WAL (torn non-tail line, commit without
    intent) — distinct from a *reconciliation* failure against the
    store, which is a :class:`WalReconcileError`."""


class WalReconcileError(RuntimeError):
    """WAL-vs-store reconciliation failed: the log claims a state the
    MetaStore does not corroborate (e.g. a committed budget claim with
    no trial row). Resume must NOT proceed past this — adopting a job
    whose accounting is provably wrong would compound the damage."""

    def __init__(self, errors: List[Dict[str, Any]]):
        self.errors = list(errors)
        super().__init__(
            f"sweep WAL reconciliation failed: {len(self.errors)} "
            f"error(s): " + "; ".join(sorted({e["type"] for e in self.errors})))


def wal_dir(store_path: str) -> Path:
    env = os.environ.get(ENV_WAL_DIR, "").strip()
    if env:
        return Path(env)
    return Path(os.path.dirname(os.path.abspath(str(store_path)))) / "wal"


def wal_path(store_path: str, job_id: str) -> Path:
    return wal_dir(store_path) / f"sweep-{job_id}.wal"


class SweepWal:
    """Append-only fsynced intent/commit log for one train job's sweep.

    Thread-safe (the supervisor, chip runners and backfill closures all
    write); every ``intent``/``commit``/``note`` is flushed AND fsynced
    before returning, so a record the caller observed written survives
    a SIGKILL of the whole process.
    """

    def __init__(self, path: Path | str, generation: int = 0):
        self.path = Path(path)
        self.generation = int(generation)
        self._lock = threading.Lock()
        self._fh = None
        self._lsn = 0
        self._txn_no = 0
        # Txn ids must be unique across every writer that ever appends
        # to this file — pid alone is not enough (one resume process
        # opens two handles: the adoption-phase log and the
        # continuation run_sweep's; pids also recycle), so each handle
        # gets its own random discriminator.
        self._txn_prefix = f"w{os.getpid()}-{uuid.uuid4().hex[:6]}"

    # -- lifecycle ----------------------------------------------------------

    @classmethod
    def for_job(cls, store, job_id: str, generation: int = 0) -> "SweepWal":
        return cls(wal_path(store.path, job_id), generation=generation)

    def exists(self) -> bool:
        try:
            return self.path.stat().st_size > 0
        except OSError:
            return False

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None

    def _ensure_open_locked(self):
        if self._fh is None:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self._fh = open(self.path, "a", encoding="utf-8")
        return self._fh

    # -- writers ------------------------------------------------------------

    def _write_locked(self, rec: str, op: str, txn: Optional[str],
                      fields: Dict[str, Any]) -> None:
        fh = self._ensure_open_locked()
        self._lsn += 1
        row = {"lsn": self._lsn, "ts": round(time.time(), 6),
               "pid": os.getpid(), "gen": self.generation,
               "rec": rec, "op": op}
        if txn is not None:
            row["txn"] = txn
        row.update(fields)
        fh.write(json.dumps(row, sort_keys=True, default=str) + "\n")
        # The whole point of this module: the record is on disk before
        # the mutation it announces. flush() alone dies with the page
        # cache on power loss and proves nothing under SIGKILL ordering
        # arguments; fsync is the contract docs/recovery.md documents.
        fh.flush()
        os.fsync(fh.fileno())

    def intent(self, op: str, **fields: Any) -> str:
        """Durably announce a mutation BEFORE executing it. Returns the
        txn id the matching :meth:`commit` must reference."""
        with self._lock:
            self._txn_no += 1
            txn = f"{self._txn_prefix}-{self._txn_no}"
            self._write_locked("intent", op, txn, fields)
            return txn

    def commit(self, txn: str, op: str, **fields: Any) -> None:
        """Durably record the outcome of an intented mutation."""
        with self._lock:
            self._write_locked("commit", op, txn, fields)

    def note(self, op: str, **fields: Any) -> None:
        """A durable single-shot fact (sweep config, adoption marker)."""
        with self._lock:
            self._write_locked("note", op, None, fields)


# ---------------------------------------------------------------------------
# Readers + reconciliation
# ---------------------------------------------------------------------------

def read_wal(path: Path | str) -> List[Dict[str, Any]]:
    """Parse a WAL file. A torn FINAL line (the process died mid-write,
    before its fsync returned — so the writer never acted on it) is
    dropped silently; a torn interior line is corruption and raises."""
    p = Path(path)
    if not p.exists():
        return []
    raw = p.read_text(encoding="utf-8", errors="replace").splitlines()
    out: List[Dict[str, Any]] = []
    for i, line in enumerate(raw):
        line = line.strip()
        if not line:
            continue
        try:
            out.append(json.loads(line))
        except ValueError:
            if i == len(raw) - 1:
                break  # torn tail: never acknowledged, never acted on
            raise WalError(f"{p}: corrupt WAL record at line {i + 1}")
    return out


@dataclass
class WalReconcile:
    """The verdict of WAL-vs-store reconciliation for one sub job."""

    ok: bool = True
    errors: List[Dict[str, Any]] = field(default_factory=list)
    #: trial_id -> number of WAL claims covering it (committed or
    #: resolved in-doubt). The budget invariant is all-values == 1.
    claims: Dict[str, int] = field(default_factory=dict)
    #: intents that never committed, resolved against the store:
    #: [{"txn", "op", "landed": bool}]
    in_doubt: List[Dict[str, Any]] = field(default_factory=list)
    denied: int = 0

    def _err(self, type_: str, **fields: Any) -> None:
        self.ok = False
        self.errors.append({"type": type_, **fields})

    def raise_if_failed(self) -> None:
        if not self.ok:
            raise WalReconcileError(self.errors)

    def summary(self) -> Dict[str, Any]:
        return {"ok": self.ok, "n_claims": len(self.claims),
                "n_in_doubt": len(self.in_doubt), "denied": self.denied,
                "errors": self.errors}


def reconcile(records: List[Dict[str, Any]], trials: List[Dict[str, Any]],
              sub: Optional[Dict[str, Any]] = None,
              sub_id: Optional[str] = None) -> WalReconcile:
    """Prove (or refute) the budget invariant for one sub-train-job.

    ``trials`` are the MetaStore rows of the sub; ``sub`` (optional)
    supplies the atomic ``claimed`` counter to cross-check; ``sub_id``
    restricts claim records to one sub of a multi-model job (claim
    intents carry their sub). Claim-class ops (``budget_claim``/
    ``backfill``) are the audited set; assignment ops (``pack_assign``)
    are checked only for intent/commit pairing.
    """
    from rafiki_tpu.obs.search.audit import knobs_hash as _khash

    r = WalReconcile()
    trials = [dict(t) for t in trials]
    for t in trials:
        if not t.get("knobs_hash") and isinstance(t.get("knobs"), dict):
            t["knobs_hash"] = _khash(t["knobs"])
    rows_by_id = {t["id"]: t for t in trials}
    intents: Dict[str, Dict[str, Any]] = {}
    commits: Dict[str, Dict[str, Any]] = {}
    for rec in records:
        kind = rec.get("rec")
        if kind == "intent":
            intents[rec["txn"]] = rec
        elif kind == "commit":
            txn = rec.get("txn")
            if txn not in intents:
                r._err("commit_without_intent", txn=txn, op=rec.get("op"))
                continue
            if txn in commits:
                r._err("double_commit", txn=txn, op=rec.get("op"))
                continue
            commits[txn] = rec

    def _in_scope(txn: str) -> bool:
        it = intents.get(txn)
        return (sub_id is None or it is None
                or it.get("sub_id") in (None, sub_id))

    # 1. Committed claims must reference real rows, each exactly once.
    for txn, c in commits.items():
        if c.get("op") not in CLAIM_OPS or not _in_scope(txn):
            continue
        if c.get("denied"):
            r.denied += 1
            continue
        tid = c.get("trial_id")
        if tid is None or tid not in rows_by_id:
            r._err("committed_unclaimed", txn=txn, trial_id=tid,
                   op=c.get("op"))
            continue
        r.claims[tid] = r.claims.get(tid, 0) + 1

    # 2. In-doubt intents (no commit): the store claim+insert is one
    #    sqlite transaction, so the slot either fully landed (an
    #    as-yet-unclaimed row with this intent's knobs hash exists) or
    #    never happened. Either way, resolvable.
    for txn, it in intents.items():
        if txn in commits or it.get("op") not in CLAIM_OPS:
            continue
        if sub_id is not None and it.get("sub_id") not in (None, sub_id):
            continue
        landed = None
        h = it.get("knobs_hash")
        if h:
            for t in trials:
                if t["id"] in r.claims:
                    continue
                if t.get("knobs_hash") == h:
                    landed = t["id"]
                    break
        if landed is not None:
            r.claims[landed] = r.claims.get(landed, 0) + 1
        r.in_doubt.append({"txn": txn, "op": it.get("op"),
                           "landed": landed is not None})

    for tid, n in r.claims.items():
        if n != 1:
            r._err("duplicate_claim", trial_id=tid, n=n)

    # 3. Every store row must be covered by a WAL claim, and the
    #    atomic counter must agree with the row count.
    for t in trials:
        if t["id"] not in r.claims:
            r._err("unlogged_claim", trial_id=t["id"])
    if sub is not None and sub.get("claimed") is not None:
        if int(sub["claimed"]) != len(trials):
            r._err("claimed_counter_mismatch", claimed=int(sub["claimed"]),
                   rows=len(trials))
    return r
