"""In-process scheduler: N worker threads, each owning a device set.

The thread-per-worker model is correct for TPU because the heavy work
happens on device: the GIL is released during XLA execution, so k
workers drive k chips concurrently from one Python process. (Compile
contention is real — heavy production use should prefer
ProcessScheduler — but for small trials and tests this is the simplest
thing that works, and it's what the 8-device CPU fake pod exercises.)
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from rafiki_tpu import telemetry
from rafiki_tpu.advisor import AdvisorService
from rafiki_tpu.constants import ServiceStatus, ServiceType, TrainJobStatus, TrialStatus
from rafiki_tpu.model.base import load_model_class
from rafiki_tpu.parallel.mesh import local_devices, partition_devices
from rafiki_tpu.store import MetaStore, ParamsStore
from rafiki_tpu.utils.events import events
from rafiki_tpu.worker.train import InProcAdvisorHandle, TrainWorker


@dataclass
class TrainJobResult:
    job_id: str
    status: str
    trials: List[dict]
    best_trials: List[dict]
    duration_s: float
    errors: List[str] = field(default_factory=list)


class LocalScheduler:
    def __init__(self, store: MetaStore, params_store: ParamsStore,
                 advisor_service: Optional[AdvisorService] = None):
        self.store = store
        self.params_store = params_store
        self.advisors = advisor_service or AdvisorService()

    def run_train_job(
        self,
        job_id: str,
        n_workers: Optional[int] = None,
        devices: Optional[List[Any]] = None,
        devices_per_trial: int = 1,
        advisor_kind: str = "gp",
        stop_event: Optional[threading.Event] = None,
        trial_pack: Optional[int] = None,
    ) -> TrainJobResult:
        """Run a train job to budget exhaustion. Blocking; thread-safe.

        Device math: with D devices and devices_per_trial=k, there are
        D//k workers (each trial data-parallel over its k chips) unless
        n_workers caps it lower. The per-model sub-jobs share the
        worker pool sequentially (models are trained one after another,
        each with full parallelism — simplest fair split; the budget is
        per sub-job, as in the reference).

        ``trial_pack``: vmap up to k same-program trials into one XLA
        program per single-device worker (None → RAFIKI_TRIAL_PACK env,
        default 1 = off; see docs/trial_packing.md). Ignored by workers
        that fail the packing eligibility checks (mesh, multihost,
        custom preprocess, masked dataset).
        """
        t0 = time.monotonic()
        job = self.store.get_train_job(job_id)
        if job is None:
            raise KeyError(f"No train job {job_id!r}")
        self.store.update_train_job_status(job_id, TrainJobStatus.RUNNING.value)
        events.emit("train_job_started", job_id=job_id, app=job["app"],
                    budget=job["budget"], scheduler="local")
        stop_event = stop_event or threading.Event()

        devices = devices if devices is not None else local_devices()
        budget = dict(job["budget"])
        chip_budget = budget.get("CHIP_COUNT") or budget.get("GPU_COUNT")
        if chip_budget:
            devices = devices[: int(chip_budget) * devices_per_trial]
        max_workers = max(1, len(devices) // devices_per_trial)
        n_workers = min(n_workers or max_workers, max_workers)
        device_sets = partition_devices(devices[: n_workers * devices_per_trial], n_workers)

        errors: List[str] = []
        subs = self.store.get_sub_train_jobs(job_id)
        if not subs:
            raise ValueError(f"Train job {job_id} has no sub jobs (no models attached)")

        for sub in subs:
            if stop_event.is_set():
                self.store.update_sub_train_job(sub["id"], status=TrainJobStatus.STOPPED.value)
                continue
            model_row = self.store.get_model(sub["model_id"])
            try:
                model_cls = load_model_class(model_row["model_file"], model_row["model_class"])
            except Exception as e:
                self.store.update_sub_train_job(sub["id"], status=TrainJobStatus.ERRORED.value)
                errors.append(f"model {model_row['name']}: {e}")
                continue
            advisor_id = self.advisors.create_advisor(
                model_cls.get_knob_config(), kind=advisor_kind,
                advisor_id=sub.get("advisor_id") or None)
            self.store.update_sub_train_job(sub["id"], advisor_id=advisor_id,
                                            status=TrainJobStatus.RUNNING.value)

            threads = []
            services = []
            for i, dev_set in enumerate(device_sets):
                service = self.store.create_service(
                    ServiceType.TRAIN_WORKER.value, job_id=job_id, worker_index=i,
                    devices=[str(d) for d in dev_set])
                services.append(service)
                worker = TrainWorker(
                    self.store, self.params_store, sub["id"], model_cls,
                    InProcAdvisorHandle(self.advisors, advisor_id),
                    job["train_dataset_uri"], job["val_dataset_uri"], budget,
                    worker_id=f"{job_id[:8]}-w{i}", devices=dev_set,
                    job_created_at=job["created_at"], service_id=service["id"],
                    stop_event=stop_event, trial_pack=trial_pack,
                )
                th = threading.Thread(target=self._run_worker, args=(worker, errors),
                                      name=f"train-worker-{i}", daemon=True)
                threads.append(th)
            for svc in services:
                self.store.update_service(svc["id"], status=ServiceStatus.RUNNING.value)
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            for svc in services:
                self.store.update_service(svc["id"], status=ServiceStatus.STOPPED.value)
            trials = self.store.get_trials_of_sub_train_job(sub["id"])
            if stop_event.is_set():
                sub_status = TrainJobStatus.STOPPED.value
            elif trials and all(t["status"] == TrialStatus.ERRORED.value for t in trials):
                sub_status = TrainJobStatus.ERRORED.value
            else:
                sub_status = TrainJobStatus.COMPLETED.value
            self.store.update_sub_train_job(sub["id"], status=sub_status)
            self.advisors.delete_advisor(advisor_id)

        subs_after = self.store.get_sub_train_jobs(job_id)
        if stop_event.is_set():
            status = TrainJobStatus.STOPPED.value
        elif subs_after and all(s["status"] == TrainJobStatus.ERRORED.value for s in subs_after):
            status = TrainJobStatus.ERRORED.value
        else:
            status = TrainJobStatus.COMPLETED.value
        self.store.update_train_job_status(job_id, status)
        telemetry.inc("scheduler.train_jobs_finished")
        # lint: disable=RF007 — job duration observed into train_job_s right here
        dur_s = time.monotonic() - t0
        telemetry.observe("scheduler.train_job_s", dur_s)
        events.emit("train_job_finished", job_id=job_id, status=status,
                    duration_s=round(dur_s, 3))
        return TrainJobResult(
            job_id=job_id,
            status=status,
            trials=self.store.get_trials_of_train_job(job_id),
            best_trials=self.store.get_best_trials_of_train_job(job_id, limit=2),
            duration_s=dur_s,
            errors=errors,
        )

    @staticmethod
    def _run_worker(worker: TrainWorker, errors: List[str]) -> None:
        telemetry.add_gauge("scheduler.active_workers", 1)
        try:
            worker.run()
        except Exception as e:  # worker crash ≠ job crash; trials already contained
            errors.append(f"worker {worker.worker_id}: {e!r}")
        finally:
            telemetry.add_gauge("scheduler.active_workers", -1)
