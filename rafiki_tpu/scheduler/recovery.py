"""Failure detection + elastic recovery of orphaned trials.

Reference parity and beyond: SURVEY.md §5 — the reference's recovery
is Docker-restart + mark-trial-ERRORED-and-move-on; a crashed trial's
progress is lost. Here, workers heartbeat their service row (between
trials in the trial loop, and within trials via the epoch-log sink),
``MetaStore.get_orphaned_trials`` detects RUNNING trials whose service
died or went silent, and ``recover_orphaned_trials`` re-adopts them —
resuming from the newest mid-trial checkpoint when one exists.

``stale_after_s`` must exceed the longest epoch (heartbeats are
per-epoch inside a trial).
"""

from __future__ import annotations

from typing import Any, List, Optional

from rafiki_tpu.constants import ServiceStatus, ServiceType
from rafiki_tpu.store import MetaStore, ParamsStore
from rafiki_tpu.utils.events import events
from rafiki_tpu.worker.train import build_worker_from_store


class _RecoveryAdvisor:
    """Advisor handle for adopted trials: knobs are already chosen, so
    propose() is never valid; feedback is accepted and dropped (the
    original advisor is usually gone with its job)."""

    def propose(self):
        raise RuntimeError("Recovery workers do not propose new trials")

    def feedback(self, score: float, knobs) -> None:
        pass


def recover_orphaned_trials(
    store: MetaStore,
    params_store: ParamsStore,
    stale_after_s: float = 60.0,
    sub_train_job_id: Optional[str] = None,
    devices: Optional[List[Any]] = None,
    advisor=None,
    orphans: Optional[List[dict]] = None,
) -> List[dict]:
    """Find and re-run every orphaned trial; returns final trial rows.

    Safe to call periodically (a sweep): adopted trials are flipped
    back to RUNNING with a fresh worker, so a second sweep during the
    re-run does not double-adopt unless the recovery worker itself
    goes silent past ``stale_after_s``.
    """
    orphans = orphans if orphans is not None \
        else store.get_orphaned_trials(stale_after_s, sub_train_job_id)
    # Claim every orphan up front via the atomic compare-and-swap
    # (status + observed owner): a sweep racing this one loses the CAS
    # on any trial we win, so each orphan is adopted exactly once.
    claimed = []
    for trial in orphans:
        service = store.create_service(ServiceType.TRAIN_WORKER.value)
        worker_id = f"recovery-{trial['id'][:8]}"
        if not store.adopt_trial(trial["id"], trial.get("service_id"),
                                 service["id"], worker_id):
            # Lost the race (another sweep adopted it, or the original
            # worker finished after all) — leave it alone.
            store.update_service(service["id"],
                                 status=ServiceStatus.STOPPED.value)
            continue
        events.emit("trial_orphan_detected", trial_id=trial["id"],
                    worker_id=trial.get("worker_id"))
        store.update_service(service["id"], heartbeat=True)
        claimed.append((trial, service, worker_id))

    # Keep every still-QUEUED claim's heartbeat fresh while earlier
    # re-runs execute: with one initial heartbeat only, a claim queued
    # behind a re-run longer than stale_after_s would go stale and a
    # periodic sweep's CAS (holding the CURRENT owner) would adopt it
    # again — two concurrent re-runs of one trial.
    import threading

    pending_services = {svc["id"] for _, svc, _ in claimed}
    pending_lock = threading.Lock()
    stop_beat = threading.Event()

    def _beat():
        interval = max(0.05, min(stale_after_s / 4.0, 5.0))
        while not stop_beat.wait(interval):
            with pending_lock:
                ids = list(pending_services)
            for sid in ids:
                store.update_service(sid, heartbeat=True)

    beater = threading.Thread(target=_beat, name="recovery-heartbeat",
                              daemon=True)
    beater.start()
    results: List[dict] = []
    try:
        for trial, service, worker_id in claimed:
            worker = build_worker_from_store(
                store, params_store, trial["sub_train_job_id"],
                advisor or _RecoveryAdvisor(),
                worker_id=worker_id, devices=devices,
                async_persist=False)  # recovery is synchronous; no saver thread
            worker.service_id = service["id"]
            # Hand heartbeat duty over to the worker's own progress-
            # coupled epoch sink BEFORE the re-run starts: if the
            # re-run hangs, its heartbeat must go stale so a periodic
            # sweep can re-adopt — the beater only covers QUEUED claims.
            with pending_lock:
                pending_services.discard(service["id"])
            try:
                results.append(worker.resume_trial(trial["id"]))
            finally:
                store.update_service(service["id"],
                                     status=ServiceStatus.STOPPED.value)
    finally:
        stop_beat.set()
        beater.join(timeout=5)
    return results
