"""Failure detection + elastic recovery of orphaned trials, and whole-
sweep crash resume (docs/recovery.md).

Reference parity and beyond: SURVEY.md §5 — the reference's recovery
is Docker-restart + mark-trial-ERRORED-and-move-on; a crashed trial's
progress is lost. Here, workers heartbeat their service row (between
trials in the trial loop, and within trials via the epoch-log sink),
``MetaStore.get_orphaned_trials`` detects RUNNING trials whose service
died or went silent, and ``recover_orphaned_trials`` re-adopts them —
resuming from the newest mid-trial checkpoint when one exists.

``resume_sweep`` goes further: a fresh process adopts a DEAD
SUPERVISOR'S ENTIRE JOB. It reconciles the sweep WAL
(scheduler/wal.py) against the MetaStore rows to prove the budget
invariant, rehydrates the dead sweep's advisor from completed-trial
rows plus ``kind="advisor"`` audit journals (advisor/rehydrate.py),
re-claims orphaned trials idempotently (double-resume loses the CAS
and backs off), then re-enters ``MeshSweepScheduler.run_sweep`` at
generation+1 to spend whatever budget remains — so ``propose_batch``
continues from an equivalent posterior, not from scratch.

``stale_after_s`` must exceed the longest epoch (heartbeats are
per-epoch inside a trial).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

from rafiki_tpu.constants import (
    BudgetType,
    ServiceStatus,
    ServiceType,
    TrainJobStatus,
    TrialStatus,
)
from rafiki_tpu.obs.journal import journal as _journal
from rafiki_tpu.obs.journal import read_dir as _read_journal_dir
from rafiki_tpu.scheduler.wal import SweepWal, read_wal, reconcile, wal_path
from rafiki_tpu.store import MetaStore, ParamsStore
from rafiki_tpu.utils.events import events
from rafiki_tpu.worker.train import build_worker_from_store

#: knobs for the resume path (docs/recovery.md): how stale a heartbeat
#: must be before a supervisor/worker counts as dead, and how often the
#: services-manager reaper polls for dead supervisors.
ENV_RESUME_STALE_S = "RAFIKI_RESUME_STALE_S"
ENV_RESUME_POLL_S = "RAFIKI_RESUME_POLL_S"

_TERMINAL_JOB = (TrainJobStatus.COMPLETED.value, TrainJobStatus.ERRORED.value,
                 TrainJobStatus.STOPPED.value)


class _RecoveryAdvisor:
    """Advisor handle for adopted trials: knobs are already chosen, so
    propose() is never valid; feedback is journaled and — when a
    rehydrated advisor handle is supplied — routed into it, so scores
    earned by adopted trials inform post-resume proposals instead of
    being silently dropped."""

    def __init__(self, inner=None):
        self._inner = inner

    def propose(self):
        raise RuntimeError("Recovery workers do not propose new trials")

    def propose_batch(self, n: int):
        raise RuntimeError("Recovery workers do not propose new trials")

    def feedback(self, score: float, knobs) -> None:
        from rafiki_tpu.obs.search.audit import knobs_hash
        routed = self._inner is not None
        if routed:
            self._inner.feedback(score, knobs)
        _journal.record("recovery", "feedback", score=float(score),
                        knobs_hash=knobs_hash(knobs), routed=routed)

    def speculate(self, score: float, knobs, fit=None) -> None:
        # Adopted trials can be speculated like any in-flight trial —
        # routed when a rehydrated advisor is attached, dropped
        # otherwise (a speculation is advisory; nothing durable owes
        # it).
        if self._inner is not None:
            self._inner.speculate(score, knobs, fit=fit)


def recover_orphaned_trials(
    store: MetaStore,
    params_store: ParamsStore,
    stale_after_s: float = 60.0,
    sub_train_job_id: Optional[str] = None,
    devices: Optional[List[Any]] = None,
    advisor=None,
    orphans: Optional[List[dict]] = None,
) -> List[dict]:
    """Find and re-run every orphaned trial; returns final trial rows.

    Safe to call periodically (a sweep): adopted trials are flipped
    back to RUNNING with a fresh worker, so a second sweep during the
    re-run does not double-adopt unless the recovery worker itself
    goes silent past ``stale_after_s``.
    """
    orphans = orphans if orphans is not None \
        else store.get_orphaned_trials(stale_after_s, sub_train_job_id)
    if not isinstance(advisor, _RecoveryAdvisor):
        advisor = _RecoveryAdvisor(advisor)
    # Claim every orphan up front via the atomic compare-and-swap
    # (status + observed owner): a sweep racing this one loses the CAS
    # on any trial we win, so each orphan is adopted exactly once.
    claimed = []
    for trial in orphans:
        service = store.create_service(ServiceType.TRAIN_WORKER.value)
        worker_id = f"recovery-{trial['id'][:8]}"
        if not store.adopt_trial(trial["id"], trial.get("service_id"),
                                 service["id"], worker_id,
                                 expected_status=trial.get("status")):
            # Lost the race (another sweep adopted it, or the original
            # worker finished after all) — leave it alone.
            store.update_service(service["id"],
                                 status=ServiceStatus.STOPPED.value)
            continue
        events.emit("trial_orphan_detected", trial_id=trial["id"],
                    worker_id=trial.get("worker_id"))
        store.update_service(service["id"], heartbeat=True)
        claimed.append((trial, service, worker_id))

    # Keep every still-QUEUED claim's heartbeat fresh while earlier
    # re-runs execute: with one initial heartbeat only, a claim queued
    # behind a re-run longer than stale_after_s would go stale and a
    # periodic sweep's CAS (holding the CURRENT owner) would adopt it
    # again — two concurrent re-runs of one trial.
    import threading

    pending_services = {svc["id"] for _, svc, _ in claimed}
    pending_lock = threading.Lock()
    stop_beat = threading.Event()

    def _beat():
        interval = max(0.05, min(stale_after_s / 4.0, 5.0))
        while not stop_beat.wait(interval):
            with pending_lock:
                ids = list(pending_services)
            for sid in ids:
                store.update_service(sid, heartbeat=True)

    beater = threading.Thread(target=_beat, name="recovery-heartbeat",
                              daemon=True)
    beater.start()
    results: List[dict] = []
    try:
        for trial, service, worker_id in claimed:
            worker = build_worker_from_store(
                store, params_store, trial["sub_train_job_id"],
                advisor,
                worker_id=worker_id, devices=devices,
                async_persist=False)  # recovery is synchronous; no saver thread
            worker.service_id = service["id"]
            # Hand heartbeat duty over to the worker's own progress-
            # coupled epoch sink BEFORE the re-run starts: if the
            # re-run hangs, its heartbeat must go stale so a periodic
            # sweep can re-adopt — the beater only covers QUEUED claims.
            with pending_lock:
                pending_services.discard(service["id"])
            try:
                results.append(worker.resume_trial(trial["id"]))
            finally:
                store.update_service(service["id"],
                                     status=ServiceStatus.STOPPED.value)
    finally:
        stop_beat.set()
        beater.join(timeout=5)
    return results


# ---------------------------------------------------------------------------
# Whole-sweep resume
# ---------------------------------------------------------------------------

def _journal_records() -> List[Dict[str, Any]]:
    """Every journal record reachable from this process (configured
    sink dir, or RAFIKI_LOG_DIR) — the advisor-audit source for
    rehydration. Empty when no journal was ever configured."""
    d = _journal.log_dir or os.environ.get("RAFIKI_LOG_DIR")
    if not d:
        return []
    try:
        return _read_journal_dir(d)
    except OSError:
        return []


def resume_sweep(
    store: MetaStore,
    params_store: ParamsStore,
    job_id: str,
    *,
    chips: Optional[int] = None,
    trials_per_chip: Optional[int] = None,
    stale_after_s: Optional[float] = None,
    devices: Optional[List[Any]] = None,
    advisor_service=None,
    stop_event=None,
) -> Dict[str, Any]:
    """Adopt a dead supervisor's train job and drive it to completion.

    The crash→detect→adopt→reconcile→resume lifecycle
    (docs/recovery.md), in order:

    1. Read the sweep WAL. No WAL → degrade LOUDLY to plain orphan-
       trial recovery (pre-WAL jobs are still salvageable, just not
       provable or continuable).
    2. Per sub job: ``reconcile`` WAL claims against trial rows —
       refuse to proceed (``WalReconcileError``) if the budget
       invariant doesn't hold.
    3. Rehydrate the advisor under the dead sweep's advisor_id from
       completed rows + advisor audit journals.
    4. CAS-adopt orphaned trials (stale-hearted AND claimed-but-never-
       assigned rows) and re-run them, feedback routed into the
       rehydrated advisor. A concurrent resumer loses the CAS per
       trial and backs off — double-resume is a no-op.
    5. Re-enter ``run_sweep`` at generation+1 with the WAL'd sweep
       config, so remaining budget is spent from the rehydrated
       posterior. Terminal job + nothing adopted → skip (no-op).

    Returns a summary dict (mode, generation, adopted/salvaged/
    restarted counts, reconcile summaries, continuation status).
    """
    t0 = time.monotonic()
    stale = float(stale_after_s if stale_after_s is not None
                  else os.environ.get(ENV_RESUME_STALE_S, "30"))
    job = store.get_train_job(job_id)
    if job is None:
        raise KeyError(f"No train job {job_id!r}")
    wal_p = wal_path(store.path, job_id)
    _journal.record("recovery", "resume_started", job_id=job_id,
                    job_status=job["status"], wal=str(wal_p),
                    stale_after_s=stale)

    summary: Dict[str, Any] = {
        "job_id": job_id, "mode": "wal", "generation": None,
        "adopted": 0, "salvaged": 0, "restarted": 0,
        "reconcile": [], "continuation": None, "wall_s": None,
    }

    records = read_wal(wal_p)
    if not records:
        # Pre-WAL job (or the WAL dir was lost): there is nothing to
        # reconcile and no config to continue from. Degrade to orphan-
        # trial recovery — and say so in the journal, loudly, because
        # the budget invariant is now unprovable for this job.
        _journal.record("recovery", "no_wal", job_id=job_id,
                        wal=str(wal_p),
                        note="degrading to orphan-trial recovery; budget "
                             "invariant unprovable, no sweep continuation")
        rows = recover_orphaned_trials(store, params_store, stale,
                                       devices=devices)
        summary["mode"] = "orphan_only"
        summary["adopted"] = len(rows)
        summary["wall_s"] = round(time.monotonic() - t0, 3)
        _journal.record("recovery", "resume_finished", job_id=job_id,
                        **{k: v for k, v in summary.items()
                           if k not in ("job_id", "reconcile")})
        return summary

    cfg: Dict[str, Any] = {}
    for r in records:
        if r.get("rec") == "note" and r.get("op") == "sweep_config":
            cfg = r  # last one wins (each generation re-notes it)
    generation = max(int(r.get("gen") or 0) for r in records) + 1
    summary["generation"] = generation

    from rafiki_tpu.advisor.rehydrate import rehydrate_advisor
    from rafiki_tpu.advisor.service import AdvisorService
    # Lazy: mesh imports worker/train and the full scheduler surface;
    # recovery must stay importable from lightweight CLI paths.
    from rafiki_tpu.model.base import load_model_class
    from rafiki_tpu.scheduler.mesh import MeshSweepScheduler, _WalAdvisorHandle
    from rafiki_tpu.worker.train import InProcAdvisorHandle

    advisors = advisor_service or AdvisorService()
    wal = SweepWal.for_job(store, job_id, generation=generation)
    jrecords = _journal_records()

    # The sub's atomic `claimed` counter only advances when the job has
    # a trial-count budget (create_trial claims a slot in the same
    # txn); without one it stays 0 and must not be cross-checked.
    has_count_budget = (dict(job.get("budget") or {})
                        .get(BudgetType.MODEL_TRIAL_COUNT.value) is not None)

    adopted_rows: List[dict] = []
    for sub in store.get_sub_train_jobs(job_id):
        trials = store.get_trials_of_sub_train_job(sub["id"])
        rec = reconcile(records, trials,
                        sub=sub if has_count_budget else None,
                        sub_id=sub["id"])
        _journal.record("recovery", "reconcile", job_id=job_id,
                        sub_id=sub["id"], **rec.summary())
        summary["reconcile"].append({"sub_id": sub["id"], **rec.summary()})
        if not rec.ok:
            _journal.record("recovery", "reconcile_failed", job_id=job_id,
                            sub_id=sub["id"], errors=rec.errors)
            rec.raise_if_failed()

        # Rehydrate the dead sweep's advisor under its original id so
        # (a) post-resume audit records join the same sweep and (b) the
        # continuation run_sweep's idempotent create_advisor reuses
        # this engine instead of building a cold one.
        handle = _RecoveryAdvisor()
        aid = sub.get("advisor_id")
        if aid:
            model_row = store.get_model(sub["model_id"])
            model_cls = load_model_class(model_row["model_file"],
                                         model_row["model_class"])
            completed = [t for t in trials
                         if t["status"] == TrialStatus.COMPLETED.value
                         and t.get("score") is not None]
            rehydrate_advisor(
                advisors, model_cls.get_knob_config(),
                kind=cfg.get("advisor_kind", "gp"), advisor_id=aid,
                completed=completed, journal_records=jrecords,
                seed=int(cfg.get("seed") or 0),
                engine_kwargs=cfg.get("advisor_kwargs") or None,
                job_id=job_id)
            handle = _RecoveryAdvisor(
                _WalAdvisorHandle(InProcAdvisorHandle(advisors, aid), wal))

        # Orphans: stale-hearted RUNNING rows, PLUS rows the dead
        # supervisor claimed but never bound to a chip (create_trial
        # landed, mark_trial_as_running didn't — service_id is NULL, so
        # get_orphaned_trials deliberately skips them; here the
        # supervisor is known-dead, so they are provably abandoned).
        orphans = {t["id"]: t
                   for t in store.get_orphaned_trials(stale, sub["id"])}
        for t in trials:
            if (t["status"] == TrialStatus.RUNNING.value
                    and not t.get("service_id")):
                orphans.setdefault(t["id"], t)
        ordered = sorted(orphans.values(),
                         key=lambda t: (t.get("no") or 0, t["id"]))
        if ordered:
            wal.note("adopt", sub_id=sub["id"],
                     trial_ids=[t["id"] for t in ordered])
            had_ckpt = {t["id"]: params_store.latest_checkpoint(t["id"])
                        is not None for t in ordered}
            rows = recover_orphaned_trials(
                store, params_store, stale, sub_train_job_id=sub["id"],
                devices=devices, advisor=handle, orphans=ordered)
            adopted_rows.extend(rows)
            summary["adopted"] += len(rows)
            for row in rows:
                if had_ckpt.get(row["id"]):
                    summary["salvaged"] += 1
                else:
                    summary["restarted"] += 1

    # Continuation: spend whatever budget remains from the rehydrated
    # posterior. run_sweep re-notes the config, takes a fresh
    # SUPERVISOR lease at this generation, claims remaining slots
    # atomically (a racing resumer's claims simply drain the budget —
    # no double-claims), and finalizes job/sub statuses even at zero
    # remaining. Skipped only when the job is already terminal and
    # nothing was adopted (true no-op double-resume).
    if job["status"] in _TERMINAL_JOB and not adopted_rows:
        summary["continuation"] = "skipped_terminal"
        _journal.record("recovery", "resume_noop", job_id=job_id,
                        job_status=job["status"], generation=generation)
    else:
        sched = MeshSweepScheduler(store, params_store,
                                   advisor_service=advisors)
        result = sched.run_sweep(
            job_id,
            chips=int(chips or cfg.get("chips") or 0) or None,
            trials_per_chip=int(trials_per_chip
                                or cfg.get("trials_per_chip") or 2),
            advisor_kind=cfg.get("advisor_kind", "gp"),
            stop_event=stop_event,
            generation=generation,
            advisor_kwargs=cfg.get("advisor_kwargs") or None,
        )
        summary["continuation"] = result.status

    wal.close()
    summary["wall_s"] = round(time.monotonic() - t0, 3)
    _journal.record("recovery", "resume_finished", job_id=job_id,
                    **{k: v for k, v in summary.items()
                       if k not in ("job_id", "reconcile")})
    events.emit("sweep_resumed", job_id=job_id, generation=generation,
                adopted=summary["adopted"], salvaged=summary["salvaged"],
                restarted=summary["restarted"],
                continuation=summary["continuation"])
    return summary
