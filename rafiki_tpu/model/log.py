"""Trial-time model logger.

Reference parity: rafiki/model/log.py (unverified path): models call
``logger.log(...)`` / ``logger.define_plot(...)`` / ``logger.log(epoch=,
loss=)`` during train(); the train worker captures entries and persists
them as TrialLog rows retrievable via the client and plotted in the UI.

Here the logger is a context-swappable collector: the worker installs a
sink around each trial; outside a trial, entries go to stdout logging.
Entries are JSONL-friendly dicts ``{"time": ..., "type": "message"|
"values"|"plot", ...}``.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

_py_logger = logging.getLogger("rafiki_tpu.model")

LogEntry = Dict[str, Any]
Sink = Callable[[LogEntry], None]


class ModelLogger:
    """The ``logger`` object importable by model templates."""

    def __init__(self):
        self._local = threading.local()

    def _sink(self) -> Optional[Sink]:
        return getattr(self._local, "sink", None)

    def _emit(self, entry: LogEntry) -> None:
        entry.setdefault("time", time.time())
        sink = self._sink()
        if sink is not None:
            sink(entry)
        else:
            _py_logger.info("%s", entry)

    # -- API used by model templates (reference-compatible) -----------------

    def log(self, msg: str = "", **values) -> None:
        """``logger.log("message")`` or ``logger.log(epoch=3, loss=0.1)``."""
        if msg:
            self._emit({"type": "message", "message": str(msg)})
        if values:
            self._emit({"type": "values", "values": values})

    def define_plot(self, title: str, metrics: List[str], x_axis: Optional[str] = None) -> None:
        self._emit({"type": "plot", "title": title, "metrics": list(metrics), "x_axis": x_axis})

    def define_loss_plot(self) -> None:
        self.define_plot("Loss over epochs", ["loss"], x_axis="epoch")

    def log_loss(self, loss: float, epoch: int) -> None:
        self.log(loss=float(loss), epoch=int(epoch))

    # -- API used by the worker ---------------------------------------------

    @contextlib.contextmanager
    def capture(self, sink: Sink):
        """Route this thread's log entries into ``sink`` for the duration."""
        prev = self._sink()
        self._local.sink = sink
        try:
            yield
        finally:
            self._local.sink = prev


logger = ModelLogger()
