"""Model contract + knob system + dataset utilities + dev harness.

Reference parity: rafiki/model/ (model.py, knob.py, dataset.py, log.py;
unverified paths — see SURVEY.md). This is the pure-library layer model
developers code against; it has no dependency on the control plane.
"""

from rafiki_tpu.model.knobs import (
    BaseKnob,
    CategoricalKnob,
    FixedKnob,
    FloatKnob,
    IntegerKnob,
    deserialize_knob_config,
    knob_config_signature,
    serialize_knob_config,
    validate_knobs,
)
from rafiki_tpu.model.base import BaseModel, JaxModel, load_model_class, parse_model_install_command
from rafiki_tpu.model.dataset import Dataset, dataset_utils
from rafiki_tpu.model.log import ModelLogger, logger
from rafiki_tpu.model.dev import test_model_class, tune_model

__all__ = [
    "BaseKnob",
    "FixedKnob",
    "CategoricalKnob",
    "IntegerKnob",
    "FloatKnob",
    "serialize_knob_config",
    "deserialize_knob_config",
    "knob_config_signature",
    "validate_knobs",
    "BaseModel",
    "JaxModel",
    "load_model_class",
    "parse_model_install_command",
    "Dataset",
    "dataset_utils",
    "ModelLogger",
    "logger",
    "test_model_class",
    "tune_model",
]
