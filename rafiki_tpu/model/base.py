"""The model contract: what a model template must implement.

Reference parity: rafiki/model/model.py (unverified path — see
SURVEY.md). The reference's ``BaseModel`` hooks are
``get_knob_config() / init(knobs) / train(dataset_uri) /
evaluate(dataset_uri) -> float / predict(queries) -> list /
dump_parameters() / load_parameters() / destroy()``; uploaded model
``.py`` files are loaded with ``load_model_class``.

We keep the same surface (so reference model templates translate
mechanically) and add a TPU-native base class, ``JaxModel``, that model
developers subclass instead of hand-writing device loops: they provide a
flax Module + knob config, and train/evaluate/predict become jit'd XLA
programs with optional within-trial data parallelism over a device mesh.
"""

from __future__ import annotations

import abc
import importlib
import importlib.util
import io
import pickle
import sys
import tempfile
import types
from typing import Any, Dict, List, Optional

import numpy as np

from rafiki_tpu.model.knobs import KnobConfig, Knobs, validate_knobs
from rafiki_tpu.model.dataset import Dataset, dataset_utils


class BaseModel(abc.ABC):
    """Abstract model template (reference-compatible surface).

    Lifecycle of one trial (driven by the train worker, SURVEY.md §3.1):
      model = ModelClass(**knobs)      # reference: init(knobs)
      model.train(train_uri)
      score = model.evaluate(val_uri)
      blob = model.dump_parameters()
      ... later, for serving ...
      model = ModelClass(**knobs); model.load_parameters(blob)
      out = model.predict(queries)
    """

    def __init__(self, **knobs: Any):
        self.knobs: Knobs = validate_knobs(self.get_knob_config(), knobs)

    # -- static declarations -------------------------------------------------

    @staticmethod
    @abc.abstractmethod
    def get_knob_config() -> KnobConfig:
        """Declare the hyperparameter space."""

    # -- trial hooks ---------------------------------------------------------

    @abc.abstractmethod
    def train(self, dataset_uri: str) -> None: ...

    @abc.abstractmethod
    def evaluate(self, dataset_uri: str) -> float: ...

    @abc.abstractmethod
    def predict(self, queries: List[Any]) -> List[Any]: ...

    def dump_parameters(self) -> bytes:
        raise NotImplementedError

    def load_parameters(self, blob: bytes) -> None:
        raise NotImplementedError

    def destroy(self) -> None:
        """Release device/host resources (optional)."""

    # -- conveniences --------------------------------------------------------

    @classmethod
    def knob_config(cls) -> KnobConfig:
        return cls.get_knob_config()


class JaxModel(BaseModel):
    """TPU-native base: subclass provides a flax Module, gets jit'd hooks.

    Subclasses implement:
      * ``get_knob_config()`` — include the conventional knobs
        ``learning_rate`` / ``batch_size`` / ``epochs`` (or override
        the corresponding properties);
      * ``build_module(num_classes, input_shape) -> flax.linen.Module``
        whose ``__call__(x, train: bool)`` returns logits.

    Optional overrides: ``make_optimizer()``, ``loss()``,
    ``preprocess(x)``.

    The mesh used for within-trial data parallelism is injected by the
    scheduler via ``set_mesh`` before ``train`` (SURVEY.md §7 step 7);
    by default the model runs on the process's default device.
    """

    def __init__(self, **knobs: Any):
        super().__init__(**knobs)
        self._loop = None  # ops.train.TrainLoop, built lazily at train/load
        self._mesh = None
        self._seed = int(self.knobs.get("seed", 0))
        self._dataset_meta: Dict[str, Any] = {}
        self._ckpt_sink = None  # set by the worker for mid-trial checkpoints
        self._start_epoch = 0  # >0 after restore_checkpoint

    # -- knob conventions ----------------------------------------------------

    @property
    def batch_size(self) -> int:
        return int(self.knobs.get("batch_size", 64))

    @property
    def epochs(self) -> int:
        return int(self.knobs.get("epochs", 1))

    @property
    def learning_rate(self) -> float:
        return float(self.knobs.get("learning_rate", 1e-3))

    # -- subclass surface ----------------------------------------------------

    @abc.abstractmethod
    def build_module(self, num_classes: int, input_shape: tuple):
        """Return a flax.linen.Module mapping x -> logits."""

    def make_base_optimizer(self):
        """Lr-free optimizer core for the standard (program-shared)
        path: the train step applies ``-effective_lr(hyper, step)``
        itself, so learning rate and warmup are traced scalars and an
        lr sweep reuses ONE compiled XLA program."""
        import optax

        return optax.scale_by_adam()

    def _warmup_steps(self) -> int:
        """Linear warmup guards deep nets (GroupNorm + bf16) against
        the early-step collapse that makes high-lr trials score as
        noise — without it the advisor's lr axis has a cliff instead of
        a slope. Capped at 10% of the planned steps so short trials
        still train."""
        planned = getattr(self, "_planned_steps", None) or 1000
        return int(self.knobs.get("warmup_steps",
                                  min(100, max(1, planned // 10))))

    def make_optimizer(self):
        """Legacy override hook: return a *complete* optax optimizer
        (lr baked in). Overriding this opts the template out of
        cross-lr program sharing — same-knob trials still reuse the
        compiled program, but each distinct lr/schedule compiles its
        own. Prefer ``make_base_optimizer`` + the lr knob."""
        import optax

        sched = optax.linear_schedule(0.0, self.learning_rate, self._warmup_steps())
        return optax.adam(sched)

    def preprocess(self, x: np.ndarray) -> np.ndarray:
        """Optional input transform. MUST NOT modify ``x`` in place —
        datasets are cached and shared across trials (dataset_utils);
        return a new array (e.g. ``x / 255.0``, not ``x /= 255.0``)."""
        return x

    def loss(self, params, batch, rng, apply_fn):
        from rafiki_tpu.ops.train import cross_entropy_loss

        logits = apply_fn(params, batch, train=True, rng=rng)
        loss, acc = cross_entropy_loss(logits, batch["y"])
        return loss, {"acc": acc}

    def should_stop_early(self, epoch: int, metrics: Dict[str, float]) -> bool:
        """Per-epoch early-stop hook: return True to end training after
        ``epoch`` (metrics are that epoch's train metrics). Honoured by
        both the serial ``train()`` loop and ``train_packed`` — a packed
        member whose stop fires before its pack-mates is EVICTED from
        the stacked state mid-pack and its slot backfilled
        (docs/mesh_sweep.md), with the evicted member's params
        bit-matching the serial early-stopped run."""
        return False

    # -- internal wiring -----------------------------------------------------

    def set_mesh(self, mesh) -> None:
        self._mesh = mesh

    def _dynamic_hyper(self, takes_dropout: bool) -> Dict[str, float]:
        """Values for the traced hyper dict carried in the train state.
        Everything here changes per trial WITHOUT recompiling."""
        hyper = {"lr": float(self.learning_rate),
                 "warmup": float(self._warmup_steps())}
        if takes_dropout and "dropout" in self.knobs:
            hyper["dropout"] = float(self.knobs["dropout"])
        return hyper

    def _program_key(self, num_classes: int, input_shape: tuple,
                     takes_dropout: bool, custom_opt: bool):
        """Cache key for the compiled Program: everything that can
        reach the traced computation EXCEPT the structurally dynamic
        knobs (lr/warmup via update scaling, dropout via the hyper
        dict, epochs = python loop count, seed = init rng value).
        A custom make_optimizer may bake any knob (and the planned-step
        count, via schedules) into its trace, so only seed is excluded
        and the planned steps are keyed in."""
        from rafiki_tpu.ops.train import DYNAMIC_KNOBS

        if custom_opt:
            dyn = {"seed"}
            extra = (getattr(self, "_planned_steps", None),)
        else:
            dyn = set(DYNAMIC_KNOBS) if takes_dropout else set(DYNAMIC_KNOBS) - {"dropout"}
            extra = ()
        baked = tuple(sorted((k, repr(v)) for k, v in self.knobs.items()
                             if k not in dyn))
        return (type(self).__module__, type(self).__qualname__,
                num_classes, tuple(input_shape), baked, custom_opt) + extra

    def _loop_fns(self, num_classes: int, input_shape: tuple) -> Dict[str, Any]:
        """Everything TrainLoop/PackedTrainLoop needs, derived once:
        the module, the pure fn closures, the optimizer, this trial's
        dynamic-hyper dict, and the program cache key. Shared by the
        serial path (``_build_loop``) and the packed path
        (``train_packed``) so the two can never drift apart."""
        import functools
        import inspect

        module = self.build_module(num_classes, input_shape)
        # Modules whose __call__ accepts ``dropout_rate`` get it as a
        # traced scalar from the hyper dict (see ops.train.dropout) —
        # a dropout sweep then reuses one compiled program.
        takes_dropout = "dropout_rate" in inspect.signature(
            type(module).__call__).parameters
        custom_opt = type(self).make_optimizer is not JaxModel.make_optimizer

        def apply_train(params, batch, train=False, rng=None, hyper=None):
            kwargs = {}
            if rng is not None:
                kwargs["rngs"] = {"dropout": rng}
            if takes_dropout and hyper is not None and "dropout" in hyper:
                kwargs["dropout_rate"] = hyper["dropout"]
            return module.apply({"params": params}, batch["x"], train=train, **kwargs)

        def apply_eval(params, batch):
            return apply_train(params, batch, train=False)

        def init_fn(rng):
            dummy = np.zeros((1,) + tuple(input_shape), self._input_dtype())
            variables = module.init(rng, dummy, train=False)
            return variables["params"]

        def loss_fn(params, batch, rng, hyper):
            return self.loss(params, batch, rng,
                             functools.partial(apply_train, hyper=hyper))

        hyper = self._dynamic_hyper(takes_dropout)
        if custom_opt:
            optimizer = self.make_optimizer()
            hyper.pop("lr", None)  # lr lives inside the custom optimizer
            hyper.pop("warmup", None)
        else:
            optimizer = self.make_base_optimizer()

        return {
            "module": module,
            "init_fn": init_fn,
            "apply_eval": apply_eval,
            "loss_fn": loss_fn,
            "optimizer": optimizer,
            "hyper": hyper,
            "program_key": self._program_key(num_classes, input_shape,
                                             takes_dropout, custom_opt),
        }

    def _build_loop(self, num_classes: int, input_shape: tuple):
        from rafiki_tpu.ops.train import TrainLoop

        fns = self._loop_fns(num_classes, input_shape)
        self._module = fns["module"]
        self._loop = TrainLoop(
            fns["init_fn"], fns["apply_eval"], fns["loss_fn"], fns["optimizer"],
            mesh=self._mesh, seed=self._seed, hyper=fns["hyper"],
            program_key=fns["program_key"])
        self._arch = (num_classes, tuple(input_shape))

    def _input_dtype(self):
        return np.float32

    def _dataset_arch(self, ds: Dataset) -> tuple:
        return ds.classes, tuple(ds.x.shape[1:])

    # -- contract hooks ------------------------------------------------------

    def _prepared_dataset(self, dataset_uri: str) -> Dataset:
        """Load + preprocess. When preprocess is the identity (returns
        the same array — the default), the process-cached Dataset
        object is used AS-IS so the device-resident copy attached to it
        (ops.train.get_device_dataset) is shared across trials; a
        custom preprocess gets a fresh wrapper per call (its output may
        depend on per-trial knobs, so it cannot be shared safely)."""
        ds = dataset_utils.load(dataset_uri)
        x = self.preprocess(ds.x)
        if x is ds.x:
            return ds
        return Dataset(x, ds.y, ds.classes, ds.mask, ds.meta)

    def _health_model_identity(self) -> Dict[str, Any]:
        """Replay-capsule identity: what a fresh process needs to
        re-create this template (docs/health.md). Templates loaded from
        uploaded source embed the bytes (load_model_class stamps
        ``__rafiki_source__`` on its scratch module)."""
        mod = sys.modules.get(type(self).__module__)
        return {
            "module": type(self).__module__,
            "qualname": type(self).__qualname__,
            "source": getattr(mod, "__rafiki_source__", None),
            "knobs": dict(self.knobs),
        }

    def train(self, dataset_uri: str) -> None:
        from rafiki_tpu.model.log import logger

        ds = self._prepared_dataset(dataset_uri)
        self._dataset_meta = dict(ds.meta)
        num_classes, input_shape = self._dataset_arch(ds)
        self._planned_steps = self.epochs * max(1, ds.size // self.batch_size)
        if self._loop is None:
            self._build_loop(num_classes, input_shape)
        elif self._arch != (num_classes, input_shape):
            raise ValueError(
                f"Dataset architecture {(num_classes, input_shape)} does not match "
                f"the loaded model {self._arch}; use a fresh model instance")
        health = getattr(self._loop, "health", None)
        if health is not None:
            health.set_context(
                model=self._health_model_identity(), train_uri=dataset_uri,
                batch_size=self.batch_size, seed=self._seed,
                planned_steps=getattr(self, "_planned_steps", None))
        logger.define_plot("Training", ["loss", "acc"], x_axis="epoch")
        for epoch in range(self._start_epoch, self.epochs):
            metrics = self._loop.run_epoch(ds, self.batch_size, epoch_seed=self._seed + epoch)
            logger.log(epoch=epoch, **metrics)
            self._epochs_done = epoch
            if self._ckpt_sink is not None:
                # The sink decides whether to materialize this epoch's
                # snapshot (dump is a device fetch — not free).
                self._ckpt_sink(epoch, self.dump_checkpoint)
            if self.should_stop_early(epoch, metrics):
                break

    def evaluate(self, dataset_uri: str) -> float:
        if self._loop is None:
            raise RuntimeError("Model has no parameters: call train() or load_parameters() first")
        ds = self._prepared_dataset(dataset_uri)
        self._check_label_space(ds)
        return float(self._loop.evaluate(ds, self.batch_size))

    # -- trial packing (docs/trial_packing.md) -------------------------------

    @classmethod
    def packable(cls) -> bool:
        """Whether instances of this template may join a trial pack.
        A pack shares ONE device-resident dataset upload, so templates
        with a custom ``preprocess`` (whose output may depend on
        per-trial knobs) are excluded."""
        return cls.preprocess is JaxModel.preprocess

    def shard_plan(self, ds: Dataset):
        """Group-sharding plan for one trial of this template, or None
        to stay in the single-chip lanes. Families whose train state
        can outgrow one chip's HBM override this to return a
        :class:`rafiki_tpu.shard.ShardPlan`; the sweep scheduler routes
        width>1 plans to a chip group (scheduler/mesh.py GroupHandle,
        docs/sharding.md). Width-1 plans (and None) mean the serial/
        packed lanes — the default for every small template."""
        return None

    def packing_key(self, ds: Dataset):
        """Bucket key for the PackedTrialRunner: two models may train
        in one pack iff their keys are equal — same compiled program
        (module config + baked knobs), same per-epoch step geometry
        (batch size, epochs), same dynamic-hyper key set (the hyper
        dict's keys are part of the traced state structure)."""
        num_classes, input_shape = self._dataset_arch(ds)
        self._planned_steps = self.epochs * max(1, ds.size // self.batch_size)
        fns = self._loop_fns(num_classes, input_shape)
        return (fns["program_key"], self.batch_size, self.epochs,
                tuple(sorted(fns["hyper"])))

    @classmethod
    def train_packed(cls, models: List["JaxModel"], dataset_uri: str,
                     on_epoch=None, checkpoint_sink=None,
                     backfill=None, on_evict=None,
                     kill_predicate=None) -> List[List[Dict[str, float]]]:
        """Train k model instances as ONE vmapped program on one device.

        All models must share a packing_key (the caller buckets).
        Per-trial identity is preserved: model i ends with the params,
        rng chain and shuffle order a serial ``train()`` with its seed
        would produce. Returns per-model epoch histories (list of
        ``{"loss": ..., "acc": ..., "epoch": e}`` dicts) — the caller
        writes them to each trial's log. ``on_epoch(round)`` fires
        after every packed round (worker heartbeats).

        ``checkpoint_sink(round, make_blobs)``, when given, fires after
        each round BEFORE ``on_epoch``; ``make_blobs()`` materializes
        one serial-format checkpoint blob per CURRENT pack member,
        returned as ``[(model_index, epoch, blob), ...]`` — sliced out of the
        live pack (``trial_state(i)`` device views, host copies
        pipelined) without serializing the stacked state, each stamped
        with that member's OWN epoch counter. A packed trial's
        checkpoint therefore restores through the ordinary serial
        resume path (docs/trial_packing.md).

        Elastic membership (docs/mesh_sweep.md): a member whose
        ``should_stop_early`` fires (or whose epoch budget completes)
        epochs before its pack-mates is EVICTED — its state is sliced
        out of the pack into a detached serial ``TrainLoop`` (so it
        still evaluates/serves/checkpoints normally and bit-matches a
        serial run) and ``on_evict(model_index, epoch, reason)`` fires
        with reason ``"early_stop"`` or ``"finished"``. A member whose
        numerics diverge (docs/health.md) leaves the same way with
        reason ``"diverged"`` — its verdict is stashed on
        ``model._health_verdict`` and the worker marks it errored
        instead of scoring it. When
        ``backfill(n)`` is given it is called with the vacancy count
        and may return freshly-proposed models (same packing_key);
        they are appended to ``models``/the returned histories and
        admitted into the freed slots mid-pack, starting at their own
        epoch 0. When every remaining member leaves in the same round,
        the pack ends and members keep live slice views (the shared
        ``evaluate_packed`` fast path).

        ``kill_predicate(model_index, epoch, metrics)``, when given, is
        consulted at each member's epoch boundary (after the
        divergence/budget/early-stop checks decline) and a True return
        evicts the member with reason ``"killed"`` — the learning-curve
        early-kill consumer (docs/early_kill.md). The caller owns all
        bookkeeping (the worker's ``on_evict`` marks the trial errored
        and routes the advisor's consolation feedback); default None =
        behavior identical to before the parameter existed.

        Not supported in a pack (callers enforce; asserted here):
        meshes (the trial axis IS the parallelism), checkpoint-resume
        (``_start_epoch > 0`` — an interrupted pack member resumes
        SERIALLY from its slice checkpoint), masked datasets.
        """
        from rafiki_tpu.obs import health as _health
        from rafiki_tpu.ops.train import PackedTrainLoop, TrainLoop

        if not models:
            return []
        lead = models[0]
        keys = {id(m): m.packing_key(lead._prepared_dataset(dataset_uri))
                for m in models}
        if len(set(map(repr, keys.values()))) != 1:
            raise ValueError("train_packed models do not share a packing key; "
                             "bucket with packing_key() first")
        for m in models:
            if m._mesh is not None:
                raise ValueError("packed trials are single-device; mesh is set")
            if m._start_epoch > 0:
                raise ValueError("packed trials cannot resume from checkpoint")
        ds = lead._prepared_dataset(dataset_uri)
        if ds.mask is not None:
            raise ValueError("packed training does not support masked datasets")
        num_classes, input_shape = lead._dataset_arch(ds)
        epochs, batch_size = lead.epochs, lead.batch_size

        # One set of traced closures (the lead's — program_key equality
        # makes them interchangeable), k hyper dicts/seeds.
        fns = lead._loop_fns(num_classes, input_shape)
        hypers = []
        for m in models:
            m._planned_steps = epochs * max(1, ds.size // batch_size)
            m._dataset_meta = dict(ds.meta)
            mf = m._loop_fns(num_classes, input_shape)
            hypers.append(mf["hyper"])
        packed = PackedTrainLoop(
            fns["init_fn"], fns["apply_eval"], fns["loss_fn"], fns["optimizer"],
            seeds=[m._seed for m in models], hypers=hypers,
            program_key=fns["program_key"],
            packing_key=repr(keys[id(lead)]))

        histories: List[List[Dict[str, float]]] = [[] for _ in models]
        arch = (num_classes, tuple(input_shape))
        planned = epochs * max(1, ds.size // batch_size)
        portable = _portable_meta(dict(ds.meta))
        pack_hypers = {i: hypers[i] for i in range(len(models))}

        def install_detached(mi: int, state, epoch: int) -> None:
            """Evicted member keeps training-equivalent state through an
            ordinary serial loop (same cached Program — ``hyper`` must
            be passed so dynamic_lr matches the pack's trace)."""
            m = models[mi]
            m._module = fns["module"]
            m._loop = TrainLoop(
                fns["init_fn"], fns["apply_eval"], fns["loss_fn"],
                fns["optimizer"], seed=m._seed, hyper=pack_hypers[mi],
                program_key=fns["program_key"], initial_state=state)
            m._arch = arch
            m._epochs_done = epoch

        slots = list(range(len(models)))  # slot j <-> packed member j
        epochs_done = {mi: 0 for mi in slots}  # epochs COMPLETED so far
        # Replay-capsule context (docs/health.md): member_info resolves
        # a LIVE slot to its trial's knobs/seed at trip time (slots and
        # models mutate as members leave and backfills arrive).
        packed.health.set_context(
            model=lead._health_model_identity(), train_uri=dataset_uri,
            batch_size=batch_size, planned_steps=planned,
            member_info=lambda j: {
                "model": dict(lead._health_model_identity(),
                              knobs=dict(models[slots[j]].knobs)),
                "seed": models[slots[j]]._seed,
            })
        rnd = 0
        while slots:
            # Serial parity: trial i's shuffle seed is seed_i + its OWN
            # epoch index, exactly what train() passes to run_epoch —
            # backfilled members count from their own epoch 0.
            mts = packed.run_epoch(
                ds, batch_size,
                [models[mi]._seed + epochs_done[mi] for mi in slots])
            for j, mi in enumerate(slots):
                histories[mi].append(dict(mts[j], epoch=epochs_done[mi]))
            if checkpoint_sink is not None:
                ents = tuple((mi, epochs_done[mi]) for mi in slots)
                checkpoint_sink(
                    rnd,
                    lambda e=ents: cls._packed_checkpoint_blobs(
                        packed, arch, e, planned, portable))
            if on_epoch is not None:
                on_epoch(rnd)
            rnd += 1

            verdicts = getattr(packed, "last_verdicts", None) or []
            leavers = []  # (slot, model_index, just-run epoch, reason)
            for j, mi in enumerate(slots):
                e = epochs_done[mi]
                verdict = verdicts[j] if j < len(verdicts) else None
                if verdict is not None:
                    # Numerics divergence (docs/health.md): the member
                    # leaves NOW regardless of budget — its verdict
                    # rides on the model for the worker's diagnosis.
                    models[mi]._health_verdict = verdict
                    leavers.append((j, mi, e, "diverged"))
                elif e + 1 >= epochs:
                    leavers.append((j, mi, e, "finished"))
                elif models[mi].should_stop_early(e, mts[j]):
                    leavers.append((j, mi, e, "early_stop"))
                elif kill_predicate is not None \
                        and kill_predicate(mi, e, mts[j]):
                    leavers.append((j, mi, e, "killed"))
            for mi in slots:
                epochs_done[mi] += 1

            if len(leavers) == len(slots):
                # Whole pack ends together: keep live slice views so
                # evaluate_packed scores everyone in ONE shared pass.
                for j, mi, e, reason in leavers:
                    m = models[mi]
                    m._module = fns["module"]
                    m._loop = packed.slice(j)
                    m._arch = arch
                    m._epochs_done = e
                    if reason == "diverged":
                        _health.note_eviction()
                    if on_evict is not None and reason in ("early_stop",
                                                           "diverged",
                                                           "killed"):
                        on_evict(mi, e, reason)
                break

            # Stragglers-in-reverse: some members are done early —
            # slice them out (descending slot so indices stay valid).
            for j, mi, e, reason in sorted(leavers, reverse=True):
                install_detached(mi, packed.evict(j), e)
                slots.pop(j)
                if reason == "diverged":
                    _health.note_eviction()
                if on_evict is not None:
                    on_evict(mi, e, reason)

            if leavers and backfill is not None:
                for m2 in (backfill(len(leavers)) or []):
                    mf2 = m2.packing_key(ds)  # sets _planned_steps
                    if repr(mf2) != repr(keys[id(lead)]):
                        raise ValueError(
                            "backfill model's packing_key differs from the "
                            "live pack's; the caller must bucket first")
                    m2._dataset_meta = dict(ds.meta)
                    hyper2 = m2._loop_fns(num_classes, input_shape)["hyper"]
                    mi2 = len(models)
                    models.append(m2)
                    histories.append([])
                    pack_hypers[mi2] = hyper2
                    packed.admit(m2._seed, hyper2)
                    slots.append(mi2)
                    epochs_done[mi2] = 0
        return histories

    @staticmethod
    def _packed_checkpoint_blobs(packed, arch, entries, planned_steps,
                                 dataset_meta) -> List[tuple]:
        """Serial-format checkpoint blobs out of a live pack, one per
        CURRENT member. ``entries`` is ``[(model_index, epoch), ...]``
        aligned with pack slots 0..k-1 (members evicted/backfilled
        mid-sweep carry their OWN epoch counters); the return is
        ``[(model_index, epoch, blob), ...]``.

        The pack is NOT serialized: each trial's state is a device-side
        slice view (``trial_state(i)`` = ``tree.map(a[i])``), and every
        slice's device→host copies are kicked off asynchronously before
        any blob is assembled, so the k transfers overlap instead of
        serializing k round-trips. Payload keys mirror
        ``dump_checkpoint`` exactly — ``restore_checkpoint`` cannot
        tell a pack-sliced snapshot from a serial one.
        """
        import jax

        from rafiki_tpu.utils.serial import dump_pytree

        states = [packed.trial_state(i) for i in range(packed.k)]
        for st in states:
            for leaf in jax.tree.leaves(st):
                if hasattr(leaf, "copy_to_host_async"):
                    leaf.copy_to_host_async()
        blobs = []
        for st, (mi, epoch) in zip(states, entries):
            payload = {
                "arch": arch,
                "state_packed": dump_pytree(st, cast_f32_to_bf16=False),
                "epoch": epoch,
                "planned_steps": planned_steps,
                "dataset_meta": dataset_meta,
            }
            blobs.append((mi, epoch, pickle.dumps(payload)))
        return blobs

    @classmethod
    def evaluate_packed(cls, models: List["JaxModel"], dataset_uri: str) -> List[float]:
        """Score a just-packed set of models in ONE shared eval pass:
        the batch stream is gathered once and every trial's params
        score it inside one vmapped program. Models must all be slices
        of the same live pack (i.e. straight out of train_packed)."""
        from rafiki_tpu.ops.train import PackedSliceLoop

        if not models:
            return []
        lead = models[0]
        loops = [m._loop for m in models]
        if not all(isinstance(lp, PackedSliceLoop) for lp in loops) or \
                len({id(lp.packed) for lp in loops}) != 1:
            # Mixed/serial loops (e.g. after load_parameters): fall back
            # to per-model evaluate — correctness over the shared pass.
            return [m.evaluate(dataset_uri) for m in models]
        ds = lead._prepared_dataset(dataset_uri)
        for m in models:
            m._check_label_space(ds)
        scores = loops[0].packed.evaluate(ds, lead.batch_size)
        return [float(scores[lp.index]) for lp in loops]

    def _check_label_space(self, ds: Dataset) -> None:
        """Fail loudly when an eval dataset's LABEL MEANING diverges
        from the train dataset's. Class counts alone cannot catch a
        corpus whose tag set differs but has the same cardinality: the
        loader's sorted tag ids would shift and every score would be
        silently computed against wrong labels."""
        train_tags = self._dataset_meta.get("tag_map")
        eval_tags = ds.meta.get("tag_map")
        if train_tags and eval_tags and train_tags != eval_tags:
            raise ValueError(
                f"Eval dataset tag map {eval_tags} != train tag map "
                f"{train_tags}; the datasets label different tag sets")

    def predict(self, queries: List[Any]) -> List[List[float]]:
        if self._loop is None:
            raise RuntimeError("Model has no parameters: call train() or load_parameters() first")
        x = self.preprocess(np.asarray(queries, dtype=self._input_dtype()))
        probs = self._loop.predict_proba(x, self.batch_size)
        return probs.tolist()

    def predict_proba(self, x: np.ndarray) -> np.ndarray:
        """Array-in/array-out fast path used by the ensemble predictor."""
        if self._loop is None:
            raise RuntimeError("Model has no parameters: call train() or load_parameters() first")
        return self._loop.predict_proba(self.preprocess(np.asarray(x, self._input_dtype())),
                                        self.batch_size)

    # -- params --------------------------------------------------------------

    def dump_parameters(self) -> bytes:
        from rafiki_tpu.config import get_config
        from rafiki_tpu.utils.serial import dump_pytree

        if self._loop is None:
            raise RuntimeError("No parameters to dump: model not trained/loaded")
        # Packed single-transfer dump (utils/serial.py): persisting is
        # on the steady-state throughput path via the async saver, and
        # per-leaf device_get costs ~2x the packed fetch.
        cast = get_config().serving_params_dtype == "bfloat16"
        payload = {
            "arch": self._arch,
            "packed": dump_pytree(self._loop.params, cast_f32_to_bf16=cast),
            "dataset_meta": _portable_meta(self._dataset_meta),
        }
        return pickle.dumps(payload)

    def load_parameters(self, blob: bytes) -> None:
        import jax
        import jax.numpy as jnp
        from flax import serialization

        payload = pickle.loads(blob)
        num_classes, input_shape = payload["arch"]
        self._dataset_meta = payload.get("dataset_meta", {})
        self._build_loop(num_classes, tuple(input_shape))
        template = self._loop.params
        if "packed" in payload:
            from rafiki_tpu.utils.serial import load_pytree

            state = load_pytree(payload["packed"])
            params = serialization.from_state_dict(template, state)
            # Upcast any bf16-stored leaves back to the template dtype
            # (exact: bf16 -> f32 is an injection).
            params = jax.tree.map(
                lambda t, v: jnp.asarray(v, jnp.asarray(t).dtype), template, params)
        else:  # pre-packed-format blobs (flax msgpack)
            params = serialization.from_bytes(template, payload["params"])
        self._loop.params = jax.device_put(params)

    def destroy(self) -> None:
        self._loop = None

    # -- mid-trial checkpointing --------------------------------------------

    def set_checkpoint_sink(self, sink) -> None:
        """Install a per-epoch checkpoint hook: ``sink(epoch, make_blob)``
        where ``make_blob()`` returns the full-train-state snapshot.
        The reference has no mid-trial checkpointing (SURVEY.md §5);
        the TrainWorker wires this to the params store so long trials
        survive worker crashes."""
        self._ckpt_sink = sink

    def dump_checkpoint(self) -> bytes:
        """Full resumable snapshot: params AND optimizer state AND step
        counter (``dump_parameters`` is params-only, for serving).
        Full precision (resume must be exact), packed single-transfer."""
        from rafiki_tpu.utils.serial import dump_pytree

        if self._loop is None:
            raise RuntimeError("No state to checkpoint: model not trained")
        payload = {
            "arch": self._arch,
            "state_packed": dump_pytree(self._loop.state, cast_f32_to_bf16=False),
            "epoch": getattr(self, "_epochs_done", 0),
            "planned_steps": getattr(self, "_planned_steps", None),
            "dataset_meta": _portable_meta(self._dataset_meta),
        }
        return pickle.dumps(payload)

    def restore_checkpoint(self, blob: bytes) -> int:
        """Restore a ``dump_checkpoint`` snapshot; returns the epoch to
        resume from. ``train()`` then skips the already-done epochs."""
        import jax
        from flax import serialization

        payload = pickle.loads(blob)
        num_classes, input_shape = payload["arch"]
        self._dataset_meta = payload.get("dataset_meta", {})
        if payload.get("planned_steps"):
            self._planned_steps = payload["planned_steps"]
        self._build_loop(num_classes, tuple(input_shape))
        template = self._loop.state
        if "state_packed" in payload:
            from rafiki_tpu.utils.serial import load_pytree

            raw = load_pytree(payload["state_packed"])
        else:  # pre-packed-format blobs (flax msgpack)
            raw = serialization.msgpack_restore(payload["state"])
        try:
            state = serialization.from_state_dict(template, raw)
        except Exception:
            # Checkpoints from an older state/optimizer layout: salvage
            # the trained params and step counter — the expensive part —
            # and reinitialize optimizer state / rng / hyper fresh.
            params = serialization.from_state_dict(template[0], raw["0"])
            try:
                step = serialization.from_state_dict(template[2], raw["2"])
            except Exception:
                step = template[2]
            state = (params, template[1], step, template[3], template[4])
        self._loop.state = jax.device_put(state)
        self._start_epoch = int(payload["epoch"]) + 1
        return self._start_epoch


# ---------------------------------------------------------------------------
# Model file loading (reference: load_model_class executes uploaded .py)
# ---------------------------------------------------------------------------

def _portable_meta(meta: Dict[str, Any]) -> Dict[str, Any]:
    """The dataset-meta slice worth persisting in params/checkpoint
    blobs: scalars, plus the label-space signature (``tag_map``) so a
    restored model still fails loudly on a mismatched eval dataset."""
    out = {k: v for k, v in meta.items()
           if isinstance(v, (str, int, float, bool))}
    if isinstance(meta.get("tag_map"), dict):
        out["tag_map"] = dict(meta["tag_map"])
    return out


def load_model_class(model_file_bytes: bytes, model_class: str,
                     temp_mod_name: Optional[str] = None) -> type:
    """Load a model template class from uploaded ``.py`` source bytes.

    Matches the reference behavior of exec-ing the uploaded file into a
    scratch module. The uploaded source is *trusted* (model developers
    are authenticated users — same trust model as the reference).
    """
    name = temp_mod_name or f"_rafiki_model_{abs(hash(model_file_bytes)) % (1 << 30):x}"
    mod = types.ModuleType(name)
    mod.__dict__["__file__"] = f"<{name}.py>"
    sys.modules[name] = mod
    try:
        exec(compile(model_file_bytes, f"<{name}.py>", "exec"), mod.__dict__)
    except Exception:
        del sys.modules[name]
        raise
    # Health replay capsules (docs/health.md) embed the source so a
    # fresh process can rebuild the class without this scratch module.
    mod.__rafiki_source__ = model_file_bytes
    if not hasattr(mod, model_class):
        del sys.modules[name]
        raise ValueError(f"Model file defines no class named {model_class!r}")
    cls = getattr(mod, model_class)
    if not (isinstance(cls, type) and issubclass(cls, BaseModel)):
        del sys.modules[name]
        raise ValueError(f"{model_class} must subclass rafiki_tpu BaseModel")
    return cls


def parse_model_install_command(dependencies: Dict[str, str]) -> List[str]:
    """Validate a model's declared deps are importable (no pip in this
    environment; the reference instead generated a pip install command)."""
    missing = []
    for dep in dependencies or {}:
        pkg = {"scikit-learn": "sklearn", "Pillow": "PIL"}.get(dep, dep.replace("-", "_"))
        if importlib.util.find_spec(pkg) is None:
            missing.append(dep)
    return missing
