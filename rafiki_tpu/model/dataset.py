"""Dataset utilities: URI-addressed datasets, device-ready batching.

Reference parity: rafiki/model/dataset.py (unverified path):
``dataset_utils.load_dataset_of_image_files(uri)`` (zip of image files +
``images.csv`` with class labels) and ``load_dataset_of_corpus(uri)``
(zip of a TSV corpus for POS tagging). Datasets are addressed by URI.

TPU-native design:
  * a loaded ``Dataset`` is dense numpy arrays (NHWC uint8 images /
    int32 token-tag matrices), so the training loop feeds the device
    fixed-shape batches — XLA traces once per (batch, shape) signature.
  * ``batches()`` drops the train remainder (static shapes for jit) and
    pads + masks the eval remainder, so evaluation is exact without
    dynamic shapes.
  * ``synthetic://`` URIs generate deterministic learnable datasets
    in-process (class-conditional Gaussian images; token-tag sequences
    with a learnable token→tag mapping). This environment has zero
    network egress, and it also gives tests/benches a data source with
    real learnable signal.

URI schemes:
  synthetic://images?classes=10&w=28&h=28&c=1&n=2048&seed=0
  synthetic://corpus?vocab=200&tags=10&n=512&len=24&seed=0
  /path/to/dataset.zip        (zip of images + images.csv, reference format)
  /path/to/dataset.npz        (npz with arrays x, y)
  file:///path/to/dataset.zip
"""

from __future__ import annotations

import csv
import io
import json
import os
import urllib.parse
import zipfile
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np


@dataclass
class Dataset:
    """An in-memory dataset of (x, y) numpy arrays.

    For images: x is (N, H, W, C) float32 in [0, 1], y is (N,) int32.
    For corpora: x is (N, L) int32 token ids, y is (N, L) int32 tag ids
    with -1 padding, plus ``mask`` (N, L) bool.
    """

    x: np.ndarray
    y: np.ndarray
    classes: int
    mask: Optional[np.ndarray] = None
    meta: dict = field(default_factory=dict)

    @property
    def size(self) -> int:
        return int(self.x.shape[0])

    def split(self, frac: float, seed: int = 0) -> Tuple["Dataset", "Dataset"]:
        """Deterministic shuffled split into (first, second) with |first| = frac*N."""
        rng = np.random.default_rng(seed)
        order = rng.permutation(self.size)
        k = int(self.size * frac)
        a, b = order[:k], order[k:]
        mk = lambda idx: Dataset(
            self.x[idx], self.y[idx], self.classes,
            None if self.mask is None else self.mask[idx], dict(self.meta),
        )
        return mk(a), mk(b)

    def batches(
        self,
        batch_size: int,
        shuffle: bool = False,
        seed: int = 0,
        drop_remainder: bool = True,
        start: int = 0,
    ) -> Iterator[dict]:
        """Yield dicts of fixed-shape numpy batches.

        drop_remainder=True  → training mode: every batch is exactly
            batch_size (static shape → single XLA program).
        drop_remainder=False → eval mode: the last batch is zero-padded
            to batch_size and carries ``valid`` (bool mask over rows) so
            metrics can ignore padding.
        start → skip the first ``start`` rows (in iteration order); used
            when a device-side scan already covered a prefix.
        """
        n = self.size
        order = np.random.default_rng(seed).permutation(n) if shuffle else np.arange(n)
        for start in range(start, n, batch_size):
            idx = order[start : start + batch_size]
            if len(idx) < batch_size:
                if drop_remainder:
                    return
                pad = batch_size - len(idx)
                idx = np.concatenate([idx, np.zeros(pad, dtype=idx.dtype)])
                valid = np.zeros(batch_size, dtype=bool)
                valid[: batch_size - pad] = True
            else:
                valid = np.ones(batch_size, dtype=bool)
            batch = {"x": self.x[idx], "y": self.y[idx], "valid": valid}
            if self.mask is not None:
                batch["mask"] = self.mask[idx]
            yield batch


# ---------------------------------------------------------------------------
# Synthetic generators (deterministic, learnable)
# ---------------------------------------------------------------------------

def synthetic_images(classes=10, w=28, h=28, c=1, n=2048, seed=0, noise=0.35,
                     dist=0, flip=0.0) -> Dataset:
    """Class-conditional Gaussian-blob images.

    Each class k gets a fixed random template image; samples are
    template + Gaussian noise, clipped to [0, 1]. Linearly separable
    enough that accuracy tracks model/knob quality (the property the
    advisor needs), hard enough that more training helps.

    ``dist`` seeds the class templates (the underlying distribution);
    ``seed`` seeds the draws. Train/test splits of the same task share
    ``dist`` and differ in ``seed`` — otherwise they would be different
    classification problems and generalization would be impossible.

    ``flip`` relabels that fraction of samples uniformly at random,
    which caps attainable accuracy at a KNOWN ceiling independent of
    model, scale, or epochs: a perfect template classifier scores
    (1-flip) + flip/classes. That makes an accuracy target falsifiable
    — on a saturating task (flip=0) every non-broken config converges
    to ~1.0 and a "top-1 >= X" gate constrains nothing.
    """
    # Low-spatial-frequency templates (drawn coarse, then upsampled):
    # learnable both by flatten-head models (MLP/VGG) and by
    # global-average-pool heads (DenseNet), which can't see per-pixel
    # high-frequency patterns.
    th, tw = max(2, h // 4), max(2, w // 4)
    coarse = (np.random.default_rng(dist)
              .uniform(0.0, 1.0, size=(classes, th, tw, c)).astype(np.float32))
    templates = np.repeat(np.repeat(coarse, h // th + 1, axis=1), w // tw + 1, axis=2)
    templates = templates[:, :h, :w, :]
    rng = np.random.default_rng(seed + 1_000_003)
    y = rng.integers(0, classes, size=n).astype(np.int32)
    x = templates[y] + rng.normal(0.0, noise, size=(n, h, w, c)).astype(np.float32)
    x = np.clip(x, 0.0, 1.0).astype(np.float32)
    if flip > 0:
        flipped = rng.uniform(size=n) < flip
        y = np.where(flipped, rng.integers(0, classes, size=n), y).astype(np.int32)
    return Dataset(x, y, classes, meta={"kind": "images", "synthetic": True})


def synthetic_corpus(vocab=200, tags=10, n=512, length=24, seed=0, noise=0.05,
                     dist=0) -> Dataset:
    """Token sequences with a fixed random token→tag mapping (+ noise).

    A model that learns the per-token mapping (as an HMM/BiLSTM will)
    reaches ~(1-noise) accuracy. ``dist`` seeds the token→tag mapping,
    ``seed`` the draws (see synthetic_images on why they are separate).
    """
    tok2tag = (np.random.default_rng(dist)
               .integers(0, tags, size=vocab).astype(np.int32))
    rng = np.random.default_rng(seed + 1_000_003)
    x = rng.integers(1, vocab, size=(n, length)).astype(np.int32)  # 0 = pad
    y = tok2tag[x]
    flip = rng.uniform(size=y.shape) < noise
    y = np.where(flip, rng.integers(0, tags, size=y.shape), y).astype(np.int32)
    lens = rng.integers(max(2, length // 2), length + 1, size=n)
    mask = np.arange(length)[None, :] < lens[:, None]
    x = np.where(mask, x, 0).astype(np.int32)
    y = np.where(mask, y, -1).astype(np.int32)
    return Dataset(x, y, tags, mask=mask, meta={"kind": "corpus", "synthetic": True, "vocab": vocab})


def synthetic_text(vocab=80, classes=5, n=256, length=16, seed=0, noise=0.1,
                   dist=0) -> Dataset:
    """Fixed-length token sequences with ONE label per sequence — the
    text-classification companion to :func:`synthetic_corpus` (which is
    per-token tagging and therefore carries a mask).

    Token identity encodes the class: token t (1-based) signals class
    ``(t - 1) % classes``; each sequence draws ``1 - noise`` of its
    positions from its own class's tokens and the rest uniformly. A
    mean-pooled embedding separates the classes, accuracy saturates at
    a noise-determined ceiling, and — crucially for the sharded-trial
    lane — sequences are fixed-length so ``mask`` is None and the
    dataset rides the device-resident scan path bit-for-bit.
    """
    rng = np.random.default_rng(seed + 1_000_003)
    y = rng.integers(0, classes, size=n).astype(np.int32)
    m = max(1, (vocab - 1) // classes)  # class tokens per class
    sig_tok = 1 + y[:, None] + classes * rng.integers(0, m, size=(n, length))
    noise_tok = rng.integers(1, vocab, size=(n, length))
    sig = rng.uniform(size=(n, length)) >= noise
    x = np.where(sig, sig_tok, noise_tok).astype(np.int32)
    return Dataset(x, y, classes,
                   meta={"kind": "text", "synthetic": True, "vocab": vocab})


# ---------------------------------------------------------------------------
# Reference on-disk formats
# ---------------------------------------------------------------------------

def load_dataset_of_image_files(uri: str) -> Dataset:
    """Load the reference's image-zip format.

    Format (ref: rafiki/model/dataset.py, unverified): a zip containing
    image files plus ``images.csv`` with header ``path,class``; images
    are loaded, converted to grayscale-or-RGB arrays scaled to [0, 1].
    """
    path = _resolve_path(uri)
    if path.endswith(".npz"):
        return _load_npz(path, kind="images")
    from PIL import Image

    xs: List[np.ndarray] = []
    ys: List[int] = []
    with zipfile.ZipFile(path) as zf:
        with zf.open("images.csv") as f:
            rows = list(csv.DictReader(io.TextIOWrapper(f, "utf-8")))
        for row in rows:
            with zf.open(row["path"]) as imf:
                img = Image.open(imf)
                arr = np.asarray(img, dtype=np.float32) / 255.0
            if arr.ndim == 2:
                arr = arr[:, :, None]
            xs.append(arr)
            ys.append(int(row["class"]))
    x = np.stack(xs)
    y = np.asarray(ys, dtype=np.int32)
    return Dataset(x, y, classes=int(y.max()) + 1, meta={"kind": "images", "uri": uri})


# Canonical corpus encoding: ids must be DETERMINISTIC FUNCTIONS OF THE
# TEXT, not of one zip's iteration order — a train zip and a val zip are
# loaded independently (the model contract passes separate URIs), and
# first-seen-order vocabularies would silently map the same token or
# tag to different ids across the two, corrupting every evaluation.
#   * tokens: feature-hashed into a fixed table (same token → same id
#     in any zip; unseen val tokens get an arbitrary-but-consistent
#     bucket instead of crashing — the standard OOV story);
#   * tags: alphabetical (train/val splits of one corpus share the tag
#     set, and sorted order is content-determined);
#   * length: one fixed bucket (static shapes — one XLA program for
#     every zip; longer sentences truncate, the mask stays exact).
CORPUS_HASH_VOCAB = 8192
CORPUS_MAX_LEN = 64


def corpus_token_id(token: str) -> int:
    """Stable token id in [1, CORPUS_HASH_VOCAB): blake2b feature hash
    (0 is reserved for padding). Use this to build predict() queries
    from raw tokens — it is the same mapping the corpus loader applies."""
    import hashlib

    h = int.from_bytes(
        hashlib.blake2b(token.encode("utf-8"), digest_size=8).digest(), "big")
    return 1 + h % (CORPUS_HASH_VOCAB - 1)


def load_dataset_of_corpus(uri: str, tag_col: str = "tag") -> Dataset:
    """Load the reference's corpus-zip format: a TSV ``corpus.tsv`` of
    token/tag rows with blank lines between sentences. Encoding is
    canonical (see above) so separately-loaded train/val zips agree."""
    path = _resolve_path(uri)
    if path.endswith(".npz"):
        return _load_npz(path, kind="corpus")
    sents: List[List[Tuple[str, str]]] = []
    with zipfile.ZipFile(path) as zf:
        name = next(n for n in zf.namelist() if n.endswith(".tsv"))
        with zf.open(name) as f:
            cur: List[Tuple[str, str]] = []
            for line in io.TextIOWrapper(f, "utf-8"):
                line = line.rstrip("\n")
                if not line:
                    if cur:
                        sents.append(cur)
                        cur = []
                    continue
                tok, tag = line.split("\t")[:2]
                cur.append((tok, tag))
            if cur:
                sents.append(cur)
    tagset = {t: i for i, t in enumerate(sorted(
        {tag for s in sents for _, tag in s}))}
    length = CORPUS_MAX_LEN
    n = len(sents)
    x = np.zeros((n, length), dtype=np.int32)
    y = np.full((n, length), -1, dtype=np.int32)
    mask = np.zeros((n, length), dtype=bool)
    for i, s in enumerate(sents):
        for j, (tok, tag) in enumerate(s[:length]):
            x[i, j] = corpus_token_id(tok)
            y[i, j] = tagset[tag]
            mask[i, j] = True
    return Dataset(x, y, classes=len(tagset), mask=mask,
                   meta={"kind": "corpus", "uri": uri,
                         "vocab": CORPUS_HASH_VOCAB, "tag_map": tagset})


def _load_npz(path: str, kind: str) -> Dataset:
    with np.load(path, allow_pickle=False) as z:
        x = z["x"]
        y = z["y"].astype(np.int32)
        mask = z["mask"] if "mask" in z else None
        saved_meta = (json.loads(str(z["meta_json"]))
                      if "meta_json" in z else {})
    classes = int(y.max()) + 1 if kind == "images" else int(y[y >= 0].max()) + 1
    if saved_meta.get("classes"):
        classes = int(saved_meta.pop("classes"))
    if kind == "images" and x.dtype == np.uint8:
        x = x.astype(np.float32) / 255.0
    meta = {"kind": kind, "uri": path}
    if kind == "corpus":
        # Legacy derivation only when the npz carries no meta: a hashed
        # corpus saved via save_npz MUST keep its fixed table size —
        # max-observed-id+1 would shrink the embedding below ids that
        # corpus_token_id() can legitimately produce for new queries.
        meta["vocab"] = int(x.max()) + 1
    meta.update(saved_meta)
    return Dataset(x, y, classes=classes, mask=mask, meta=meta)


def _resolve_path(uri: str) -> str:
    if uri.startswith("file://"):
        return urllib.parse.urlparse(uri).path
    return os.path.expanduser(uri)


# ---------------------------------------------------------------------------
# Front door
# ---------------------------------------------------------------------------

class DatasetUtils:
    """URI front door, mirroring the reference's ``dataset_utils`` object.

    Loads are cached process-wide (small LRU, keyed by URI + file mtime
    for local paths): a train worker loads the SAME dataset URI once
    per trial, and regenerating a CIFAR-scale synthetic set (~600MB of
    RNG) or re-decoding a zip costs about as much as a warm trial's
    entire compute — a straight trials/hour tax. Datasets are treated
    as immutable by every consumer (templates wrap them in new
    ``Dataset`` views; ``batches()`` shuffles indices, not arrays).
    """

    _CACHE_CAP = 4  # datasets can be ~GBs; keep the working set tight

    def __init__(self):
        import threading

        self._cache: "dict" = {}  # key -> Dataset; insertion order = LRU
        self._lock = threading.Lock()

    def _cache_key(self, uri: str):
        if uri.startswith("synthetic://"):
            return uri  # fully determined by the URI itself
        path = _resolve_path(uri)
        try:
            return (uri, os.path.getmtime(path))  # changed file = new key
        except OSError:
            return None  # missing/odd path: let _load raise, uncached

    def load(self, uri: str) -> Dataset:
        key = self._cache_key(uri)
        if key is not None:
            with self._lock:
                ds = self._cache.get(key)
                if ds is not None:
                    self._cache[key] = self._cache.pop(key)  # refresh LRU
                    return ds
        ds = self._load(uri)
        if key is not None:
            with self._lock:
                self._cache[key] = ds
                while len(self._cache) > self._CACHE_CAP:
                    self._cache.pop(next(iter(self._cache)))
        return ds

    def clear_cache(self) -> None:
        with self._lock:
            self._cache.clear()

    def _load(self, uri: str) -> Dataset:
        if uri.startswith("synthetic://"):
            parsed = urllib.parse.urlparse(uri)
            q = {k: int(v[0]) if v[0].lstrip("-").isdigit() else float(v[0])
                 for k, v in urllib.parse.parse_qs(parsed.query).items()}
            if parsed.netloc == "images":
                return synthetic_images(**{k: q[k] for k in q if k in
                                           ("classes", "w", "h", "c", "n", "seed", "noise", "dist", "flip")})
            if parsed.netloc == "corpus":
                kw = dict(q)
                if "len" in kw:
                    kw["length"] = kw.pop("len")
                return synthetic_corpus(**{k: kw[k] for k in kw if k in
                                           ("vocab", "tags", "n", "length", "seed", "noise", "dist")})
            if parsed.netloc == "text":
                kw = dict(q)
                if "len" in kw:
                    kw["length"] = kw.pop("len")
                return synthetic_text(**{k: kw[k] for k in kw if k in
                                         ("vocab", "classes", "n", "length", "seed", "noise", "dist")})
            raise ValueError(f"Unknown synthetic dataset: {parsed.netloc!r}")
        path = _resolve_path(uri)
        if path.endswith(".npz"):
            with np.load(path, allow_pickle=False) as z:
                kind = "corpus" if ("mask" in z or z["x"].ndim == 2) else "images"
            return _load_npz(path, kind)
        # zip: sniff for corpus vs images
        with zipfile.ZipFile(path) as zf:
            names = zf.namelist()
        if any(n.endswith(".tsv") for n in names):
            return load_dataset_of_corpus(uri)
        return load_dataset_of_image_files(uri)

    load_dataset_of_image_files = staticmethod(load_dataset_of_image_files)
    load_dataset_of_corpus = staticmethod(load_dataset_of_corpus)

    @staticmethod
    def save_npz(dataset: Dataset, path: str) -> str:
        arrays = {"x": dataset.x, "y": dataset.y}
        if dataset.mask is not None:
            arrays["mask"] = dataset.mask
        # Persist the json-able meta (vocab size, tag_map, classes):
        # without it a reloaded hashed corpus would re-derive vocab as
        # max-observed-id+1 and lose the label-space signature.
        portable = {k: v for k, v in dataset.meta.items()
                    if isinstance(v, (str, int, float, bool))}
        if isinstance(dataset.meta.get("tag_map"), dict):
            portable["tag_map"] = dataset.meta["tag_map"]
        portable["classes"] = dataset.classes
        arrays["meta_json"] = np.asarray(json.dumps(portable))
        np.savez_compressed(path, **arrays)
        return path


dataset_utils = DatasetUtils()
