"""Declarative hyperparameter ("knob") space.

Reference parity: rafiki/model/knob.py (unverified path): FixedKnob,
CategoricalKnob, IntegerKnob(min,max), FloatKnob(min,max,is_exp) with
JSON (de)serialization so the advisor can consume the space.

TPU-native additions:
  * every knob declares whether it affects compiled program shapes
    (`affects_shape`) — the trial runner uses this to key the XLA
    compilation cache and the scheduler uses it to bucket proposals so
    recompiles are amortized (SURVEY.md §7 "compile-time vs trial
    throughput").
  * `knob_config_signature` gives a stable hash of the static
    (shape-affecting) part of a knob config.
"""

from __future__ import annotations

import hashlib
import json
import math
from typing import Any, Dict, List

KnobConfig = Dict[str, "BaseKnob"]
Knobs = Dict[str, Any]


class BaseKnob:
    """A declared hyperparameter dimension."""

    #: whether a change in this knob changes traced array shapes (and
    #: therefore forces an XLA recompile of the trial program)
    affects_shape: bool = False

    def validate(self, value) -> None:
        raise NotImplementedError

    def sample(self, rng) -> Any:
        """Draw a uniform random value (numpy Generator rng)."""
        raise NotImplementedError

    def to_json(self) -> dict:
        raise NotImplementedError

    @staticmethod
    def from_json(obj: dict) -> "BaseKnob":
        ktype = obj["type"]
        cls = _KNOB_TYPES.get(ktype)
        if cls is None:
            raise ValueError(f"Unknown knob type: {ktype!r}")
        return cls._from_json(obj)

    def __repr__(self):
        return f"{type(self).__name__}({self.to_json()})"

    def __eq__(self, other):
        return type(self) is type(other) and self.to_json() == other.to_json()


class FixedKnob(BaseKnob):
    """A constant exposed through the knob system (not tuned)."""

    def __init__(self, value, affects_shape: bool = False):
        self.value = value
        self.affects_shape = affects_shape

    def validate(self, value):
        if value != self.value:
            raise ValueError(f"FixedKnob expects {self.value!r}, got {value!r}")

    def sample(self, rng):
        return self.value

    def to_json(self):
        return {"type": "fixed", "value": self.value, "affects_shape": self.affects_shape}

    @classmethod
    def _from_json(cls, obj):
        return cls(obj["value"], obj.get("affects_shape", False))


class CategoricalKnob(BaseKnob):
    def __init__(self, values: List[Any], affects_shape: bool = False):
        if not values:
            raise ValueError("CategoricalKnob needs at least one value")
        self.values = list(values)
        self.affects_shape = affects_shape

    def validate(self, value):
        if value not in self.values:
            raise ValueError(f"{value!r} not in categorical values {self.values!r}")

    def sample(self, rng):
        return self.values[int(rng.integers(0, len(self.values)))]

    def to_json(self):
        return {"type": "categorical", "values": self.values, "affects_shape": self.affects_shape}

    @classmethod
    def _from_json(cls, obj):
        return cls(obj["values"], obj.get("affects_shape", False))


class IntegerKnob(BaseKnob):
    def __init__(self, value_min: int, value_max: int, is_exp: bool = False, affects_shape: bool = False):
        if value_min > value_max:
            raise ValueError("value_min > value_max")
        if is_exp and value_min <= 0:
            raise ValueError("log-scale IntegerKnob requires value_min > 0")
        self.value_min = int(value_min)
        self.value_max = int(value_max)
        self.is_exp = is_exp
        self.affects_shape = affects_shape

    def validate(self, value):
        if not isinstance(value, (int,)) or isinstance(value, bool):
            raise ValueError(f"IntegerKnob expects int, got {type(value).__name__}")
        if not (self.value_min <= value <= self.value_max):
            raise ValueError(f"{value} outside [{self.value_min}, {self.value_max}]")

    def sample(self, rng):
        if self.is_exp:
            lo, hi = math.log(self.value_min), math.log(self.value_max)
            return int(round(math.exp(rng.uniform(lo, hi))))
        return int(rng.integers(self.value_min, self.value_max + 1))

    def to_json(self):
        return {
            "type": "integer",
            "value_min": self.value_min,
            "value_max": self.value_max,
            "is_exp": self.is_exp,
            "affects_shape": self.affects_shape,
        }

    @classmethod
    def _from_json(cls, obj):
        return cls(obj["value_min"], obj["value_max"], obj.get("is_exp", False), obj.get("affects_shape", False))


class FloatKnob(BaseKnob):
    """Float dimension; ``is_exp`` samples log-uniformly (e.g. learning rates)."""

    def __init__(self, value_min: float, value_max: float, is_exp: bool = False,
                 affects_shape: bool = False):
        if value_min > value_max:
            raise ValueError("value_min > value_max")
        if is_exp and value_min <= 0:
            raise ValueError("log-scale FloatKnob requires value_min > 0")
        self.value_min = float(value_min)
        self.value_max = float(value_max)
        self.is_exp = is_exp
        self.affects_shape = affects_shape

    def validate(self, value):
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            raise ValueError(f"FloatKnob expects float, got {type(value).__name__}")
        if not (self.value_min <= value <= self.value_max):
            raise ValueError(f"{value} outside [{self.value_min}, {self.value_max}]")

    def sample(self, rng):
        if self.is_exp:
            lo, hi = math.log(self.value_min), math.log(self.value_max)
            return float(math.exp(rng.uniform(lo, hi)))
        return float(rng.uniform(self.value_min, self.value_max))

    def to_json(self):
        return {
            "type": "float",
            "value_min": self.value_min,
            "value_max": self.value_max,
            "is_exp": self.is_exp,
            "affects_shape": self.affects_shape,
        }

    @classmethod
    def _from_json(cls, obj):
        return cls(obj["value_min"], obj["value_max"], obj.get("is_exp", False),
                   obj.get("affects_shape", False))


_KNOB_TYPES = {
    "fixed": FixedKnob,
    "categorical": CategoricalKnob,
    "integer": IntegerKnob,
    "float": FloatKnob,
}


def serialize_knob_config(knob_config: KnobConfig) -> str:
    return json.dumps({name: k.to_json() for name, k in sorted(knob_config.items())})


def deserialize_knob_config(s: str) -> KnobConfig:
    obj = json.loads(s)
    return {name: BaseKnob.from_json(kj) for name, kj in obj.items()}


def validate_knobs(knob_config: KnobConfig, knobs: Knobs) -> Knobs:
    """Check a concrete knob dict against the declared space; fill fixed knobs."""
    out = dict(knobs)
    for name, knob in knob_config.items():
        if name not in out:
            if isinstance(knob, FixedKnob):
                out[name] = knob.value
                continue
            raise ValueError(f"Missing knob {name!r}")
        knob.validate(out[name])
    extra = set(out) - set(knob_config)
    if extra:
        raise ValueError(f"Unknown knobs: {sorted(extra)}")
    return out


def sample_knobs(knob_config: KnobConfig, rng) -> Knobs:
    return {name: k.sample(rng) for name, k in knob_config.items()}


def knob_config_signature(knob_config: KnobConfig, knobs: Knobs) -> str:
    """Stable hash of the shape-affecting subset of a concrete config.

    Two trials with the same signature reuse the same compiled XLA
    program (jit cache hit), so schedulers can group proposals by
    signature to minimise compile overhead.
    """
    static = {n: knobs[n] for n, k in knob_config.items() if k.affects_shape and n in knobs}
    blob = json.dumps(static, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]
