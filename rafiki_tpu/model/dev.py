"""Model-developer harness: run the full trial loop locally.

Reference parity: ``test_model_class(...)`` in rafiki/model/model.py
(unverified path) — the reference's de-facto unit test (SURVEY.md §4):
every example model's ``__main__`` runs init → train → evaluate →
dump → load → predict against a real small dataset before upload.

``tune_model`` additionally runs a local multi-trial knob search with
an advisor — the in-process miniature of a train job.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from rafiki_tpu.model.base import BaseModel
from rafiki_tpu.model.knobs import Knobs, sample_knobs, validate_knobs


def test_model_class(model_class: type, task: str, train_dataset_uri: str,
                     test_dataset_uri: str, queries: Optional[List[Any]] = None,
                     knobs: Optional[Knobs] = None, seed: int = 0) -> Tuple[float, List[Any]]:
    # (name matches the reference API; the attribute below stops pytest
    # from collecting it as a test function when imported)
    """Run one full trial in-process; raises on contract violations.

    Returns (score, predictions). Mirrors the reference harness's
    checks: knob config sanity, train/evaluate, params round-trip, and
    predict on the given queries via a *fresh* instance.
    """
    knob_config = model_class.get_knob_config()
    if not isinstance(knob_config, dict) or not knob_config:
        raise ValueError("get_knob_config() must return a non-empty dict of knobs")
    rng = np.random.default_rng(seed)
    knobs = validate_knobs(knob_config, knobs or sample_knobs(knob_config, rng))

    model: BaseModel = model_class(**knobs)
    try:
        t0 = time.monotonic()
        model.train(train_dataset_uri)
        score = model.evaluate(test_dataset_uri)
        if not isinstance(score, float):
            raise ValueError(f"evaluate() must return float, got {type(score).__name__}")
        blob = model.dump_parameters()
        if not isinstance(blob, (bytes, bytearray)):
            raise ValueError("dump_parameters() must return bytes")
    finally:
        model.destroy()

    # Round-trip into a fresh instance, as the inference worker will.
    fresh: BaseModel = model_class(**knobs)
    try:
        fresh.load_parameters(bytes(blob))
        score2 = fresh.evaluate(test_dataset_uri)
        if abs(score2 - score) > 0.05:
            raise ValueError(
                f"params round-trip drifted: evaluate {score:.4f} -> {score2:.4f}")
        predictions = fresh.predict(list(queries)) if queries is not None else []
    finally:
        fresh.destroy()
    elapsed = time.monotonic() - t0
    print(f"[test_model_class] {model_class.__name__}: score={score:.4f} "
          f"round_trip={score2:.4f} trial_time={elapsed:.1f}s knobs={knobs}")
    return score, predictions


test_model_class.__test__ = False  # not a pytest case despite the name


def tune_model(model_class: type, train_dataset_uri: str, test_dataset_uri: str,
               total_trials: int = 5, advisor: str = "gp", seed: int = 0,
               ) -> Tuple[Knobs, float, List[Dict]]:
    """Local advisor-driven knob search (one device, one process).

    Returns (best_knobs, best_score, trial_records).
    """
    from rafiki_tpu.advisor import make_advisor

    adv = make_advisor(model_class.get_knob_config(), kind=advisor, seed=seed)
    records: List[Dict] = []
    for i in range(total_trials):
        knobs = adv.propose()
        t0 = time.monotonic()
        model = None
        try:
            model = model_class(**knobs)
            model.train(train_dataset_uri)
            score = float(model.evaluate(test_dataset_uri))
            status = "COMPLETED"
        except Exception as e:  # containment: a bad knob config must not kill the loop
            score, status = 0.0, f"ERRORED: {e}"
        finally:
            if model is not None:
                model.destroy()
        adv.feedback(score, knobs)
        records.append({"no": i, "knobs": knobs, "score": score,
                        "time_s": time.monotonic() - t0, "status": status})
    best_knobs, best_score = adv.best()
    return best_knobs, best_score, records
