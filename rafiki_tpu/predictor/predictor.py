"""Predictor core: scatter queries to workers over the bus, gather with
timeout, ensemble.

Reference parity: rafiki/predictor/predictor.py (unverified —
SURVEY.md §3.2 call stack): per query, enqueue to every live worker of
the job, await all predictions with a timeout, ensemble, respond.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, List, Optional

from rafiki_tpu.predictor.ensemble import ensemble_predictions


class Predictor:
    def __init__(self, bus, job_id: str, timeout_s: float = 10.0,
                 worker_ttl_s: float = 3.0):
        self.bus = bus
        self.job_id = job_id
        self.timeout_s = timeout_s
        # Liveness lease TTL: workers heartbeat every ~0.5s from a
        # dedicated thread (worker/inference.py), so a worker missing
        # for worker_ttl_s is dead (SIGKILL never runs remove_worker).
        # Must comfortably exceed the heartbeat period, not predict
        # latency — the lease stays fresh through a long forward.
        self.worker_ttl_s = worker_ttl_s

    def predict(self, queries: List[Any]) -> List[Any]:
        """Fan each query out to all fresh-leased workers; ensemble per
        query. A dead-but-registered worker stops being fanned out to
        (and waited on) within one lease TTL — the ensemble degrades to
        k-1 instead of every batch paying the full gather timeout."""
        workers = self.bus.get_workers(self.job_id,
                                       max_age_s=self.worker_ttl_s)
        if not workers:
            # Stale leases but live registrations: fall back to the
            # registry rather than failing — a paused/starved host must
            # degrade to slow answers, not a hard outage.
            workers = self.bus.get_workers(self.job_id)
        if not workers:
            raise RuntimeError(f"No live inference workers for job {self.job_id}")
        qids = []
        for query in queries:
            qid = uuid.uuid4().hex
            qids.append(qid)
            for w in workers:
                self.bus.add_query(w, qid, query)
        # One deadline for the whole batch: a dead-but-registered worker
        # costs at most timeout_s total, not timeout_s per query, and
        # partial gathers still ensemble whatever arrived. Past the
        # deadline, remaining queries gather non-blockingly (timeout 0)
        # so batch latency stays bounded by timeout_s regardless of
        # batch size.
        deadline = time.monotonic() + self.timeout_s
        out: List[Any] = []
        for qid in qids:
            remaining = max(0.0, deadline - time.monotonic())
            preds = self.bus.get_predictions(qid, n=len(workers), timeout=remaining)
            if not preds:
                out.append({"error": "prediction timeout"})
            else:
                out.append(ensemble_predictions([p for _, p in preds]))
        return out
