"""Predictor core: scatter queries to workers over the bus, gather with
timeout, ensemble.

Reference parity: rafiki/predictor/predictor.py (unverified —
SURVEY.md §3.2 call stack): per query, enqueue to every live worker of
the job, await all predictions with a timeout, ensemble, respond.

Liveness contract: fan-out goes ONLY to workers with a fresh heartbeat
lease. When every lease is stale the batch fails fast with
``RuntimeError("no live inference workers ...")`` — an outage must
surface as an outage (503 at the HTTP layer, ``predictor.no_live_workers``
in telemetry), not as per-query timeout errors that masquerade as slow
answers. The predictor also runs the bus janitor each batch: leases
older than ``REAP_TTL_FACTOR×TTL`` are corpses whose registrations get
deleted outright.

Gather modes: the default is wait-for-all (every fresh-leased replica,
bounded by the batch deadline). The serving gateway
(rafiki_tpu/gateway/) instead calls :meth:`predict_detailed` with
``min_replies`` — a *quorum* gather: once ``min_replies`` replicas
answered, only a short hedge grace is granted for stragglers before
ensembling, so batch p99 tracks the median replica rather than the
slowest. ``predict_detailed`` also reports per-worker reply counts,
which feed the gateway's circuit breakers.
"""

from __future__ import annotations

import dataclasses
import math
import time
import uuid
from typing import Any, Dict, List, Optional

from rafiki_tpu import telemetry
from rafiki_tpu.obs import context as trace_context
from rafiki_tpu.obs.anatomy import hops as _hops
from rafiki_tpu.obs.journal import journal as _journal
from rafiki_tpu.predictor.ensemble import ensemble_predictions

#: Straggler grace once the gather quorum arrived. Exported (not an
#: inline default) because the digital twin (rafiki_tpu/obs/twin/)
#: mirrors the quorum-gather semantics — twin and live code must read
#: the SAME constant or capacity predictions silently drift.
DEFAULT_HEDGE_GRACE_S = 0.25

#: Sentinel key wrapping a combined query list into ONE bus envelope
#: (the gateway microbatcher's wire format, docs/serving.md). Workers
#: expand it, run one forward over the flattened batch, and reply with
#: a list of per-query predictions in order.
BATCH_KEY = "__rafiki_batch__"


@dataclasses.dataclass
class GatherReport:
    """Everything the gateway needs to know about one predict batch."""

    outputs: List[Any]              # per-query ensembled predictions
    workers: List[str]              # the fan-out set actually used
    quorum: int                     # replies waited for per query
    replies: Dict[str, int]         # worker -> queries it answered in time
    timeouts: int                   # queries with ZERO replies by deadline
    hedged: int                     # queries ensembled before all replied
    elapsed_s: float                # whole-batch gather wall time

    def ok(self) -> bool:
        return self.timeouts == 0


@dataclasses.dataclass
class BatchGatherReport(GatherReport):
    """A :class:`GatherReport` for one microbatched fan-out, plus the
    raw hop chains so the gateway can stitch per-member waterfalls
    (each member re-absorbs the shared suffix under its own trace)."""

    chains: Dict[str, List[Any]] = dataclasses.field(default_factory=dict)
    dec_mark: Optional[List[Any]] = None


class Predictor:
    # A lease this many TTLs old is a corpse, not a starved worker:
    # reap its registration instead of filtering it forever.
    REAP_TTL_FACTOR = 4.0
    # Bounded stale-lease grace: when NO lease is fresh, fall back to
    # workers at most this many TTLs old — a hiccup (GC pause, beat
    # thread starved behind a compile) shouldn't 503 the job. Strictly
    # below REAP_TTL_FACTOR: a worker past the grace window is treated
    # as dead even before the janitor deletes its registration, so an
    # actual all-workers-dead outage still surfaces as RuntimeError.
    STALE_GRACE_FACTOR = 2.0

    def __init__(self, bus, job_id: str, timeout_s: float = 10.0,
                 worker_ttl_s: float = 3.0,
                 min_replies: Optional[int] = None,
                 hedge_grace_s: float = DEFAULT_HEDGE_GRACE_S,
                 program: Optional[str] = None):
        self.bus = bus
        self.job_id = job_id
        self.timeout_s = timeout_s
        # Co-hosted serving (docs/multitenancy.md): when this job's
        # model lives in a shared multi-model worker (ProgramHost),
        # every fanned-out query is tagged with the job's program id —
        # the same payload-envelope trick as BATCH_KEY — so the host
        # routes it to the right resident model. None = classic
        # one-job-per-worker wire format, untouched.
        self.program = program
        # Liveness lease TTL: workers heartbeat every ~0.5s from a
        # dedicated thread (worker/inference.py), so a worker missing
        # for worker_ttl_s is dead (SIGKILL never runs remove_worker).
        # Must comfortably exceed the heartbeat period, not predict
        # latency — the lease stays fresh through a long forward.
        self.worker_ttl_s = worker_ttl_s
        # Default gather quorum. None → wait for every fanned-out
        # replica (the conservative standalone default); the gateway
        # passes an explicit quorum (ceil(k/2) unless configured).
        self.min_replies = min_replies
        self.hedge_grace_s = hedge_grace_s

    def _tagged(self, query: Any) -> Any:
        """The query as it rides the bus: wrapped with this job's
        program tag when the job is co-hosted, verbatim otherwise."""
        if self.program is None:
            return query
        from rafiki_tpu.tenancy.hosting import wrap_query

        return wrap_query(self.program, query)

    def live_workers(self) -> List[str]:
        """Reap corpses, then return the fresh-leased worker set — or,
        when that set is empty, the BOUNDED stale fallback: workers with
        a lease younger than ``STALE_GRACE_FACTOR×TTL``. Past that, []:
        the caller raises and the outage surfaces instead of fanning
        out to corpses forever (ADVICE round 5)."""
        reap = getattr(self.bus, "reap_stale", None)
        if reap is not None:
            reap(self.REAP_TTL_FACTOR * self.worker_ttl_s, job_id=self.job_id)
        fresh = self.bus.get_workers(self.job_id, max_age_s=self.worker_ttl_s)
        if fresh:
            return fresh
        graced = self.bus.get_workers(
            self.job_id, max_age_s=self.STALE_GRACE_FACTOR * self.worker_ttl_s)
        if graced:
            telemetry.inc("predictor.stale_lease_fallback")
        return graced

    def predict(self, queries: List[Any],
                timeout_s: Optional[float] = None) -> List[Any]:
        """Fan each query out to all fresh-leased workers; ensemble per
        query. A dead-but-registered worker stops being fanned out to
        (and waited on) within one lease TTL — the ensemble degrades to
        k-1 instead of every batch paying the full gather timeout."""
        return self.predict_detailed(queries, timeout_s=timeout_s).outputs

    def predict_detailed(self, queries: List[Any],
                         workers: Optional[List[str]] = None,
                         timeout_s: Optional[float] = None,
                         min_replies: Optional[int] = None,
                         hedge_grace_s: Optional[float] = None) -> GatherReport:
        """The full-control entry the gateway uses: an explicit fan-out
        set (already breaker-filtered), a per-request gather budget,
        and a reply quorum. Returns per-worker reply counts alongside
        the ensembled outputs.

        Trace edge: the batch binds a trace context (inheriting the
        gateway's when called from one, minting a fresh id when used
        standalone) so every bus envelope, worker span and journal
        record of this batch stitches into one end-to-end trace."""
        with trace_context.trace():
            return self._predict_detailed(
                queries, workers=workers, timeout_s=timeout_s,
                min_replies=min_replies, hedge_grace_s=hedge_grace_s)

    def _predict_detailed(self, queries, workers=None, timeout_s=None,
                          min_replies=None, hedge_grace_s=None) -> GatherReport:
        if workers is None:
            workers = self.live_workers()
        if not workers:
            # Every lease is stale (or nothing registered): this job has
            # no serving capacity RIGHT NOW. Fail the batch explicitly —
            # fanning out to corpses would mask the outage as per-query
            # timeout errors and stall every caller for timeout_s.
            telemetry.inc("predictor.no_live_workers")
            raise RuntimeError(
                f"no live inference workers for job {self.job_id}")
        timeout_s = self.timeout_s if timeout_s is None else timeout_s
        if min_replies is None:
            min_replies = self.min_replies
        quorum = (len(workers) if min_replies is None
                  else max(1, min(min_replies, len(workers))))
        grace = self.hedge_grace_s if hedge_grace_s is None else hedge_grace_s
        telemetry.inc("predictor.queries", len(queries))
        telemetry.observe("predictor.fanout_workers", len(workers))
        qids = []
        for query in queries:
            qid = uuid.uuid4().hex
            qids.append(qid)
            tagged = self._tagged(query)
            for w in workers:
                self.bus.add_query(w, qid, tagged)
        # One deadline for the whole batch: a dead-but-registered worker
        # costs at most timeout_s total, not timeout_s per query, and
        # partial gathers still ensemble whatever arrived. Past the
        # deadline, remaining queries gather non-blockingly (timeout 0)
        # so batch latency stays bounded by timeout_s regardless of
        # batch size.
        t_gather = time.monotonic()
        deadline = t_gather + timeout_s
        out: List[Any] = []
        replies: Dict[str, int] = {}
        timeouts = 0
        hedged = 0
        for qid in qids:
            remaining = max(0.0, deadline - time.monotonic())
            t_q = time.monotonic()
            preds = self.bus.get_predictions(
                qid, n=len(workers), timeout=remaining,
                min_n=quorum, grace_s=grace)
            telemetry.observe("predictor.gather_quorum_s",
                              # lint: disable=RF007 — the delta IS the observation
                              time.monotonic() - t_q)
            # The quorum/hedge decision closes every hop chain: replies
            # are (worker, pred) or (worker, pred, hops) — index, don't
            # destructure, so plain replies keep working.
            dec = _hops.mark("dec")
            chains = {item[0]: list(item[2]) + [dec]
                      for item in preds if len(item) > 2 and item[2]}
            if chains:
                _hops.absorb(qid, chains)
            for item in preds:
                replies[item[0]] = replies.get(item[0], 0) + 1
            if not preds:
                timeouts += 1
                out.append({"error": "prediction timeout"})
            else:
                if len(preds) < len(workers):
                    hedged += 1
                out.append(ensemble_predictions([item[1] for item in preds]))
        # lint: disable=RF007 — observed into gather_s right below
        elapsed = time.monotonic() - t_gather
        telemetry.observe("predictor.gather_s", elapsed)
        if timeouts:
            telemetry.inc("predictor.query_timeouts", timeouts)
        if hedged:
            telemetry.inc("predictor.hedged_gathers", hedged)
        # Quorum decision record: which workers answered, who straggled
        # (docs/observability.md — breaker/quorum decisions journal).
        _journal.record("gather", "predictor.gather", job_id=self.job_id,
                        queries=len(queries), workers=list(workers),
                        quorum=quorum, replies=replies, timeouts=timeouts,
                        hedged=hedged, dur_s=round(elapsed, 6))
        from rafiki_tpu.obs.perf import slo as _slo

        _slo.maybe_tick()
        return GatherReport(outputs=out, workers=list(workers),
                            quorum=quorum, replies=replies,
                            timeouts=timeouts, hedged=hedged,
                            elapsed_s=elapsed)


    def predict_batch_detailed(self, queries: List[Any],
                               workers: Optional[List[str]] = None,
                               timeout_s: Optional[float] = None,
                               min_replies: Optional[int] = None,
                               hedge_grace_s: Optional[float] = None,
                               ) -> BatchGatherReport:
        """ONE fan-out for a whole microbatch: the combined query list
        rides a single ``BATCH_KEY`` envelope per worker instead of
        ``len(queries)`` envelopes each — the wire-tax collapse of the
        stacked serving route (docs/serving.md). Workers reply with a
        per-query prediction list; replies ensemble per query index
        across workers under the same quorum/hedge semantics as
        :meth:`predict_detailed`.

        Runs under its OWN batch trace (members re-absorb hop chains
        under their request traces); returns the gathered chains so
        the gateway can stitch per-member waterfalls."""
        with trace_context.trace():
            return self._predict_batch_detailed(
                queries, workers=workers, timeout_s=timeout_s,
                min_replies=min_replies, hedge_grace_s=hedge_grace_s)

    def _predict_batch_detailed(self, queries, workers=None, timeout_s=None,
                                min_replies=None,
                                hedge_grace_s=None) -> BatchGatherReport:
        if workers is None:
            workers = self.live_workers()
        if not workers:
            telemetry.inc("predictor.no_live_workers")
            raise RuntimeError(
                f"no live inference workers for job {self.job_id}")
        timeout_s = self.timeout_s if timeout_s is None else timeout_s
        if min_replies is None:
            min_replies = self.min_replies
        quorum = (len(workers) if min_replies is None
                  else max(1, min(min_replies, len(workers))))
        grace = self.hedge_grace_s if hedge_grace_s is None else hedge_grace_s
        n = len(queries)
        telemetry.inc("predictor.queries", n)
        telemetry.observe("predictor.fanout_workers", len(workers))
        qid = uuid.uuid4().hex
        # Tag INNER queries, not the batch envelope: the worker expands
        # BATCH_KEY before model.predict, so per-query program tags are
        # what a ProgramHost actually sees.
        payload = {BATCH_KEY: [self._tagged(q) for q in queries]}
        for w in workers:
            self.bus.add_query(w, qid, payload)
        t_gather = time.monotonic()
        preds = self.bus.get_predictions(
            qid, n=len(workers), timeout=timeout_s,
            min_n=quorum, grace_s=grace)
        telemetry.observe("predictor.gather_quorum_s",
                          # lint: disable=RF007 — the delta IS the observation
                          time.monotonic() - t_gather)
        dec = _hops.mark("dec")
        chains = {item[0]: list(item[2])
                  for item in preds if len(item) > 2 and item[2]}
        # Only well-formed replies (a per-query list of length n) can
        # scatter back; anything else is a malformed reply from that
        # worker and counts as silence.
        valid = [item for item in preds
                 if isinstance(item[1], list) and len(item[1]) == n]
        replies: Dict[str, int] = {item[0]: n for item in valid}
        hedged = n if valid and len(valid) < len(workers) else 0
        if valid:
            timeouts = 0
            out = [ensemble_predictions([item[1][i] for item in valid])
                   for i in range(n)]
        else:
            timeouts = n
            out = [{"error": "prediction timeout"}] * n
        # lint: disable=RF007 — observed into gather_s right below
        elapsed = time.monotonic() - t_gather
        telemetry.observe("predictor.gather_s", elapsed)
        if timeouts:
            telemetry.inc("predictor.query_timeouts", timeouts)
        if hedged:
            telemetry.inc("predictor.hedged_gathers", hedged)
        _journal.record("gather", "predictor.gather", job_id=self.job_id,
                        queries=n, workers=list(workers), quorum=quorum,
                        replies=replies, timeouts=timeouts, hedged=hedged,
                        batched=True, dur_s=round(elapsed, 6))
        from rafiki_tpu.obs.perf import slo as _slo

        _slo.maybe_tick()
        return BatchGatherReport(outputs=out, workers=list(workers),
                                 quorum=quorum, replies=replies,
                                 timeouts=timeouts, hedged=hedged,
                                 elapsed_s=elapsed, chains=chains,
                                 dec_mark=dec)


def default_quorum(k: int) -> int:
    """The gateway's default gather quorum: a majority of the fan-out."""
    return max(1, math.ceil(k / 2))
