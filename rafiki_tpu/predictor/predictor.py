"""Predictor core: scatter queries to workers over the bus, gather with
timeout, ensemble.

Reference parity: rafiki/predictor/predictor.py (unverified —
SURVEY.md §3.2 call stack): per query, enqueue to every live worker of
the job, await all predictions with a timeout, ensemble, respond.

Liveness contract: fan-out goes ONLY to workers with a fresh heartbeat
lease. When every lease is stale the batch fails fast with
``RuntimeError("no live inference workers ...")`` — an outage must
surface as an outage (503 at the HTTP layer, ``predictor.no_live_workers``
in telemetry), not as per-query timeout errors that masquerade as slow
answers. The predictor also runs the bus janitor each batch: leases
older than ``REAP_TTL_FACTOR×TTL`` are corpses whose registrations get
deleted outright.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, List, Optional

from rafiki_tpu import telemetry
from rafiki_tpu.predictor.ensemble import ensemble_predictions


class Predictor:
    # A lease this many TTLs old is a corpse, not a starved worker:
    # reap its registration instead of filtering it forever.
    REAP_TTL_FACTOR = 4.0

    def __init__(self, bus, job_id: str, timeout_s: float = 10.0,
                 worker_ttl_s: float = 3.0):
        self.bus = bus
        self.job_id = job_id
        self.timeout_s = timeout_s
        # Liveness lease TTL: workers heartbeat every ~0.5s from a
        # dedicated thread (worker/inference.py), so a worker missing
        # for worker_ttl_s is dead (SIGKILL never runs remove_worker).
        # Must comfortably exceed the heartbeat period, not predict
        # latency — the lease stays fresh through a long forward.
        self.worker_ttl_s = worker_ttl_s

    def predict(self, queries: List[Any]) -> List[Any]:
        """Fan each query out to all fresh-leased workers; ensemble per
        query. A dead-but-registered worker stops being fanned out to
        (and waited on) within one lease TTL — the ensemble degrades to
        k-1 instead of every batch paying the full gather timeout."""
        reap = getattr(self.bus, "reap_stale", None)
        if reap is not None:
            reap(self.REAP_TTL_FACTOR * self.worker_ttl_s, job_id=self.job_id)
        workers = self.bus.get_workers(self.job_id,
                                       max_age_s=self.worker_ttl_s)
        if not workers:
            # Every lease is stale (or nothing registered): this job has
            # no serving capacity RIGHT NOW. Fail the batch explicitly —
            # fanning out to corpses would mask the outage as per-query
            # timeout errors and stall every caller for timeout_s.
            telemetry.inc("predictor.no_live_workers")
            raise RuntimeError(
                f"no live inference workers for job {self.job_id}")
        telemetry.inc("predictor.queries", len(queries))
        telemetry.observe("predictor.fanout_workers", len(workers))
        qids = []
        for query in queries:
            qid = uuid.uuid4().hex
            qids.append(qid)
            for w in workers:
                self.bus.add_query(w, qid, query)
        # One deadline for the whole batch: a dead-but-registered worker
        # costs at most timeout_s total, not timeout_s per query, and
        # partial gathers still ensemble whatever arrived. Past the
        # deadline, remaining queries gather non-blockingly (timeout 0)
        # so batch latency stays bounded by timeout_s regardless of
        # batch size.
        t_gather = time.monotonic()
        deadline = t_gather + self.timeout_s
        out: List[Any] = []
        timeouts = 0
        for qid in qids:
            remaining = max(0.0, deadline - time.monotonic())
            preds = self.bus.get_predictions(qid, n=len(workers), timeout=remaining)
            if not preds:
                timeouts += 1
                out.append({"error": "prediction timeout"})
            else:
                out.append(ensemble_predictions([p for _, p in preds]))
        telemetry.observe("predictor.gather_s", time.monotonic() - t_gather)
        if timeouts:
            telemetry.inc("predictor.query_timeouts", timeouts)
        return out
