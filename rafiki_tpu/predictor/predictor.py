"""Predictor core: scatter queries to workers over the bus, gather with
timeout, ensemble.

Reference parity: rafiki/predictor/predictor.py (unverified —
SURVEY.md §3.2 call stack): per query, enqueue to every live worker of
the job, await all predictions with a timeout, ensemble, respond.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, List, Optional

from rafiki_tpu.predictor.ensemble import ensemble_predictions


class Predictor:
    def __init__(self, bus, job_id: str, timeout_s: float = 10.0):
        self.bus = bus
        self.job_id = job_id
        self.timeout_s = timeout_s

    def predict(self, queries: List[Any]) -> List[Any]:
        """Fan each query out to all live workers; ensemble per query."""
        workers = self.bus.get_workers(self.job_id)
        if not workers:
            raise RuntimeError(f"No live inference workers for job {self.job_id}")
        qids = []
        for query in queries:
            qid = uuid.uuid4().hex
            qids.append(qid)
            for w in workers:
                self.bus.add_query(w, qid, query)
        # One deadline for the whole batch: a dead-but-registered worker
        # costs at most timeout_s total, not timeout_s per query, and
        # partial gathers still ensemble whatever arrived. Past the
        # deadline, remaining queries gather non-blockingly (timeout 0)
        # so batch latency stays bounded by timeout_s regardless of
        # batch size.
        deadline = time.monotonic() + self.timeout_s
        out: List[Any] = []
        for qid in qids:
            remaining = max(0.0, deadline - time.monotonic())
            preds = self.bus.get_predictions(qid, n=len(workers), timeout=remaining)
            if not preds:
                out.append({"error": "prediction timeout"})
            else:
                out.append(ensemble_predictions([p for _, p in preds]))
        return out
