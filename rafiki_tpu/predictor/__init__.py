"""Predictor: the serving frontend that fans queries out to inference
workers and ensembles their predictions.

Reference parity: rafiki/predictor/ (app.py, predictor.py, ensemble.py;
unverified — SURVEY.md §3.2). The HTTP app lives in
rafiki_tpu.predictor.app; the scatter/gather core and the ensemble
math are importable without any server.
"""

from rafiki_tpu.predictor.ensemble import ensemble_predictions
from rafiki_tpu.predictor.predictor import (DEFAULT_HEDGE_GRACE_S,
                                            GatherReport, Predictor,
                                            default_quorum)

__all__ = ["DEFAULT_HEDGE_GRACE_S", "GatherReport", "Predictor",
           "default_quorum", "ensemble_predictions"]
