"""Ensembling of per-trial predictions.

Reference parity: rafiki/predictor/ensemble.py (unverified):
classification ensembles by averaging probability vectors (then the
caller argmaxes); non-numeric predictions fall back to the first
worker's answer.

Also hosts the TPU-native *stacked* ensemble forward used when all
served trials share one architecture: parameters are stacked into one
pytree with a leading trial axis and the forward is ``vmap``'d over it
— k models in one XLA program, one device round-trip (optionally
sharded over chips via a "model" mesh axis; see
rafiki_tpu.parallel.ensemble).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import numpy as np


def ensemble_predictions(predictions: Sequence[Any]) -> Any:
    """Combine k workers' predictions for ONE query."""
    preds = [p for p in predictions if not (isinstance(p, dict) and "error" in p)]
    if not preds:
        return {"error": "all workers errored", "detail": list(predictions)[:3]}
    try:
        arrs = [np.asarray(p) for p in preds]
    except (ValueError, TypeError):
        return preds[0]
    # Only *float* arrays are probability vectors we can average;
    # integer arrays are class labels / tag sequences (averaging tag
    # ids is meaningless) → fall back to the best worker's answer.
    if any(a.shape != arrs[0].shape or a.ndim == 0
           or not np.issubdtype(a.dtype, np.floating) for a in arrs):
        return preds[0]
    mean = np.mean(arrs, axis=0)
    # Re-normalize probability vectors so the ensemble is a distribution.
    if mean.ndim >= 1 and np.all(mean >= 0):
        s = mean.sum(axis=-1, keepdims=True)
        with np.errstate(invalid="ignore", divide="ignore"):
            mean = np.where(s > 0, mean / s, mean)
    return mean.tolist()
