"""Ensembling of per-trial predictions.

Reference parity: rafiki/predictor/ensemble.py (unverified):
classification ensembles by averaging probability vectors (then the
caller argmaxes); non-numeric predictions fall back to the first
worker's answer.

Also hosts the TPU-native *stacked* ensemble forward used when all
served trials share one architecture: parameters are stacked into one
pytree with a leading trial axis and the forward is ``vmap``'d over it
— k models in one XLA program, one device round-trip (optionally
sharded over chips via a "model" mesh axis; see
rafiki_tpu.parallel.ensemble).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import numpy as np


def renormalize_probs(mean: np.ndarray) -> np.ndarray:
    """Re-normalize probability vectors so the ensemble is a
    distribution. Shared by the host-side mean below AND the stacked
    device-resident path (rafiki_tpu/parallel/serving.py) — both
    routes MUST run the identical op sequence or the stacked-vs-serial
    bit-parity contract breaks."""
    if mean.ndim >= 1 and np.all(mean >= 0):
        s = mean.sum(axis=-1, keepdims=True)
        with np.errstate(invalid="ignore", divide="ignore"):
            mean = np.where(s > 0, mean / s, mean)
    return mean


def ensemble_predictions(predictions: Sequence[Any]) -> Any:
    """Combine k workers' predictions for ONE query."""
    preds = [p for p in predictions if not (isinstance(p, dict) and "error" in p)]
    if not preds:
        return {"error": "all workers errored", "detail": list(predictions)[:3]}
    try:
        arrs = [np.asarray(p) for p in preds]
    except (ValueError, TypeError):
        return preds[0]
    # Only *float* arrays are probability vectors we can average;
    # integer arrays are class labels / tag sequences (averaging tag
    # ids is meaningless) → fall back to the best worker's answer.
    if any(a.shape != arrs[0].shape or a.ndim == 0
           or not np.issubdtype(a.dtype, np.floating) for a in arrs):
        return preds[0]
    # Models emit float32 probabilities; replies arrive as JSON floats
    # (float64 carrying exact float32 values). Cast back to float32 so
    # the mean is computed in the SAME dtype the stacked on-device
    # ensemble uses — the bit-parity contract between the two routes.
    mean = renormalize_probs(np.mean(
        np.stack([a.astype(np.float32) for a in arrs]), axis=0))
    return mean.tolist()
