"""Predictor HTTP frontend: the published ``POST /predict`` endpoint.

Reference parity: rafiki/predictor/app.py (unverified — SURVEY.md
§3.2): each inference job publishes one predictor port; external
clients POST queries there and get ensembled predictions. The
services manager starts one of these per inference job (loopback by
default; bind 0.0.0.0 for external traffic) and records host:port in
the inference-job row so clients can discover it.
"""

from __future__ import annotations

import json
from typing import Any, Optional

from werkzeug.wrappers import Request, Response

from rafiki_tpu.predictor.predictor import Predictor
from rafiki_tpu.utils.jsonable import jsonable as _jsonable


class PredictorApp:
    """WSGI app: POST /predict {"queries": [...]}, GET /healthz,
    GET /metrics (read-only telemetry snapshot — spans, counters,
    queue-depth gauges, gather-latency histograms of THIS process)."""

    def __init__(self, predictor: Predictor):
        self.predictor = predictor

    def __call__(self, environ, start_response):
        request = Request(environ)
        try:
            if request.path == "/healthz" and request.method == "GET":
                response = self._json({"status": "ok"})
            elif request.path == "/metrics" and request.method == "GET":
                from rafiki_tpu import telemetry

                response = self._json(telemetry.snapshot())
            elif request.path == "/predict" and request.method == "POST":
                body = request.get_json(force=True, silent=True) or {}
                queries = body.get("queries")
                if not isinstance(queries, list):
                    response = self._json(
                        {"error": "Body must be {\"queries\": [...]}"}, 400)
                else:
                    preds = self.predictor.predict(queries)
                    response = self._json({"predictions": _jsonable(preds)})
            else:
                response = self._json({"error": "Not found"}, 404)
        except RuntimeError as e:  # e.g. no live workers
            response = self._json({"error": str(e)}, 503)
        except Exception as e:
            response = self._json({"error": f"{type(e).__name__}: {e}"}, 500)
        return response(environ, start_response)

    @staticmethod
    def _json(data: Any, status: int = 200) -> Response:
        return Response(json.dumps(data), status=status,
                        mimetype="application/json")


def start_predictor_server(predictor: Predictor, host: str = "127.0.0.1",
                           port: int = 0):
    """Serve a predictor in a daemon thread; returns (server, "host:port")."""
    import threading

    from werkzeug.serving import make_server

    server = make_server(host, port, PredictorApp(predictor), threaded=True)
    threading.Thread(target=server.serve_forever, name="predictor-http",
                     daemon=True).start()
    return server, f"{host}:{server.server_port}"
