"""Predictor HTTP frontend: the published ``POST /predict`` endpoint.

Reference parity: rafiki/predictor/app.py (unverified — SURVEY.md
§3.2): each inference job publishes one predictor port; external
clients POST queries there and get ensembled predictions. The
services manager starts one of these per inference job (loopback by
default; bind 0.0.0.0 for external traffic) and records host:port in
the inference-job row so clients can discover it.

The app no longer talks to the Predictor directly: every request goes
through the serving Gateway (rafiki_tpu/gateway/), which owns
admission control, deadlines, quorum fan-out, circuit breakers and
drain. Status mapping:

  200  admitted and answered
  400  malformed body (not JSON / queries not a list)
  413  more queries than ``max_queries_per_request``
  429  shed by admission control (``Retry-After`` header set)
  503  no live workers, or gateway draining (``Retry-After`` set)
"""

from __future__ import annotations

import json
import math
from typing import Any, Union

from werkzeug.wrappers import Request, Response

from rafiki_tpu.gateway import Gateway, ShedError
from rafiki_tpu.predictor.predictor import Predictor
from rafiki_tpu.utils.jsonable import jsonable as _jsonable


class PredictorApp:
    """WSGI app: POST /predict {"queries": [...], "deadline_s"?: float},
    GET /healthz (503 + draining while the gateway drains),
    GET /gateway (admission/breaker/routing stats),
    POST /drain (stop admitting, flush inflight),
    GET /metrics (read-only telemetry snapshot — spans, counters,
    queue-depth gauges, gather-latency histograms of THIS process)."""

    def __init__(self, target: Union[Gateway, Predictor]):
        # Accept a bare Predictor for back-compat with direct callers
        # (tests, notebooks): it gets a default-config Gateway.
        self.gateway = target if isinstance(target, Gateway) else Gateway(target)
        self.predictor = self.gateway.predictor

    def __call__(self, environ, start_response):
        request = Request(environ)
        try:
            if request.path == "/healthz" and request.method == "GET":
                if self.gateway.draining:
                    response = self._json({"status": "draining"}, 503)
                else:
                    response = self._json({"status": "ok"})
            elif request.path == "/metrics" and request.method == "GET":
                from rafiki_tpu import telemetry

                if request.args.get("format") == "prom":
                    from rafiki_tpu.obs import prom

                    response = Response(
                        prom.to_prometheus(telemetry.snapshot()),
                        mimetype="text/plain; version=0.0.4")
                else:
                    response = self._json(telemetry.snapshot())
            elif request.path == "/gateway" and request.method == "GET":
                response = self._json(self.gateway.stats())
            elif request.path == "/drain" and request.method == "POST":
                flushed = self.gateway.drain()
                response = self._json({"status": "draining",
                                       "flushed": flushed})
            elif request.path == "/predict" and request.method == "POST":
                response = self._predict(request)
            else:
                response = self._json({"error": "Not found"}, 404)
        except ShedError as e:
            status = 503 if e.reason == "draining" else 429
            response = self._json({"error": str(e), "reason": e.reason},
                                  status)
            response.headers["Retry-After"] = str(
                max(1, math.ceil(e.retry_after_s)))
        except RuntimeError as e:  # e.g. no live workers
            response = self._json({"error": str(e)}, 503)
        except Exception as e:
            response = self._json({"error": f"{type(e).__name__}: {e}"}, 500)
        return response(environ, start_response)

    def _predict(self, request: Request) -> Response:
        body = request.get_json(force=True, silent=True)
        if not isinstance(body, dict):
            return self._json(
                {"error": "Body must be {\"queries\": [...]}"}, 400)
        queries = body.get("queries")
        if not isinstance(queries, list):
            return self._json(
                {"error": "Body must be {\"queries\": [...]}"}, 400)
        cap = self.gateway.cfg.max_queries_per_request
        if len(queries) > cap:
            return self._json(
                {"error": f"{len(queries)} queries exceeds the "
                          f"per-request limit of {cap}"}, 413)
        deadline_s = body.get("deadline_s")
        if deadline_s is not None:
            try:
                deadline_s = float(deadline_s)
            except (TypeError, ValueError):
                return self._json({"error": "deadline_s must be a number"},
                                  400)
            if deadline_s <= 0:
                return self._json({"error": "deadline_s must be > 0"}, 400)
        # Trace propagation in: a client (or upstream proxy) may pin
        # the trace id; otherwise the gateway mints one. Either way the
        # id is echoed back so callers can `obs trace <id>` the request.
        trace_id = request.headers.get("X-Rafiki-Trace-Id")
        # Tenant propagation in (docs/multitenancy.md): the caller's
        # tenant id (or the body's "tenant" key) charges admission,
        # shed and latency accounting to that tenant when the gateway
        # has a TenantFabric. Absent header = anonymous bucket.
        tenant = (request.headers.get("X-Rafiki-Tenant")
                  or body.get("tenant"))
        from rafiki_tpu.obs import context as trace_context

        with trace_context.trace(trace_id) as tid:
            preds = self.gateway.predict(queries, deadline_s=deadline_s,
                                         tenant=tenant)
        response = self._json({"predictions": _jsonable(preds),
                               "trace_id": tid})
        response.headers["X-Rafiki-Trace-Id"] = tid
        return response

    @staticmethod
    def _json(data: Any, status: int = 200) -> Response:
        return Response(json.dumps(data), status=status,
                        mimetype="application/json")


def start_predictor_server(target: Union[Gateway, Predictor],
                           host: str = "127.0.0.1", port: int = 0):
    """Serve a gateway (or bare predictor) in a daemon thread; returns
    (server, "host:port")."""
    import threading

    from werkzeug.serving import make_server

    server = make_server(host, port, PredictorApp(target), threaded=True)
    threading.Thread(target=server.serve_forever, name="predictor-http",
                     daemon=True).start()
    return server, f"{host}:{server.server_port}"
