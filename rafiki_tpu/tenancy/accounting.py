"""Bounded per-tenant accounting (docs/multitenancy.md).

Everything the fleet knows about a tenant at runtime lives here:
admit/shed counters, a rolling latency window, and the SLO-burn ratio
against the tenant's tier budget. Two design rules:

* **Bounded state.** Tenant ids arrive from the network; an
  adversarial stream of fresh ids must not grow server memory. Every
  per-tenant structure in this package hangs off
  :class:`BoundedTenantMap` — an LRU-evicting dict capped at
  ``RAFIKI_TENANT_MAX_TENANTS`` — which is also the eviction idiom the
  RF017 checker (unbounded-per-tenant-state) looks for.
* **Journal-first evidence.** The ``noisy-neighbor-shed`` chaos gate
  proves isolation *from per-tenant journals alone*: ``tenant/admit``
  (admission grant, with the wait), ``tenant/request`` (completion,
  with e2e latency), ``tenant/shed`` (denial, with the reason), and a
  ``tenant/summary`` counter flush that ``obs tenants --check``
  reconciles against the per-record tallies.

Metrics: literal aggregates ``serving.tenant.admitted`` /
``serving.tenant.shed`` plus the ``serving.tenant.burn`` gauge (max
burn across tenants — the arbiter lane's pressure input), with
per-tenant dynamic names under the bounded-set suppression precedent
the gateway's shed-reason counters established.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional

from rafiki_tpu import telemetry
from rafiki_tpu.obs.journal import journal as _journal
from rafiki_tpu.tenancy.qos import TenantDirectory

#: Rolling latency window per tenant — enough for a stable p99 at
#: smoke scale without unbounded growth.
LATENCY_WINDOW = 512


class BoundedTenantMap:
    """An LRU-evicting ``tenant_id -> value`` map with a hard cap.

    The single sanctioned container for per-tenant runtime state
    (RF017): inserting tenant ``cap+1`` evicts the least-recently
    touched entry, so memory is O(cap) no matter how many distinct
    tenant ids a client invents. Reads refresh recency.
    """

    def __init__(self, cap: int, factory: Optional[Callable[[], Any]] = None):
        self.cap = max(1, int(cap))
        self._factory = factory
        self._data: "OrderedDict[str, Any]" = OrderedDict()

    def get(self, tenant: str) -> Any:
        """The tenant's slot, created via the factory on first touch."""
        slot = self._data.get(tenant)
        if slot is None:
            if self._factory is None:
                return None
            slot = self._factory()
            self._data[tenant] = slot
            while len(self._data) > self.cap:
                evicted, _ = self._data.popitem(last=False)
                telemetry.inc("tenant.accounting_evictions")
        else:
            self._data.move_to_end(tenant)
        return slot

    def peek(self, tenant: str) -> Any:
        """Read without creating (and without refreshing recency)."""
        return self._data.get(tenant)

    def items(self):
        return list(self._data.items())

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, tenant: str) -> bool:
        return tenant in self._data


class _TenantStats:
    __slots__ = ("admitted", "shed", "ok", "errors", "shed_reasons",
                 "latencies_s", "waited_s")

    def __init__(self):
        self.admitted = 0
        self.shed = 0
        self.ok = 0
        self.errors = 0
        self.shed_reasons: Dict[str, int] = {}
        self.latencies_s: deque = deque(maxlen=LATENCY_WINDOW)
        self.waited_s = 0.0


def _p99(values: List[float]) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    return ordered[min(len(ordered) - 1, int(0.99 * len(ordered)))]


def _p50(values: List[float]) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    return ordered[len(ordered) // 2]


class TenantAccounting:
    """Per-tenant admit/shed/latency/burn ledger behind a lock.

    One instance per gateway; the gateway calls :meth:`admitted`,
    :meth:`completed` and :meth:`shed` on the request path and
    :meth:`flush` at drain. ``collector()`` registers under the
    ``tenants`` telemetry section so the Prometheus exposition carries
    the per-tenant serving state.
    """

    def __init__(self, directory: TenantDirectory):
        self.directory = directory
        self._lock = threading.Lock()
        self._stats = BoundedTenantMap(directory.max_tenants, _TenantStats)

    # -- request path --------------------------------------------------------

    def admitted(self, tenant: str, waited_s: float) -> None:
        tier = self.directory.tier_of(tenant)
        with self._lock:
            st = self._stats.get(tenant)
            st.admitted += 1
            st.waited_s += waited_s
        telemetry.inc("serving.tenant.admitted")
        _journal.record("tenant", "admit", tenant=tenant, tier=tier.name,
                        waited_s=round(waited_s, 6))

    def completed(self, tenant: str, e2e_s: float, ok: bool) -> None:
        with self._lock:
            st = self._stats.get(tenant)
            st.latencies_s.append(e2e_s)
            if ok:
                st.ok += 1
            else:
                st.errors += 1
        telemetry.set_gauge("serving.tenant.burn", self.max_burn())
        _journal.record("tenant", "request", tenant=tenant,
                        e2e_s=round(e2e_s, 6), ok=bool(ok))

    def shed(self, tenant: str, reason: str) -> None:
        with self._lock:
            st = self._stats.get(tenant)
            st.shed += 1
            st.shed_reasons[reason] = st.shed_reasons.get(reason, 0) + 1
        telemetry.inc("serving.tenant.shed")
        # lint: disable=RF008 — tenant set capped by RAFIKI_TENANT_MAX_TENANTS under the literal aggregate
        telemetry.inc(f"serving.tenant.shed_{self.directory.tier_of(tenant).name}")
        _journal.record("tenant", "shed", tenant=tenant, reason=reason,
                        tier=self.directory.tier_of(tenant).name)

    # -- burn ----------------------------------------------------------------

    def burn(self, tenant: str) -> float:
        """p99 over the tier's budget: >1.0 means the tenant's latency
        promise is burning."""
        tier = self.directory.tier_of(tenant)
        with self._lock:
            st = self._stats.peek(tenant)
            lat = list(st.latencies_s) if st is not None else []
        if not lat:
            return 0.0
        return (_p99(lat) * 1000.0) / max(tier.p99_budget_ms, 1e-9)

    def max_burn(self) -> float:
        with self._lock:
            tenants = [t for t, _ in self._stats.items()]
        return max((self.burn(t) for t in tenants), default=0.0)

    def shed_rate(self) -> float:
        """Fleet-wide tenant shed fraction (arbiter pressure input)."""
        with self._lock:
            admitted = sum(st.admitted for _, st in self._stats.items())
            shed = sum(st.shed for _, st in self._stats.items())
        total = admitted + shed
        return (shed / total) if total else 0.0

    # -- introspection -------------------------------------------------------

    def per_tenant(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            rows = {}
            for tenant, st in self._stats.items():
                lat = list(st.latencies_s)
                rows[tenant] = {
                    "tier": self.directory.tier_of(tenant).name,
                    "admitted": st.admitted,
                    "ok": st.ok,
                    "errors": st.errors,
                    "shed": st.shed,
                    "shed_reasons": dict(st.shed_reasons),
                    "p50_ms": round(_p50(lat) * 1000.0, 3),
                    "p99_ms": round(_p99(lat) * 1000.0, 3),
                    "shed_rate": round(
                        st.shed / (st.admitted + st.shed), 4)
                        if (st.admitted + st.shed) else 0.0,
                }
        for tenant, row in rows.items():
            row["burn"] = round(self.burn(tenant), 4)
        return rows

    def collector(self) -> Dict[str, Any]:
        rows = self.per_tenant()
        return {
            "tracked": len(rows),
            "admitted": telemetry.get_counter("serving.tenant.admitted"),
            "shed": telemetry.get_counter("serving.tenant.shed"),
            "max_burn": round(self.max_burn(), 4),
            "per_tenant": rows,
        }

    def flush(self) -> None:
        """Journal the counter summary (``tenant/summary``) —
        ``obs tenants --check`` reconciles these totals against the
        per-record admit/shed tallies."""
        rows = self.per_tenant()
        _journal.record("tenant", "summary",
                        tenants={t: {"admitted": r["admitted"],
                                     "shed": r["shed"],
                                     "p99_ms": r["p99_ms"],
                                     "burn": r["burn"]}
                                 for t, r in rows.items()})
