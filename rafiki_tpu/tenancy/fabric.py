"""TenantFabric: the bundle a gateway opts into tenancy with.

One object wiring the three per-gateway tenancy pieces together —
directory (who maps to which tier), weighted-fair admission
(built against the gateway's capacity knobs), and bounded accounting
(metrics + journals). ``Gateway(..., tenancy=TenantFabric())`` is the
whole opt-in; a gateway without a fabric behaves exactly as before.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from rafiki_tpu import telemetry
from rafiki_tpu.tenancy.accounting import TenantAccounting
from rafiki_tpu.tenancy.admission import TenantAdmissionController
from rafiki_tpu.tenancy.qos import TenantDirectory


class TenantFabric:
    """Directory + admission + accounting for one gateway."""

    def __init__(self, directory: Optional[TenantDirectory] = None,
                 register_collector: bool = True):
        self.directory = directory or TenantDirectory()
        self.accounting = TenantAccounting(self.directory)
        self.admission: Optional[TenantAdmissionController] = None
        if register_collector:
            telemetry.register_collector("tenants",
                                         self.accounting.collector)

    def build_admission(self, max_inflight: int,
                        max_queue: int) -> TenantAdmissionController:
        """The gateway calls this in place of constructing a plain
        AdmissionController — same capacity knobs, tenant-aware."""
        self.admission = TenantAdmissionController(
            self.directory, max_inflight=max_inflight, max_queue=max_queue)
        return self.admission

    def sensors(self) -> Dict[str, Any]:
        """Tenant additions to the gateway sensor snapshot (the
        arbiter lane's pressure inputs)."""
        return {
            "tenant_burn": round(self.accounting.max_burn(), 4),
            "tenant_shed_rate": round(self.accounting.shed_rate(), 4),
            "tenants_tracked": len(self.accounting.per_tenant()),
        }
