"""Fleet-level tenant arbitration (docs/multitenancy.md).

Two pieces, both riding infrastructure that already exists:

* :func:`tenant_pressure` — the autoscale lane pressure function for a
  shared multi-tenant fleet. Same shape as the inference lane's
  (max-of-components, 1.0 = at the line) but reading the TENANT
  aggregates: worst per-tenant SLO burn, queue fraction, and the
  weighted tenant shed rate. Wire it with
  ``LaneSpec("tenants", pressure_fn=tenant_pressure)`` — the
  controller's hysteresis/cooldown/flap machinery applies unchanged.
* :class:`JobAdmissionGate` — twin-gated admission of NEW jobs onto a
  shared fleet. Before the services manager creates a job's serving
  stack, the gate simulates the fleet's current per-tenant load PLUS
  the newcomer's forecast rate through the serving twin (per-tenant
  weighted admission model, engine.py) and REJECTS the job when the
  forecast breaches an existing tenant's p99 budget that the baseline
  kept. Every verdict — admit or reject, with both forecasts —
  journals ``tenancy/arbiter``, so fleet-shape decisions replay like
  autoscale decisions do.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from rafiki_tpu import telemetry
from rafiki_tpu.obs.journal import journal as _journal
from rafiki_tpu.tenancy.qos import TIERS, DEFAULT_TIER


def tenant_pressure(sensors: Dict[str, Any]) -> Tuple[Optional[float], str]:
    """Tenant-lane pressure: the max of worst per-tenant burn, queue
    fraction, and (weighted) tenant shed rate. Mirrors
    ``autoscale.controller.inference_pressure`` so the lane drops into
    the existing controller unchanged."""
    components = {
        "tenant_burn": float(sensors.get("tenant_burn") or 0.0),
        "queue_frac": float(sensors.get("queue_frac") or 0.0),
        "tenant_shed": float(sensors.get("tenant_shed_rate") or 0.0) * 10.0,
    }
    reason = max(components, key=lambda k: components[k])
    return components[reason], reason


class ModelUnvalidated(RuntimeError):
    """The twin failed per-tenant validation against the capture a
    :class:`JobAdmissionGate` was about to forecast with."""

    def __init__(self, source: str, report: Dict[str, Any]):
        self.report = report
        super().__init__(
            f"twin failed per-tenant validation against {source} — "
            f"refusing to arbitrate with an unvalidated model: "
            f"{report.get('tenants')}")


class JobRejected(RuntimeError):
    """A new job's forecast breaches an existing tenant's SLO."""

    def __init__(self, job_id: str, detail: Dict[str, Any]):
        super().__init__(f"job {job_id} rejected by tenant arbiter: "
                         f"{detail.get('breaches')}")
        self.detail = detail


class JobAdmissionGate:
    """Forecast-before-admit for new jobs on a shared tenant fleet.

    ``cal`` is a twin :class:`~rafiki_tpu.obs.twin.calibration.
    Calibration` (captured from the live fleet's journals);
    ``base_cfg`` the matching ``TwinConfig``. ``existing`` maps tenant
    id → ``(tier_name, qps)`` for the load already on the fleet.
    """

    def __init__(self, cal: Any, base_cfg: Any,
                 existing: Optional[Dict[str, Tuple[str, float]]] = None,
                 horizon_s: float = 2.0, seed: int = 0):
        self.cal = cal
        self.base_cfg = base_cfg
        self.existing: Dict[str, Tuple[str, float]] = dict(existing or {})
        self.horizon_s = horizon_s
        self.seed = seed

    @classmethod
    def from_capture(cls, log_dir, horizon_s: float = 2.0, seed: int = 0,
                     require_valid: bool = True,
                     tolerance: Optional[float] = None
                     ) -> "JobAdmissionGate":
        """Build the gate straight from a ``bench_serving --tenants``
        capture: calibration, gateway knobs, AND the existing
        per-tenant load (tier + observed qps) all come from the same
        journal directory. With ``require_valid`` (the default) the
        twin's weighted-admission model must first pass
        :func:`~rafiki_tpu.obs.twin.validate.validate_tenants` against
        that capture — a gate whose forecasts disagree with the very
        run that calibrated it has no business vetoing jobs."""
        from rafiki_tpu.obs import journal as journal_mod
        from rafiki_tpu.obs.twin.calibration import Calibration
        from rafiki_tpu.obs.twin.engine import TwinConfig
        from rafiki_tpu.obs.twin import validate as validate_mod

        if require_valid:
            kwargs = {} if tolerance is None else {"tolerance": tolerance}
            report = validate_mod.validate_tenants(log_dir, seed=seed,
                                                   **kwargs)
            if not report["ok"]:
                raise ModelUnvalidated(str(log_dir), report)
        records = journal_mod.read_dir(log_dir)
        cal = Calibration.from_journal_dir(log_dir)
        arrivals, lats, tiers = (
            validate_mod.tenant_measured_from_records(records))
        span = (arrivals[-1][0] - arrivals[0][0]) if len(arrivals) > 1 else 0
        existing = {}
        for tenant, xs in lats.items():
            if tenant is None:
                continue
            qps = (len(xs) / span) if span else float(len(xs))
            existing[tenant] = (tiers.get(tenant, DEFAULT_TIER), qps)
        return cls(cal, TwinConfig.from_calibration(cal),
                   existing=existing, horizon_s=horizon_s, seed=seed)

    # -- load shapes ---------------------------------------------------------

    def _arrivals(self, load: Dict[str, Tuple[str, float]]):
        """Deterministic uniform per-tenant arrival trains over the
        horizon, merged by time (ties broken by tenant name so the
        event order is stable)."""
        out = []
        for tenant in sorted(load):
            _, qps = load[tenant]
            n = max(1, int(qps * self.horizon_s))
            step = self.horizon_s / n
            for i in range(n):
                out.append((i * step, 1, tenant))
        out.sort(key=lambda a: (a[0], a[2]))
        return out

    def _tenant_classes(self, load: Dict[str, Tuple[str, float]]):
        tiers = TIERS()
        return {tenant: {"weight": tiers.get(tier, tiers[DEFAULT_TIER]).weight}
                for tenant, (tier, _) in load.items()}

    def _budget_ms(self, tier: str) -> float:
        tiers = TIERS()
        return tiers.get(tier, tiers[DEFAULT_TIER]).p99_budget_ms

    def _forecast(self, load: Dict[str, Tuple[str, float]]) -> Dict[str, Any]:
        import dataclasses

        from rafiki_tpu.obs.twin.engine import simulate

        cfg = dataclasses.replace(self.base_cfg,
                                  tenants=self._tenant_classes(load))
        return simulate(self.cal, cfg, self._arrivals(load), seed=self.seed)

    # -- the gate ------------------------------------------------------------

    def admit_job(self, job_id: str, tenant: str, tier: str,
                  expected_qps: float, enforce: bool = True
                  ) -> Dict[str, Any]:
        """Forecast the fleet with ``tenant``'s new job added. Returns
        the journaled verdict dict; raises :class:`JobRejected` when
        ``enforce`` and an existing tenant's forecast p99 breaches its
        budget that the baseline forecast kept."""
        baseline = (self._forecast(self.existing)
                    if self.existing else None)
        proposed_load = dict(self.existing)
        prior_tier, prior_qps = proposed_load.get(tenant, (tier, 0.0))
        proposed_load[tenant] = (tier, prior_qps + max(0.0, expected_qps))
        proposed = self._forecast(proposed_load)
        breaches = []
        base_tenants = (baseline or {}).get("tenants", {})
        for other, (other_tier, _) in self.existing.items():
            if other == tenant:
                continue
            budget = self._budget_ms(other_tier)
            # Budgets gate CALLER-observed latency (full_p99_ms:
            # admission wait + service) — post-admission p99 stays low
            # under a flood precisely because the quota pushes the
            # damage into queue wait.
            fore = (proposed.get("tenants", {}).get(other, {})
                    .get("full_p99_ms"))
            base = base_tenants.get(other, {}).get("full_p99_ms")
            if fore is not None and fore > budget and (
                    base is None or base <= budget):
                breaches.append({"tenant": other, "tier": other_tier,
                                 "forecast_p99_ms": fore,
                                 "baseline_p99_ms": base,
                                 "budget_ms": budget})
        verdict = {
            "job_id": job_id,
            "tenant": tenant,
            "tier": tier,
            "expected_qps": expected_qps,
            "admit": not breaches,
            "breaches": breaches,
            "forecast_p99_ms": proposed.get("p99_ms"),
            "forecast_shed_rate": proposed.get("shed_rate"),
            "baseline_p99_ms": (baseline or {}).get("p99_ms"),
        }
        _journal.record("tenancy", "arbiter", **verdict)
        if breaches:
            telemetry.inc("tenancy.jobs_rejected")
            if enforce:
                raise JobRejected(job_id, verdict)
        else:
            telemetry.inc("tenancy.jobs_admitted")
            self.existing = proposed_load
        return verdict
