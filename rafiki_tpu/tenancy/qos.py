"""QoS classes and the tenant directory (docs/multitenancy.md).

A *tenant* is the unit of isolation: one user/team/job stream sharing
the fleet with everyone else. Every tenant maps to one of three QoS
tiers, mirroring the deadline tiers real MLaaS fleets sell:

    gold   interactive traffic — short deadline, largest admission
           weight, tight p99 budget
    std    default tier — the balanced middle
    batch  throughput traffic — long deadline, smallest weight, loose
           budget; first to shed under pressure

A tier is three numbers. ``weight`` is the weighted-fair admission
share (admission.py grants capacity to the waiting tenant with the
lowest inflight/weight charge, so a weight-4 gold tenant gets 4× a
weight-1 batch tenant's share under contention — not absolute
priority: batch still progresses). ``deadline_s`` is the default
request deadline when the caller doesn't send one. ``p99_budget_ms``
is the latency promise per tier — per-tenant burn accounting and the
``noisy-neighbor-shed`` chaos gate both measure against it.

Knobs (defaults in :data:`TIERS`, one-liners in docs/knobs.md):

    RAFIKI_TENANT_TIERS          tenant→tier map, "alice=gold,bob=batch"
    RAFIKI_TENANT_DEFAULT_TIER   tier for unmapped tenants (std)
    RAFIKI_TENANT_GOLD_WEIGHT    admission weight per tier
    RAFIKI_TENANT_STD_WEIGHT
    RAFIKI_TENANT_BATCH_WEIGHT
    RAFIKI_TENANT_QUOTA_FRAC     per-tenant cap as a fraction of the
                                 gateway's inflight/queue capacity
    RAFIKI_TENANT_MAX_TENANTS    bound on tracked per-tenant state
    RAFIKI_TENANT_UNWEIGHTED     polarity knob: disable weighting and
                                 quotas (tenancy smoke's doctored run)
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional

ENV_PREFIX = "RAFIKI_TENANT_"

#: Tenant charged when the caller sent no tenant id: anonymous traffic
#: shares one bucket (and one quota) instead of bypassing isolation.
#: Lives here (the dependency-free leaf of the package) so the gateway
#: can import it without a tenancy.admission ↔ gateway.gateway cycle.
ANON_TENANT = "anon"

#: Bound on per-tenant accounting/admission state fleet-wide. Tenants
#: beyond the cap still get served (at the default tier) — only their
#: per-tenant counters are subject to LRU eviction (accounting.py).
DEFAULT_MAX_TENANTS = 64

#: Per-tenant cap as a fraction of gateway capacity: with 0.5, one
#: tenant can use at most half the queue and half the inflight slots,
#: so a flood leaves the other half to everyone else.
DEFAULT_QUOTA_FRAC = 0.5


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(ENV_PREFIX + name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(ENV_PREFIX + name)
    if raw is None:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def unweighted() -> bool:
    """Whether weighted-fair admission is DISABLED (quotas off, all
    weights equal) — exists only so the tenancy smoke can run the
    doctored polarity and watch the victim-p99 gate fail."""
    return os.environ.get(ENV_PREFIX + "UNWEIGHTED", "").lower() in (
        "1", "true", "yes", "on")


@dataclasses.dataclass(frozen=True)
class QosClass:
    """One QoS tier: the admission weight, the default deadline, and
    the latency promise the tier sells."""

    name: str
    weight: float
    deadline_s: float
    p99_budget_ms: float


def TIERS() -> Dict[str, QosClass]:
    """The three tiers with env-overridable weights. A function, not a
    module constant, so tests and the smoke's doctored polarity can
    flip knobs per-process without import-order traps."""
    if unweighted():
        gold = std = batch = 1.0
    else:
        gold = _env_float("GOLD_WEIGHT", 4.0)
        std = _env_float("STD_WEIGHT", 2.0)
        batch = _env_float("BATCH_WEIGHT", 1.0)
    return {
        "gold": QosClass("gold", weight=gold, deadline_s=2.0,
                         p99_budget_ms=200.0),
        "std": QosClass("std", weight=std, deadline_s=5.0,
                        p99_budget_ms=500.0),
        "batch": QosClass("batch", weight=batch, deadline_s=30.0,
                          p99_budget_ms=5000.0),
    }


DEFAULT_TIER = "std"


class TenantDirectory:
    """Resolves ``tenant_id`` → :class:`QosClass`.

    The mapping comes from RAFIKI_TENANT_TIERS ("alice=gold,bob=batch")
    or an explicit dict; unmapped tenants get the default tier. The
    directory is immutable after construction — per-tenant RUNTIME
    state (counters, queues) lives in accounting/admission behind
    bounded maps, never here, so an adversarial stream of fresh tenant
    ids cannot grow this object.
    """

    def __init__(self, tiers: Optional[Dict[str, str]] = None,
                 default_tier: Optional[str] = None,
                 quota_frac: Optional[float] = None,
                 max_tenants: Optional[int] = None):
        self._classes = TIERS()
        self.default_tier = (default_tier
                             or os.environ.get(ENV_PREFIX + "DEFAULT_TIER",
                                               DEFAULT_TIER))
        if self.default_tier not in self._classes:
            self.default_tier = DEFAULT_TIER
        self._map: Dict[str, str] = {}
        raw = (tiers if tiers is not None
               else _parse_tiers(os.environ.get(ENV_PREFIX + "TIERS", "")))
        for tenant, tier in raw.items():
            if tier in self._classes:
                # lint: disable=RF017 — construction-time only: keys come from the operator's tiers config, never the wire
                self._map[tenant] = tier
        self.quota_frac = (quota_frac if quota_frac is not None
                           else _env_float("QUOTA_FRAC", DEFAULT_QUOTA_FRAC))
        self.unweighted = unweighted()
        if self.unweighted:
            self.quota_frac = 1.0  # doctored polarity: no per-tenant cap
        self.quota_frac = min(1.0, max(0.05, self.quota_frac))
        self.max_tenants = (max_tenants if max_tenants is not None
                            else _env_int("MAX_TENANTS", DEFAULT_MAX_TENANTS))

    def tier_of(self, tenant: Optional[str]) -> QosClass:
        """The tenant's QoS class (default tier for None/unmapped)."""
        name = self._map.get(tenant or "", self.default_tier)
        return self._classes[name]

    def known_tenants(self) -> Dict[str, str]:
        return dict(self._map)


def _parse_tiers(raw: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        tenant, _, tier = part.partition("=")
        out[tenant.strip()] = tier.strip().lower()
    return out
