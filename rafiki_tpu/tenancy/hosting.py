"""ProgramHost: one worker process, many models (docs/multitenancy.md).

The PR 13 StackedEnsemble fused k *trials of one job* into one
program. ProgramHost generalizes the other axis: k *models of many
jobs* behind ONE InferenceWorker. Each co-hosted job's predictor
wraps its queries with a program tag (:data:`PROGRAM_KEY`, riding the
query payload exactly like the microbatcher's ``BATCH_KEY``), the
shared worker registers on the bus under every co-hosted job id (same
worker id → same queue), and ``ProgramHost.predict`` routes each
query batch to its program through the :class:`ResidencyManager` — so
swapping which models are hot is an LRU byte-budget decision, not a
fleet redeploy, and activating a cold model is a CAS params fetch
(store/cas.py) instead of a worker rollout.

Untagged queries route to the host's default program, so a co-hosted
worker still serves the legacy single-job wire format.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

from rafiki_tpu import telemetry
from rafiki_tpu.tenancy.residency import ResidencyManager

#: Sentinel key tagging one query with its target program — the same
#: back-compat envelope trick as predictor.BATCH_KEY: untagged queries
#: are served by the default program, so old clients keep working.
PROGRAM_KEY = "__rafiki_program__"


def wrap_query(program_id: str, query: Any) -> Dict[str, Any]:
    """Tag ``query`` for ``program_id`` (the co-hosted predictor's
    per-query wrapper)."""
    return {PROGRAM_KEY: program_id, "q": query}


def _unwrap(query: Any) -> "tuple[Optional[str], Any]":
    if isinstance(query, dict) and PROGRAM_KEY in query:
        return query[PROGRAM_KEY], query.get("q")
    return None, query


@dataclasses.dataclass
class ProgramSpec:
    """One co-hostable program: how to load it and what it costs.

    ``loader`` builds the servable model (anything with ``predict``;
    typically a JaxModel or StackedTrialModel restored via a CAS
    params manifest); ``size_bytes`` is its HBM residency charge,
    sized from perf/cost captures or the params blob size.
    """

    program_id: str
    loader: Callable[[], Any]
    size_bytes: int


class ProgramHost:
    """Implements the model contract (``predict``/``destroy``) over a
    residency-managed set of programs."""

    def __init__(self, specs: List[ProgramSpec],
                 residency: Optional[ResidencyManager] = None,
                 default_program: Optional[str] = None):
        if not specs:
            raise ValueError("ProgramHost needs at least one program")
        self.residency = residency or ResidencyManager()
        self._specs: Dict[str, ProgramSpec] = {
            s.program_id: s for s in specs}
        self.default_program = default_program or specs[0].program_id
        if self.default_program not in self._specs:
            raise ValueError(
                f"default program {self.default_program!r} not in specs")

    def add_program(self, spec: ProgramSpec) -> None:
        """Register another co-hosted program (instant activation: the
        model loads lazily on its first query, through the residency
        budget)."""
        self._specs[spec.program_id] = spec

    def program_ids(self) -> List[str]:
        return sorted(self._specs)

    def _model(self, program_id: str) -> Any:
        spec = self._specs.get(program_id)
        if spec is None:
            raise KeyError(f"unknown program {program_id!r}")
        return self.residency.activate(spec.program_id, spec.size_bytes,
                                       spec.loader)

    def predict(self, queries: List[Any]) -> List[Any]:
        """Route each query to its tagged program, preserving order.

        Queries group by program so each resident model runs ONE
        forward per batch (the device-efficiency point of hosting);
        a failed group degrades to per-query error dicts, the same
        containment contract as the inference worker loop.
        """
        groups: Dict[str, List[int]] = {}
        bare: List[Any] = []
        for i, q in enumerate(queries):
            pid, inner = _unwrap(q)
            bare.append(inner)
            groups.setdefault(pid or self.default_program, []).append(i)
        out: List[Any] = [None] * len(queries)
        for pid in sorted(groups):
            idxs = groups[pid]
            batch = [bare[i] for i in idxs]
            try:
                model = self._model(pid)
                preds = model.predict(batch)
                if not isinstance(preds, list) or len(preds) != len(batch):
                    raise RuntimeError(
                        f"program {pid} returned {type(preds).__name__} "
                        f"for a {len(batch)}-query batch")
            except Exception as e:
                preds = [{"error": str(e)}] * len(batch)
                telemetry.inc("tenancy.host_errors")
            for i, p in zip(idxs, preds):
                out[i] = p
        telemetry.inc("tenancy.host_queries", len(queries))
        return out

    def destroy(self) -> None:
        """Evict everything (worker shutdown) — through the normal
        eviction path so the swaps journal like any other."""
        self.residency.drain()
