"""Weighted-fair tenant admission (docs/multitenancy.md).

:class:`TenantAdmissionController` extends the gateway's admission
controller (bounded inflight + bounded deadline-aware queue) with the
two primitives tenant isolation needs:

* **Per-tenant quotas.** One tenant may hold at most
  ``quota_frac × max_inflight`` slots and ``quota_frac × max_queue``
  queue positions. A flooding tenant exhausts ITS queue quota and
  sheds with reason ``tenant_quota`` — charged to the flooder — while
  the rest of the queue stays open to everyone else. This is the
  mechanism behind the ``noisy-neighbor-shed`` acceptance gate: the
  aggressor's 10× spike sheds the aggressor, never the victim.
* **Weighted-fair granting.** When a slot frees, it goes to the
  waiting tenant with the lowest ``inflight / weight`` charge (FIFO
  within a tenant), so a gold tenant (weight 4) gets 4× a batch
  tenant's share under contention — proportional share, not absolute
  priority: batch still progresses.

With ``RAFIKI_TENANT_UNWEIGHTED=1`` (the tenancy smoke's doctored
polarity) quotas widen to the whole gateway and granting degrades to
global FIFO — exactly the pre-tenancy behaviour, which demonstrably
fails the victim-p99 gate.

Per-tenant state here is bounded: idle tenant slots (no inflight, no
waiters) are pruned once the tracked-tenant cap is exceeded.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Dict, Optional

from rafiki_tpu.gateway.admission import AdmissionController, ShedError
from rafiki_tpu.tenancy.qos import ANON_TENANT, TenantDirectory


class _TenantSlot:
    __slots__ = ("inflight", "waiters")

    def __init__(self):
        self.inflight = 0
        self.waiters: deque = deque()  # arrival seq tickets, FIFO

    def idle(self) -> bool:
        return self.inflight == 0 and not self.waiters


class TenantAdmissionController(AdmissionController):
    """Drop-in for :class:`AdmissionController` with tenant-aware
    ``admit``/``release`` (the tenant-less signature still works —
    untagged traffic lands in the shared anonymous bucket)."""

    def __init__(self, directory: TenantDirectory,
                 max_inflight: int = 8, max_queue: int = 32):
        super().__init__(max_inflight=max_inflight, max_queue=max_queue)
        self.directory = directory
        frac = directory.quota_frac
        self.quota_inflight = max(1, int(math.ceil(max_inflight * frac)))
        self.quota_queue = (max(1, int(math.ceil(self.max_queue * frac)))
                            if self.max_queue else 0)
        self._slots: Dict[str, _TenantSlot] = {}
        self._seq = 0

    # -- fairness ------------------------------------------------------------

    def _slot(self, tenant: str) -> _TenantSlot:
        slot = self._slots.get(tenant)
        if slot is None:
            slot = _TenantSlot()
            self._slots[tenant] = slot
            self._prune_locked()
        return slot

    def _prune_locked(self) -> None:
        """Bound per-tenant state: drop idle slots beyond the cap
        (insertion order ≈ LRU at this cadence). Never drops a slot
        with live inflight or waiters — counts must stay exact."""
        cap = self.directory.max_tenants
        if len(self._slots) <= cap:
            return
        for tenant in [t for t, s in self._slots.items() if s.idle()]:
            self._slots.pop(tenant, None)
            if len(self._slots) <= cap:
                break

    def _charge(self, tenant: str, slot: _TenantSlot) -> float:
        weight = max(self.directory.tier_of(tenant).weight, 1e-9)
        return slot.inflight / weight

    def _chosen_tenant(self) -> Optional[str]:
        """The tenant whose head waiter gets the next free slot.

        Weighted mode: the eligible (waiting, under inflight quota)
        tenant with the lowest inflight/weight charge, oldest arrival
        breaking ties. Unweighted (doctored) mode: global FIFO — the
        tenant owning the oldest waiter, quota ignored.
        """
        eligible = [(t, s) for t, s in self._slots.items() if s.waiters]
        if not eligible:
            return None
        if getattr(self.directory, "unweighted", False):
            return min(eligible, key=lambda ts: ts[1].waiters[0])[0]
        eligible = [(t, s) for t, s in eligible
                    if s.inflight < self.quota_inflight]
        if not eligible:
            return None
        return min(eligible,
                   key=lambda ts: (self._charge(*ts), ts[1].waiters[0]))[0]

    # -- admission -----------------------------------------------------------

    def admit(self, deadline: float, retry_after_s: float = 1.0,
              tenant: Optional[str] = None) -> float:
        tenant = tenant or ANON_TENANT
        unweighted = getattr(self.directory, "unweighted", False)
        t0 = time.monotonic()
        with self._cv:
            if self._closed:
                raise ShedError("draining", retry_after_s)
            slot = self._slot(tenant)
            if (self._inflight < self.max_inflight and self._waiting == 0
                    and (unweighted
                         or slot.inflight < self.quota_inflight)):
                self._inflight += 1
                slot.inflight += 1
                return 0.0
            # Quota shed order matters: the per-tenant check runs FIRST
            # so a flooder exhausts tenant_quota (charged to itself)
            # before it can fill the shared queue and charge queue_full
            # to everyone.
            if (not unweighted and self.quota_queue
                    and len(slot.waiters) >= self.quota_queue):
                raise ShedError("tenant_quota", retry_after_s)
            if self._waiting >= self.max_queue:
                raise ShedError("queue_full", retry_after_s)
            if time.monotonic() >= deadline:
                raise ShedError("deadline", retry_after_s)
            self._seq += 1
            ticket = self._seq
            slot.waiters.append(ticket)
            self._waiting += 1
            try:
                while True:
                    if self._closed:
                        raise ShedError("draining", retry_after_s)
                    if (self._inflight < self.max_inflight
                            and slot.waiters[0] == ticket
                            and self._chosen_tenant() == tenant):
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise ShedError("deadline", retry_after_s)
                    self._cv.wait(remaining)
                self._inflight += 1
                slot.inflight += 1
            finally:
                try:
                    slot.waiters.remove(ticket)
                except ValueError:
                    pass
                self._waiting -= 1
                # A shed/deadline exit may unblock a DIFFERENT tenant
                # (we might have been the chosen head).
                self._cv.notify_all()
        return time.monotonic() - t0

    def release(self, tenant: Optional[str] = None) -> None:
        tenant = tenant or ANON_TENANT
        with self._cv:
            self._inflight -= 1
            slot = self._slots.get(tenant)
            if slot is not None:
                slot.inflight = max(0, slot.inflight - 1)
            self._prune_locked()
            self._cv.notify_all()

    # -- introspection -------------------------------------------------------

    def tenant_inflight(self, tenant: str) -> int:
        with self._cv:
            slot = self._slots.get(tenant)
            return slot.inflight if slot is not None else 0

    def tenant_waiting(self, tenant: str) -> int:
        with self._cv:
            slot = self._slots.get(tenant)
            return len(slot.waiters) if slot is not None else 0
