"""LRU program residency against an HBM byte budget.

Co-hosting many models in one worker process only works if the worker
never tries to keep more program state resident than the device has
HBM. :class:`ResidencyManager` is the gatekeeper: programs activate
through it, it charges each one's byte estimate against the budget
(sized from the same ``perf/cost`` capture numbers the roofline join
uses, or RAFIKI_TENANT_HBM_BUDGET_MB), and when an activation would
overflow it evicts least-recently-USED residents first — destroying
the evicted program's device state via its ``destroy()`` hook.

Every transition journals ``tenancy/residency`` (event =
``activate`` / ``evict`` / ``hit``), so a co-hosted fleet's swap
history replays from journals alone — the acceptance gate for the
co-hosting tentpole reads exactly this stream. Activation is
CAS-friendly by construction: the loader callable runs only on a
miss, so a params fetch by manifest (store/cas.py dedup) happens once
per residency, not once per request.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional

from rafiki_tpu import telemetry
from rafiki_tpu.obs.journal import journal as _journal

#: Default HBM budget for co-hosted programs when the caller doesn't
#: size one from perf/cost captures (RAFIKI_TENANT_HBM_BUDGET_MB).
DEFAULT_HBM_BUDGET_MB = 512


def default_budget_bytes() -> int:
    raw = os.environ.get("RAFIKI_TENANT_HBM_BUDGET_MB")
    try:
        mb = int(raw) if raw else DEFAULT_HBM_BUDGET_MB
    except ValueError:
        mb = DEFAULT_HBM_BUDGET_MB
    return max(1, mb) * 1024 * 1024


class _Resident:
    __slots__ = ("program", "size_bytes", "activations")

    def __init__(self, program: Any, size_bytes: int):
        self.program = program
        self.size_bytes = size_bytes
        self.activations = 1


class ResidencyManager:
    """LRU cache of live programs keyed by program id, budgeted in
    bytes. ``activate`` is the only entry: a hit refreshes recency, a
    miss runs the loader (evicting LRU residents until the new program
    fits) and journals the swap."""

    def __init__(self, budget_bytes: Optional[int] = None):
        self.budget_bytes = (default_budget_bytes()
                             if budget_bytes is None else int(budget_bytes))
        self._lock = threading.Lock()
        self._residents: "OrderedDict[str, _Resident]" = OrderedDict()
        self._used = 0

    def activate(self, key: str, size_bytes: int,
                 loader: Callable[[], Any]) -> Any:
        """The resident program for ``key``, loading (and evicting)
        as needed. ``size_bytes`` is the program's HBM charge; a
        program larger than the whole budget is refused."""
        with self._lock:
            res = self._residents.get(key)
            if res is not None:
                self._residents.move_to_end(key)
                res.activations += 1
                telemetry.inc("tenancy.residency_hits")
                _journal.record("tenancy", "residency", event="hit",
                                program=key)
                return res.program
            size_bytes = max(0, int(size_bytes))
            if size_bytes > self.budget_bytes:
                raise MemoryError(
                    f"program {key} ({size_bytes}B) exceeds the HBM "
                    f"residency budget ({self.budget_bytes}B)")
            while self._used + size_bytes > self.budget_bytes:
                self._evict_lru_locked(for_program=key)
            t0 = time.monotonic()
            program = loader()
            # lint: disable=RF007 — load_s rides the residency journal record itself; a span here would nest inside the caller's predict span and double-count the load
            load_s = time.monotonic() - t0
            self._residents[key] = _Resident(program, size_bytes)
            self._used += size_bytes
            telemetry.inc("tenancy.residency_misses")
            telemetry.set_gauge("tenancy.residency_used_bytes", self._used)
            _journal.record("tenancy", "residency", event="activate",
                            program=key, size_bytes=size_bytes,
                            used_bytes=self._used,
                            budget_bytes=self.budget_bytes,
                            load_s=round(load_s, 6))
            return program

    def _evict_lru_locked(self, for_program: str) -> None:
        if not self._residents:
            raise MemoryError(
                f"HBM residency budget ({self.budget_bytes}B) cannot "
                f"fit program {for_program} even with nothing resident")
        # lint: disable=RF004 — _locked helper: every caller (activate, drain) already holds self._lock
        key, res = self._residents.popitem(last=False)
        self._used -= res.size_bytes
        destroy = getattr(res.program, "destroy", None)
        if callable(destroy):
            try:
                destroy()
            except Exception:
                pass  # eviction must not fail on a broken destroy hook
        telemetry.inc("tenancy.residency_evictions")
        telemetry.set_gauge("tenancy.residency_used_bytes", self._used)
        _journal.record("tenancy", "residency", event="evict",
                        program=key, size_bytes=res.size_bytes,
                        used_bytes=self._used, for_program=for_program)

    def drain(self) -> None:
        """Evict every resident (host shutdown), journaling each."""
        with self._lock:
            while self._residents:
                self._evict_lru_locked(for_program="shutdown")

    # -- introspection -------------------------------------------------------

    def resident_keys(self):
        with self._lock:
            return list(self._residents)

    def used_bytes(self) -> int:
        with self._lock:
            return self._used

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "resident": len(self._residents),
                "used_bytes": self._used,
                "budget_bytes": self.budget_bytes,
                "hits": telemetry.get_counter("tenancy.residency_hits"),
                "misses": telemetry.get_counter("tenancy.residency_misses"),
                "evictions": telemetry.get_counter(
                    "tenancy.residency_evictions"),
            }
