"""Multi-tenant serving fabric (docs/multitenancy.md).

Rafiki's original premise is multi-user MLaaS — many concurrent jobs
from many users sharing one cluster — yet until this package every
inference job got dedicated workers and the gateway admitted requests
first-come-first-served. The tenancy layer makes the serving chain
tenant-aware end to end:

* :mod:`qos` — QoS classes (``gold``/``std``/``batch``: weight,
  deadline tier, p99 budget) and the tenant→tier directory, all
  ``RAFIKI_TENANT_*`` knobs.
* :mod:`admission` — weighted-fair admission across tenants with
  per-tenant queue/inflight quotas: one tenant's spike sheds THAT
  tenant, never starves another.
* :mod:`accounting` — bounded per-tenant admit/shed/latency/burn
  accounting (``serving.tenant.*`` metrics, ``tenant/*`` journals).
* :mod:`residency` — LRU program residency against an HBM byte
  budget with journaled activate/evict (``tenancy/residency``).
* :mod:`hosting` — ``ProgramHost``: one worker process serving many
  models behind the residency manager (the PR 13 StackedEnsemble
  generalization from k-trials-one-job to k-models-many-jobs).
* :mod:`arbiter` — fleet-level arbitration: the autoscale tenant
  lane's pressure function and the twin-gated admission of NEW jobs
  (``tenancy/arbiter`` journals).
"""

from rafiki_tpu.tenancy.qos import (  # noqa: F401
    ANON_TENANT, QosClass, TenantDirectory, DEFAULT_TIER, TIERS)
from rafiki_tpu.tenancy.accounting import (  # noqa: F401
    BoundedTenantMap, TenantAccounting)
from rafiki_tpu.tenancy.admission import TenantAdmissionController  # noqa: F401
from rafiki_tpu.tenancy.residency import ResidencyManager  # noqa: F401
from rafiki_tpu.tenancy.hosting import (  # noqa: F401
    PROGRAM_KEY, ProgramHost, ProgramSpec, wrap_query)
from rafiki_tpu.tenancy.fabric import TenantFabric  # noqa: F401
from rafiki_tpu.tenancy.arbiter import (  # noqa: F401
    JobAdmissionGate, tenant_pressure)
