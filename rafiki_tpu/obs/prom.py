"""Prometheus text exposition of the telemetry snapshot.

``GET /metrics?format=prom`` on both web apps renders the SAME
registry snapshot the JSON endpoint serves — one source of truth, two
encodings. Mapping:

* counters            -> ``# TYPE rafiki_<name> counter``
* gauges              -> ``# TYPE rafiki_<name> gauge``
* histogram summaries -> Prometheus *summary*: ``{quantile="0.5|0.9|0.99"}``
  series plus ``_sum``/``_count``
* span aggregates     -> ``rafiki_span_seconds_total{name="..."}`` /
  ``rafiki_span_count{name="..."}``
* collectors          -> numeric leaves flattened to gauges
  (``rafiki_program_cache_hits``); non-numeric leaves dropped —
  Prometheus has no string samples.

Output is deterministic (sorted names) so the exposition is
golden-file testable. Stdlib-only formatter: no prometheus_client
dependency, the text format is ~20 lines of spec.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List

PREFIX = "rafiki"

_SAN_RE = re.compile(r"[^a-zA-Z0-9_]")
_QUANTILES = (("p50", "0.5"), ("p90", "0.9"), ("p99", "0.99"))
#: Snapshot keys that are NOT collectors.
_STRUCTURAL = {"ts", "counters", "gauges", "histograms", "spans"}


def _san(name: str) -> str:
    out = _SAN_RE.sub("_", name)
    return out if not out[:1].isdigit() else "_" + out


def _fmt(v: Any) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _esc(label: str) -> str:
    return label.replace("\\", "\\\\").replace('"', '\\"')


def _is_num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _flatten(prefix: str, value: Any, out: Dict[str, float]) -> None:
    if _is_num(value):
        out[prefix] = value
    elif isinstance(value, dict):
        for k, v in value.items():
            _flatten(f"{prefix}_{_san(str(k))}", v, out)
    # strings / None / bools / lists: no Prometheus representation


def to_prometheus(snapshot: Dict[str, Any]) -> str:
    """Render a ``telemetry.snapshot()`` dict as Prometheus text
    exposition format (version 0.0.4)."""
    lines: List[str] = []

    for name in sorted(snapshot.get("counters", {})):
        metric = f"{PREFIX}_{_san(name)}"
        lines.append(f"# TYPE {metric} counter")
        lines.append(f"{metric} {_fmt(snapshot['counters'][name])}")

    for name in sorted(snapshot.get("gauges", {})):
        metric = f"{PREFIX}_{_san(name)}"
        lines.append(f"# TYPE {metric} gauge")
        lines.append(f"{metric} {_fmt(snapshot['gauges'][name])}")

    for name in sorted(snapshot.get("histograms", {})):
        summary = snapshot["histograms"][name]
        metric = f"{PREFIX}_{_san(name)}"
        lines.append(f"# TYPE {metric} summary")
        for key, q in _QUANTILES:
            if summary.get(key) is not None:
                lines.append(
                    f'{metric}{{quantile="{q}"}} {_fmt(summary[key])}')
        lines.append(f"{metric}_sum {_fmt(summary.get('sum', 0.0))}")
        lines.append(f"{metric}_count {_fmt(summary.get('count', 0))}")

    spans = snapshot.get("spans", {})
    if spans:
        lines.append(f"# TYPE {PREFIX}_span_seconds_total counter")
        for name in sorted(spans):
            lines.append(
                f'{PREFIX}_span_seconds_total{{name="{_esc(name)}"}} '
                f"{_fmt(spans[name].get('total_s', 0.0))}")
        lines.append(f"# TYPE {PREFIX}_span_count counter")
        for name in sorted(spans):
            lines.append(
                f'{PREFIX}_span_count{{name="{_esc(name)}"}} '
                f"{_fmt(spans[name].get('count', 0))}")

    flat: Dict[str, float] = {}
    for key in sorted(snapshot):
        if key in _STRUCTURAL:
            continue
        _flatten(f"{PREFIX}_{_san(key)}", snapshot[key], flat)
    for metric in sorted(flat):
        lines.append(f"# TYPE {metric} gauge")
        lines.append(f"{metric} {_fmt(flat[metric])}")

    return "\n".join(lines) + "\n"
