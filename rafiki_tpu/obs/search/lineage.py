"""Trial lineage: stitch journaled lifecycle events into genealogy.

Workers already journal every pack/evict/backfill/resume lifecycle
transition as ``event/*`` records and the mesh scheduler journals
``mesh/chip_lost``/``mesh/repack``/``mesh/repack_failed`` — but each
record only sees its own hop. This module joins them per trial id into
explicit incarnation chains:

* an **incarnation** starts at each ``trial_started`` (serial runs,
  pack rows, mid-pack backfills and post-repack resumes all re-emit
  it) and collects that attempt's events in timestamp order;
* a trial is **closed** when its last incarnation carries a terminal
  event (``trial_completed``/``trial_errored``/``trial_diverged``) or
  ends on a ``pack_member_evicted`` (the eviction *is* the
  explanation);
* anything else is an **orphaned incarnation** — a trial the fleet
  lost without writing down why. ``reconcile`` surfaces those and the
  CLI (``obs lineage --check``) fails loudly on them.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

TERMINAL = ("trial_completed", "trial_errored", "trial_diverged")

#: lifecycle events worth keeping on the per-incarnation walk (the
#: full journal line stays in the journal; lineage keeps the join keys)
_KEEP_FIELDS = ("epoch", "from_epoch", "reason", "score", "error",
                "divergence", "diagnosis", "sub_job_id", "model")


def _slim(rec: Dict[str, Any]) -> Dict[str, Any]:
    out = {"ts": rec.get("ts"), "event": rec.get("name"),
           "worker_id": rec.get("worker_id"), "pid": rec.get("pid")}
    for k in _KEEP_FIELDS:
        if rec.get(k) is not None:
            out[k] = rec[k]
    return out


def build(records: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """records (from ``journal.read_dir``) -> {trial_id: lineage}."""
    # knobs hashed lazily to avoid importing audit when unused
    from rafiki_tpu.obs.search.audit import knobs_hash

    trials: Dict[str, Dict[str, Any]] = {}
    evict_ts_by_worker: Dict[str, List[float]] = {}
    for rec in records:
        kind, name = rec.get("kind"), rec.get("name")
        if kind == "event" and name == "pack_member_evicted":
            evict_ts_by_worker.setdefault(
                str(rec.get("worker_id")), []).append(rec.get("ts", 0.0))
        if kind == "event" and rec.get("trial_id") is not None:
            tid = str(rec["trial_id"])
            t = trials.setdefault(tid, {
                "trial_id": tid, "incarnations": [], "workers": [],
                "knobs_hash": None, "n_epoch_evals": 0,
                "repacked_from": [], "repack_orphaned": False,
            })
            if name == "trial_started":
                t["incarnations"].append({
                    "seq": len(t["incarnations"]) + 1,
                    "started_ts": rec.get("ts"),
                    "worker_id": rec.get("worker_id"),
                    "events": [], "terminal": None,
                })
                if rec.get("knobs") is not None:
                    t["knobs_hash"] = knobs_hash(rec["knobs"])
            if not t["incarnations"]:
                # Event before any trial_started (e.g. a resume record
                # from a process whose start landed in a rotated-away
                # generation): keep it on a synthetic incarnation so
                # nothing is silently dropped.
                t["incarnations"].append({
                    "seq": 1, "started_ts": rec.get("ts"),
                    "worker_id": rec.get("worker_id"),
                    "events": [], "terminal": None, "synthetic": True,
                })
            inc = t["incarnations"][-1]
            if name != "trial_started":
                inc["events"].append(_slim(rec))
            if name in TERMINAL:
                inc["terminal"] = name
            w = rec.get("worker_id")
            if w is not None and w not in t["workers"]:
                t["workers"].append(w)
        elif kind == "trial" and name == "epoch_eval":
            tid = str(rec.get("trial_id"))
            if tid in trials:
                trials[tid]["n_epoch_evals"] += 1
        elif kind == "mesh" and name == "repack":
            for tid in rec.get("moved") or []:
                if str(tid) in trials:
                    trials[str(tid)]["repacked_from"].append(
                        rec.get("chip"))
        elif kind == "mesh" and name == "repack_failed":
            for tid in rec.get("orphans") or []:
                if str(tid) in trials:
                    trials[str(tid)]["repack_orphaned"] = True

    for t in trials.values():
        incs = t["incarnations"]
        last = incs[-1] if incs else None
        evicted_last = bool(
            last and last["events"]
            and last["events"][-1]["event"] == "pack_member_evicted")
        t["n_incarnations"] = len(incs)
        t["n_evictions"] = sum(
            1 for i in incs for e in i["events"]
            if e["event"] == "pack_member_evicted")
        t["n_resumes"] = sum(
            1 for i in incs for e in i["events"]
            if e["event"] == "trial_resumed")
        t["n_checkpoints"] = sum(
            1 for i in incs for e in i["events"]
            if e["event"] == "checkpoint_written")
        # A backfill fills a slot some eviction freed: first start
        # strictly after an eviction on the same worker.
        first = incs[0] if incs else None
        t["backfilled"] = bool(
            first and any(ts <= (first["started_ts"] or 0.0)
                          for ts in evict_ts_by_worker.get(
                              str(first["worker_id"]), ())))
        t["status"] = (last["terminal"] if last and last["terminal"]
                       else "evicted" if evicted_last
                       else "orphaned")
    return trials


def reconcile(trials: Dict[str, Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Fleet-wide orphan check: every trial the journals started must
    end with a written-down fate. Returns the violations (empty list
    == clean); callers exit nonzero on any."""
    orphans = []
    for tid, t in sorted(trials.items()):
        if t["status"] == "orphaned":
            last = t["incarnations"][-1] if t["incarnations"] else {}
            orphans.append({
                "trial_id": tid,
                "incarnation": t["n_incarnations"],
                "worker_id": last.get("worker_id"),
                "last_event": (last["events"][-1]["event"]
                               if last.get("events") else "trial_started"),
                "repack_orphaned": t["repack_orphaned"],
            })
    return orphans


def walk(trials: Dict[str, Dict[str, Any]],
         trial: str) -> Optional[Dict[str, Any]]:
    """One trial's lineage by exact id or unique prefix."""
    if trial in trials:
        return trials[trial]
    hits = [t for tid, t in trials.items() if tid.startswith(trial)]
    return hits[0] if len(hits) == 1 else None
