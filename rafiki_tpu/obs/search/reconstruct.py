"""Rebuild a whole sweep from journals alone (``obs sweep``).

The audit plane (:mod:`rafiki_tpu.obs.search.audit`) gives every
proposal, batch draft and feedback a durable record; this module is
the reader that turns a journal directory back into the sweep:
ordered proposals with their acquisition breakdowns, the score each
one earned, the best-so-far/regret curve, lineage roll-ups, and —
when a random-engine baseline ran beside the main advisor — the
advisor lift with a seeded bootstrap CI (the same
:func:`~rafiki_tpu.obs.search.stats.bootstrap_ci` bench.py uses).

Reconciliation is always on and loud: a ``feedback`` whose knobs-hash
never appeared in a ``propose`` record, or a ``propose_batch`` member
with no matching ``propose``, means an advisor decision escaped the
audit trail — the CLI exits nonzero naming the hash, and the sweep
smoke proves that path by doctoring a journal.

Joins (all by the canonical knobs-hash):

    advisor/propose --(hash)--> event/trial_started --(trial_id)-->
        trial/epoch_eval + terminal events
    advisor/feedback --(hash)--> advisor/propose (order-preserving)
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from rafiki_tpu.obs.search import lineage as lineage_mod
from rafiki_tpu.obs.search import stats
from rafiki_tpu.obs.search.audit import knobs_hash

SWEEP_SCHEMA_VERSION = 1


def _group_key(rec: Dict[str, Any]) -> str:
    if rec.get("advisor_id"):
        return str(rec["advisor_id"])
    return (f"{rec.get('engine', '?')}/{rec.get('role', '?')}-"
            f"{rec.get('pid', 0)}/seed{rec.get('seed', 0)}")


def _match(rec: Dict[str, Any], job: Optional[str]) -> bool:
    if not job:
        return True
    j = str(job)
    return (j in str(rec.get("job_id") or "")
            or str(rec.get("advisor_id") or "").startswith(j))


def reconstruct(records: List[Dict[str, Any]], job: Optional[str] = None,
                boot_seed: int = 0,
                n_boot: int = stats.DEFAULT_N_BOOT) -> Dict[str, Any]:
    """Journal records -> sweep document. Never raises on bad input;
    violations land in ``doc["reconciliation"]["errors"]``."""
    adv = [r for r in records
           if r.get("kind") == "advisor" and _match(r, job)]
    groups: Dict[str, Dict[str, Any]] = {}
    # The curve plane (predict/kill/false_kill) journals from the
    # coordinator, which deliberately knows no advisor identity —
    # these records join the sweep by knobs_hash, not by group.
    predicts: List[Dict[str, Any]] = []
    kills: List[Dict[str, Any]] = []
    false_kills: List[Dict[str, Any]] = []
    for r in adv:
        if r.get("name") == "predict":
            predicts.append(r)
            continue
        if r.get("name") == "kill":
            kills.append(r)
            continue
        if r.get("name") == "false_kill":
            false_kills.append(r)
            continue
        g = groups.setdefault(_group_key(r), {
            "engine": r.get("engine"), "seed": r.get("seed"),
            "job_id": r.get("job_id"),
            "proposes": [], "feedbacks": [], "batches": [],
            "speculates": [], "corrects": []})
        if r.get("name") == "propose":
            g["proposes"].append(r)
        elif r.get("name") == "feedback":
            g["feedbacks"].append(r)
        elif r.get("name") == "propose_batch":
            g["batches"].append(r)
        elif r.get("name") == "speculate":
            g["speculates"].append(r)
        elif r.get("name") == "correct":
            g["corrects"].append(r)

    errors: List[Dict[str, Any]] = []

    # -- per-group audit reconciliation (loud) -------------------------------
    for key, g in groups.items():
        unmatched: Dict[str, int] = {}
        for p in g["proposes"]:
            h = p.get("knobs_hash")
            unmatched[h] = unmatched.get(h, 0) + 1
        for f in g["feedbacks"]:
            h = f.get("knobs_hash")
            if unmatched.get(h, 0) > 0:
                unmatched[h] -= 1
            else:
                errors.append({
                    "type": "feedback_without_propose", "group": key,
                    "knobs_hash": h, "ts": f.get("ts"),
                    "detail": "a score arrived for a knob assignment no "
                              "advisor/propose record ever chose — an "
                              "unjournaled decision or a torn journal"})
        batch_budget: Dict[str, int] = {}
        for p in g["proposes"]:
            h = p.get("knobs_hash")
            batch_budget[h] = batch_budget.get(h, 0) + 1
        for b in g["batches"]:
            for h in b.get("knobs_hashes") or []:
                if batch_budget.get(h, 0) > 0:
                    batch_budget[h] -= 1
                else:
                    errors.append({
                        "type": "batch_member_without_propose",
                        "group": key, "knobs_hash": h, "ts": b.get("ts"),
                        "detail": "a propose_batch member has no matching "
                                  "advisor/propose record"})
        # Membership (not count) check: rehydration legitimately
        # re-journals a speculation it replays, so duplicates per hash
        # are fine — a speculation for a never-proposed assignment is
        # not.
        proposed = {p.get("knobs_hash") for p in g["proposes"]}
        for s in g["speculates"]:
            if s.get("knobs_hash") not in proposed:
                errors.append({
                    "type": "speculate_without_propose", "group": key,
                    "knobs_hash": s.get("knobs_hash"), "ts": s.get("ts"),
                    "detail": "a speculative score entered the advisor "
                              "for a knob assignment no advisor/propose "
                              "record ever chose"})

    # Kill verdicts join globally (coordinator records carry no group
    # identity): a kill for a hash nobody proposed escaped the audit
    # trail.
    all_proposed = {p.get("knobs_hash")
                    for g in groups.values() for p in g["proposes"]}
    for kr in kills:
        if kr.get("knobs_hash") not in all_proposed:
            errors.append({
                "type": "kill_without_propose",
                "knobs_hash": kr.get("knobs_hash"), "ts": kr.get("ts"),
                "detail": "an early-kill verdict names a knob assignment "
                          "no advisor/propose record ever chose"})

    # -- pick the main sweep + random baseline -------------------------------
    def _n(gk: str) -> int:
        return len(groups[gk]["proposes"])

    non_random = [k for k, g in groups.items() if g["engine"] != "random"]
    main_key = (max(non_random, key=_n) if non_random
                else (max(groups, key=_n) if groups else None))
    baselines = [k for k, g in groups.items()
                 if g["engine"] == "random" and k != main_key]
    base_key = (max(baselines, key=lambda k: len(groups[k]["feedbacks"]))
                if baselines else None)

    # -- trial join: hash -> trial ids (order-preserving queues) -------------
    trial_q: Dict[str, List[str]] = {}
    for r in records:
        if (r.get("kind") == "event" and r.get("name") == "trial_started"
                and r.get("knobs") is not None):
            trial_q.setdefault(knobs_hash(r["knobs"]), []).append(
                str(r.get("trial_id")))
    trials = lineage_mod.build(records)

    doc: Dict[str, Any] = {
        "sweep_schema_version": SWEEP_SCHEMA_VERSION,
        "job": job,
        "groups": {k: {"engine": g["engine"], "seed": g["seed"],
                       "job_id": g["job_id"],
                       "n_proposals": len(g["proposes"]),
                       "n_feedbacks": len(g["feedbacks"]),
                       "n_batches": len(g["batches"])}
                   for k, g in groups.items()},
        "main": main_key,
        "baseline": base_key,
    }

    proposals: List[Dict[str, Any]] = []
    scores: List[float] = []
    n_doomed = 0
    if main_key is not None:
        g = groups[main_key]
        doc["engine"] = g["engine"]
        doc["seed"] = g["seed"]
        # feedback join per hash, order-preserving
        fb_q: Dict[str, List[Dict[str, Any]]] = {}
        for f in g["feedbacks"]:
            fb_q.setdefault(f.get("knobs_hash"), []).append(f)
        # Curve-plane joins, last record per hash wins (the newest fit
        # has the most observations).
        predict_by_hash = {p.get("knobs_hash"): p for p in predicts}
        kill_by_hash = {kr.get("knobs_hash"): kr for kr in kills}
        false_kill_hashes = {fk.get("knobs_hash") for fk in false_kills}
        speculated_hashes = {s.get("knobs_hash")
                             for s in g["speculates"]}
        correct_by_hash = {c.get("knobs_hash"): c for c in g["corrects"]}
        pred_errors: List[float] = []
        for seq, p in enumerate(g["proposes"], start=1):
            h = p.get("knobs_hash")
            fb = fb_q.get(h)
            f = fb.pop(0) if fb else None
            tq = trial_q.get(h)
            tid = tq.pop(0) if tq else None
            t = trials.get(tid) if tid else None
            doomed = bool(
                (f and f.get("doomed"))
                or (t and t["status"] in ("trial_errored",
                                          "trial_diverged")))
            row = {
                "seq": seq, "ts": p.get("ts"), "knobs_hash": h,
                "acquisition": p.get("acquisition"),
                "trial_id": tid,
                "score": f.get("score") if f else None,
                "doomed": doomed,
                "n_epoch_evals": (t or {}).get("n_epoch_evals"),
                "status": (t or {}).get("status"),
            }
            pr = predict_by_hash.get(h) or kill_by_hash.get(h)
            if pr is not None:
                row["predicted_final"] = pr.get("predicted")
                row["prediction_band"] = pr.get("band")
            if h in kill_by_hash:
                row["killed"] = True
                row["kill_epoch"] = kill_by_hash[h].get("epoch")
                row["false_kill"] = h in false_kill_hashes
            if h in speculated_hashes:
                row["speculated"] = True
                row["corrected"] = h in correct_by_hash
            # Per-trial prediction error: the truth (real score, or a
            # correction's `actual`) vs the newest mid-flight
            # prediction.
            truth = None
            if f is not None and not doomed:
                truth = float(f["score"])
            elif h in correct_by_hash:
                truth = correct_by_hash[h].get("actual")
            if truth is not None and row.get("predicted_final") is not None:
                err = float(truth) - float(row["predicted_final"])
                row["prediction_error"] = round(err, 9)
                pred_errors.append(abs(err))
            proposals.append(row)
            if f is not None and not doomed:
                scores.append(float(f["score"]))
            if doomed:
                n_doomed += 1
        doc["proposals"] = proposals
        doc["curve"] = stats.regret_curve(scores)
        ts_all = ([p.get("ts") for p in g["proposes"]]
                  + [f.get("ts") for f in g["feedbacks"]])
        ts_all = [t for t in ts_all if t is not None]
        span_s = (max(ts_all) - min(ts_all)) if len(ts_all) > 1 else 0.0
        doc.update({
            "n_proposals": len(proposals),
            "n_scored": len(scores),
            "n_doomed": n_doomed,
            "span_s": round(span_s, 6),
            "best_score": doc["curve"]["best_score"],
            "regret": doc["curve"]["mean_regret"],
            "effective_trials_per_hour": (
                round(len(scores) / (span_s / 3600.0), 4)
                if span_s > 0 and scores else None),
        })
        # -- learning-curve roll-up (docs/early_kill.md) ---------------------
        n_kills = sum(1 for row in proposals if row.get("killed"))
        n_false = sum(1 for row in proposals if row.get("false_kill"))
        true_kills = n_kills - n_false
        # Recall ground truth: scored trials that finished below
        # final-best minus the kill margin SHOULD have been killed;
        # each one that ran to completion is a miss. Margin comes from
        # the kill records' own config (they carry the knobs in force).
        margin = 0.02
        for kr in kills:
            cfg = kr.get("config") or {}
            if cfg.get("margin") is not None:
                margin = float(cfg["margin"])
                break
        final_best = doc["curve"]["best_score"]
        missed = (sum(1 for s in scores if s < final_best - margin)
                  if final_best is not None else 0)
        curve_stats: Dict[str, Any] = {
            "n_predicts": len(predicts),
            "n_kills": n_kills,
            "n_false_kills": n_false,
            "n_speculations": len(g["speculates"]),
            "n_corrections": len(g["corrects"]),
            "kill_precision": (round(true_kills / n_kills, 4)
                               if n_kills else None),
            "kill_recall": (round(true_kills / (true_kills + missed), 4)
                            if (true_kills + missed) else None),
            "mean_abs_prediction_error": (
                round(sum(pred_errors) / len(pred_errors), 6)
                if pred_errors else None),
        }
        doc["curve_advisor"] = curve_stats
        doc.update({k: v for k, v in curve_stats.items()
                    if k != "n_predicts"})

    # -- advisor lift vs the random baseline ---------------------------------
    if main_key is not None and base_key is not None:
        base_scores = [float(f["score"])
                       for f in groups[base_key]["feedbacks"]
                       if not f.get("doomed")]
        n_pair = min(len(scores), len(base_scores))
        if n_pair:
            diffs = [scores[i] - base_scores[i] for i in range(n_pair)]
            ci = stats.bootstrap_ci(diffs, n_boot=n_boot, seed=boot_seed)
            doc["lift"] = ci
            doc["advisor_lift"] = ci["mean"]
            doc["lift_ci_low"] = ci["lo"]
            doc["lift_ci_high"] = ci["hi"]

    # -- lineage roll-up ------------------------------------------------------
    orphans = lineage_mod.reconcile(trials)
    doc["lineage"] = {
        "n_trials": len(trials),
        "n_evictions": sum(t["n_evictions"] for t in trials.values()),
        "n_resumes": sum(t["n_resumes"] for t in trials.values()),
        "n_backfilled": sum(1 for t in trials.values() if t["backfilled"]),
        "n_multi_incarnation": sum(
            1 for t in trials.values() if t["n_incarnations"] > 1),
        "orphans": orphans,
    }

    doc["reconciliation"] = {"ok": not errors, "errors": errors}
    return doc


def artifact(doc: Dict[str, Any]) -> Dict[str, Any]:
    """The trendable SWEEP_r*.json slice of a sweep document — headline
    keys at top level for ``bench_report --sweep`` (polarities live in
    its SWEEP_METRICS table)."""
    keys = ("sweep_schema_version", "job", "engine", "seed",
            "n_proposals", "n_scored", "n_doomed", "span_s",
            "best_score", "regret", "effective_trials_per_hour",
            "advisor_lift", "lift_ci_low", "lift_ci_high",
            "n_kills", "n_false_kills", "n_speculations",
            "n_corrections", "kill_precision", "kill_recall",
            "mean_abs_prediction_error")
    out = {k: doc.get(k) for k in keys if doc.get(k) is not None}
    out["sweep_schema_version"] = doc.get("sweep_schema_version",
                                          SWEEP_SCHEMA_VERSION)
    if not doc.get("reconciliation", {}).get("ok", False):
        out["error"] = "sweep reconciliation failed"
    return out
