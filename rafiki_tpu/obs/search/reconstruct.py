"""Rebuild a whole sweep from journals alone (``obs sweep``).

The audit plane (:mod:`rafiki_tpu.obs.search.audit`) gives every
proposal, batch draft and feedback a durable record; this module is
the reader that turns a journal directory back into the sweep:
ordered proposals with their acquisition breakdowns, the score each
one earned, the best-so-far/regret curve, lineage roll-ups, and —
when a random-engine baseline ran beside the main advisor — the
advisor lift with a seeded bootstrap CI (the same
:func:`~rafiki_tpu.obs.search.stats.bootstrap_ci` bench.py uses).

Reconciliation is always on and loud: a ``feedback`` whose knobs-hash
never appeared in a ``propose`` record, or a ``propose_batch`` member
with no matching ``propose``, means an advisor decision escaped the
audit trail — the CLI exits nonzero naming the hash, and the sweep
smoke proves that path by doctoring a journal.

Joins (all by the canonical knobs-hash):

    advisor/propose --(hash)--> event/trial_started --(trial_id)-->
        trial/epoch_eval + terminal events
    advisor/feedback --(hash)--> advisor/propose (order-preserving)
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from rafiki_tpu.obs.search import lineage as lineage_mod
from rafiki_tpu.obs.search import stats
from rafiki_tpu.obs.search.audit import knobs_hash

SWEEP_SCHEMA_VERSION = 1


def _group_key(rec: Dict[str, Any]) -> str:
    if rec.get("advisor_id"):
        return str(rec["advisor_id"])
    return (f"{rec.get('engine', '?')}/{rec.get('role', '?')}-"
            f"{rec.get('pid', 0)}/seed{rec.get('seed', 0)}")


def _match(rec: Dict[str, Any], job: Optional[str]) -> bool:
    if not job:
        return True
    j = str(job)
    return (j in str(rec.get("job_id") or "")
            or str(rec.get("advisor_id") or "").startswith(j))


def reconstruct(records: List[Dict[str, Any]], job: Optional[str] = None,
                boot_seed: int = 0,
                n_boot: int = stats.DEFAULT_N_BOOT) -> Dict[str, Any]:
    """Journal records -> sweep document. Never raises on bad input;
    violations land in ``doc["reconciliation"]["errors"]``."""
    adv = [r for r in records
           if r.get("kind") == "advisor" and _match(r, job)]
    groups: Dict[str, Dict[str, Any]] = {}
    for r in adv:
        g = groups.setdefault(_group_key(r), {
            "engine": r.get("engine"), "seed": r.get("seed"),
            "job_id": r.get("job_id"),
            "proposes": [], "feedbacks": [], "batches": []})
        if r.get("name") == "propose":
            g["proposes"].append(r)
        elif r.get("name") == "feedback":
            g["feedbacks"].append(r)
        elif r.get("name") == "propose_batch":
            g["batches"].append(r)

    errors: List[Dict[str, Any]] = []

    # -- per-group audit reconciliation (loud) -------------------------------
    for key, g in groups.items():
        unmatched: Dict[str, int] = {}
        for p in g["proposes"]:
            h = p.get("knobs_hash")
            unmatched[h] = unmatched.get(h, 0) + 1
        for f in g["feedbacks"]:
            h = f.get("knobs_hash")
            if unmatched.get(h, 0) > 0:
                unmatched[h] -= 1
            else:
                errors.append({
                    "type": "feedback_without_propose", "group": key,
                    "knobs_hash": h, "ts": f.get("ts"),
                    "detail": "a score arrived for a knob assignment no "
                              "advisor/propose record ever chose — an "
                              "unjournaled decision or a torn journal"})
        batch_budget: Dict[str, int] = {}
        for p in g["proposes"]:
            h = p.get("knobs_hash")
            batch_budget[h] = batch_budget.get(h, 0) + 1
        for b in g["batches"]:
            for h in b.get("knobs_hashes") or []:
                if batch_budget.get(h, 0) > 0:
                    batch_budget[h] -= 1
                else:
                    errors.append({
                        "type": "batch_member_without_propose",
                        "group": key, "knobs_hash": h, "ts": b.get("ts"),
                        "detail": "a propose_batch member has no matching "
                                  "advisor/propose record"})

    # -- pick the main sweep + random baseline -------------------------------
    def _n(gk: str) -> int:
        return len(groups[gk]["proposes"])

    non_random = [k for k, g in groups.items() if g["engine"] != "random"]
    main_key = (max(non_random, key=_n) if non_random
                else (max(groups, key=_n) if groups else None))
    baselines = [k for k, g in groups.items()
                 if g["engine"] == "random" and k != main_key]
    base_key = (max(baselines, key=lambda k: len(groups[k]["feedbacks"]))
                if baselines else None)

    # -- trial join: hash -> trial ids (order-preserving queues) -------------
    trial_q: Dict[str, List[str]] = {}
    for r in records:
        if (r.get("kind") == "event" and r.get("name") == "trial_started"
                and r.get("knobs") is not None):
            trial_q.setdefault(knobs_hash(r["knobs"]), []).append(
                str(r.get("trial_id")))
    trials = lineage_mod.build(records)

    doc: Dict[str, Any] = {
        "sweep_schema_version": SWEEP_SCHEMA_VERSION,
        "job": job,
        "groups": {k: {"engine": g["engine"], "seed": g["seed"],
                       "job_id": g["job_id"],
                       "n_proposals": len(g["proposes"]),
                       "n_feedbacks": len(g["feedbacks"]),
                       "n_batches": len(g["batches"])}
                   for k, g in groups.items()},
        "main": main_key,
        "baseline": base_key,
    }

    proposals: List[Dict[str, Any]] = []
    scores: List[float] = []
    n_doomed = 0
    if main_key is not None:
        g = groups[main_key]
        doc["engine"] = g["engine"]
        doc["seed"] = g["seed"]
        # feedback join per hash, order-preserving
        fb_q: Dict[str, List[Dict[str, Any]]] = {}
        for f in g["feedbacks"]:
            fb_q.setdefault(f.get("knobs_hash"), []).append(f)
        for seq, p in enumerate(g["proposes"], start=1):
            h = p.get("knobs_hash")
            fb = fb_q.get(h)
            f = fb.pop(0) if fb else None
            tq = trial_q.get(h)
            tid = tq.pop(0) if tq else None
            t = trials.get(tid) if tid else None
            doomed = bool(
                (f and f.get("doomed"))
                or (t and t["status"] in ("trial_errored",
                                          "trial_diverged")))
            row = {
                "seq": seq, "ts": p.get("ts"), "knobs_hash": h,
                "acquisition": p.get("acquisition"),
                "trial_id": tid,
                "score": f.get("score") if f else None,
                "doomed": doomed,
                "n_epoch_evals": (t or {}).get("n_epoch_evals"),
                "status": (t or {}).get("status"),
            }
            proposals.append(row)
            if f is not None and not doomed:
                scores.append(float(f["score"]))
            if doomed:
                n_doomed += 1
        doc["proposals"] = proposals
        doc["curve"] = stats.regret_curve(scores)
        ts_all = ([p.get("ts") for p in g["proposes"]]
                  + [f.get("ts") for f in g["feedbacks"]])
        ts_all = [t for t in ts_all if t is not None]
        span_s = (max(ts_all) - min(ts_all)) if len(ts_all) > 1 else 0.0
        doc.update({
            "n_proposals": len(proposals),
            "n_scored": len(scores),
            "n_doomed": n_doomed,
            "span_s": round(span_s, 6),
            "best_score": doc["curve"]["best_score"],
            "regret": doc["curve"]["mean_regret"],
            "effective_trials_per_hour": (
                round(len(scores) / (span_s / 3600.0), 4)
                if span_s > 0 and scores else None),
        })

    # -- advisor lift vs the random baseline ---------------------------------
    if main_key is not None and base_key is not None:
        base_scores = [float(f["score"])
                       for f in groups[base_key]["feedbacks"]
                       if not f.get("doomed")]
        n_pair = min(len(scores), len(base_scores))
        if n_pair:
            diffs = [scores[i] - base_scores[i] for i in range(n_pair)]
            ci = stats.bootstrap_ci(diffs, n_boot=n_boot, seed=boot_seed)
            doc["lift"] = ci
            doc["advisor_lift"] = ci["mean"]
            doc["lift_ci_low"] = ci["lo"]
            doc["lift_ci_high"] = ci["hi"]

    # -- lineage roll-up ------------------------------------------------------
    orphans = lineage_mod.reconcile(trials)
    doc["lineage"] = {
        "n_trials": len(trials),
        "n_evictions": sum(t["n_evictions"] for t in trials.values()),
        "n_resumes": sum(t["n_resumes"] for t in trials.values()),
        "n_backfilled": sum(1 for t in trials.values() if t["backfilled"]),
        "n_multi_incarnation": sum(
            1 for t in trials.values() if t["n_incarnations"] > 1),
        "orphans": orphans,
    }

    doc["reconciliation"] = {"ok": not errors, "errors": errors}
    return doc


def artifact(doc: Dict[str, Any]) -> Dict[str, Any]:
    """The trendable SWEEP_r*.json slice of a sweep document — headline
    keys at top level for ``bench_report --sweep`` (polarities live in
    its SWEEP_METRICS table)."""
    keys = ("sweep_schema_version", "job", "engine", "seed",
            "n_proposals", "n_scored", "n_doomed", "span_s",
            "best_score", "regret", "effective_trials_per_hour",
            "advisor_lift", "lift_ci_low", "lift_ci_high")
    out = {k: doc.get(k) for k in keys if doc.get(k) is not None}
    out["sweep_schema_version"] = doc.get("sweep_schema_version",
                                          SWEEP_SCHEMA_VERSION)
    if not doc.get("reconciliation", {}).get("ok", False):
        out["error"] = "sweep reconciliation failed"
    return out
