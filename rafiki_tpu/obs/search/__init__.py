"""Search anatomy plane: the Advisor loop, made auditable.

The paper's core claim is proposal quality — the GP/TPE loop finds
better knobs faster than random — yet the search loop is the one part
of the stack the other observability planes never open up. This
package closes that:

* :mod:`~rafiki_tpu.obs.search.audit` — journal-record helpers every
  advisor ``_propose*``/``_feedback`` implementation calls (enforced
  by the RF011 checker), carrying acquisition internals (EI of the
  chosen candidate, posterior mean/std, pool size, constant-liar
  state, fit wall-time, seed) keyed by a knobs-hash joinable against
  ``event/trial_started`` and ``trial/epoch_eval`` records;
* :mod:`~rafiki_tpu.obs.search.ledger` — the ``search`` telemetry
  collector charging wall-time to proposed-but-doomed vs
  completed-and-scored trials (``search.effective_trials_per_hour``,
  ``search.regret``, ``search.best_score``);
* :mod:`~rafiki_tpu.obs.search.reconstruct` — rebuilds a whole sweep
  from journals alone (ordered proposals, scores, best-so-far/regret
  curve, advisor-lift-vs-random with a bootstrap CI) and fails loudly
  when a feedback has no matching proposal record;
* :mod:`~rafiki_tpu.obs.search.lineage` — stitches the already-
  journaled pack/evict/backfill/resume/repack events into explicit
  trial genealogy, with fleet-wide orphan reconciliation;
* :mod:`~rafiki_tpu.obs.search.stats` — the seeded bootstrap-CI
  helper shared by the reconstruction and ``bench.py``.

Read through ``python -m rafiki_tpu.obs sweep`` / ``... lineage``
(docs/search_anatomy.md).
"""

from __future__ import annotations

import importlib

_LAZY = ("audit", "ledger", "lineage", "reconstruct", "stats", "cli")

__all__ = list(_LAZY)


def __getattr__(name: str):
    if name in _LAZY:
        mod = importlib.import_module(f"rafiki_tpu.obs.search.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
