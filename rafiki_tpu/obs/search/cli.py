"""``obs sweep`` / ``obs lineage`` — the search-anatomy reader verbs.

Mounted by :mod:`rafiki_tpu.obs.cli` the same way the twin verbs are:
``attach(sub)`` is stdlib-only at import time; the reconstruction
(numpy for the bootstrap) loads inside the verbs.

    sweep [job]     rebuild the whole sweep from journals alone:
                    ordered proposals with acquisition breakdowns,
                    scores, best-so-far/regret curve, lineage roll-up,
                    advisor lift vs the random baseline with a seeded
                    bootstrap CI. Exit 1 when audit reconciliation
                    fails (a feedback or batch member with no propose
                    record) or no advisor records exist. ``--out``
                    writes the trendable SWEEP_r*.json artifact for
                    ``bench_report --sweep``.
    lineage [trial] walk one trial across incarnations, chips and
                    packs; omit the trial for the fleet-wide table.
                    ``--check`` exits 1 on orphaned incarnations —
                    trials the fleet lost without writing down why.
    resume [job]    reconstruct a sweep's crash→detect→adopt→
                    reconcile→resume timeline from journals alone:
                    supervisor incarnations, the fault that killed
                    generation 0, WAL reconcile verdicts, advisor
                    rehydration, adopted-trial feedback routing, and
                    the first post-resume proposal batch. Exit 1 when
                    no recovery records exist for the job — a resume
                    that leaves no story is itself a failure
                    (docs/recovery.md).
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict


def attach(sub) -> None:
    sp = sub.add_parser(
        "sweep",
        help="reconstruct a sweep from advisor/* journal records")
    sp.add_argument("job", nargs="?", default=None,
                    help="job-id substring or advisor-id prefix filter")
    sp.add_argument("--out", default=None,
                    help="write the SWEEP artifact (bench_report --sweep)")
    sp.add_argument("--boot-seed", type=int, default=0,
                    help="bootstrap-CI seed (default 0, deterministic)")
    sp = sub.add_parser(
        "lineage",
        help="trial genealogy from journaled lifecycle events")
    sp.add_argument("trial", nargs="?", default=None,
                    help="trial id or unique prefix (omit for all)")
    sp.add_argument("--check", action="store_true",
                    help="exit 1 on orphaned incarnations")
    sp = sub.add_parser(
        "resume",
        help="crash→adopt→resume timeline from recovery records")
    sp.add_argument("job", nargs="?", default=None,
                    help="job-id substring filter (omit for all)")


def dispatch(args, log_dir: str, as_json: bool) -> int:
    if args.cmd == "sweep":
        return cmd_sweep(args, log_dir, as_json)
    if args.cmd == "resume":
        return cmd_resume(args, log_dir, as_json)
    return cmd_lineage(args, log_dir, as_json)


def _print_sweep(doc: Dict[str, Any]) -> None:
    print(f"sweep: engine={doc.get('engine')} seed={doc.get('seed')} "
          f"advisor={doc.get('main')}"
          + (f" job={doc.get('job')}" if doc.get("job") else ""))
    print(f"  proposals={doc.get('n_proposals')} "
          f"scored={doc.get('n_scored')} doomed={doc.get('n_doomed')} "
          f"span={doc.get('span_s')}s "
          f"eff_trials_per_hour={doc.get('effective_trials_per_hour')}")
    curve = doc.get("curve") or {}
    print(f"  best={curve.get('best_score')} "
          f"mean_regret={curve.get('mean_regret')}")
    for p in doc.get("proposals") or []:
        acq = p.get("acquisition") or {}
        why = acq.get("phase", "?")
        if why == "ei":
            why += (f" ei={acq.get('ei')} mu={acq.get('mu')} "
                    f"sigma={acq.get('sigma')} pool={acq.get('pool')}")
            if acq.get("fit_s") is not None:
                why += f" fit={acq['fit_s']}s"
        elif why == "tpe":
            why += (f" log_ratio={acq.get('log_ratio')} "
                    f"pool={acq.get('pool')} n_good={acq.get('n_good')}")
        mark = " DOOMED" if p.get("doomed") else ""
        if p.get("killed"):
            fk = " FALSE-KILL" if p.get("false_kill") else ""
            mark += (f" KILLED@e{p.get('kill_epoch')}"
                     f"(pred={p.get('predicted_final')}){fk}")
        elif p.get("speculated"):
            mark += (" corrected" if p.get("corrected")
                     else " SPECULATED")
        if p.get("prediction_error") is not None:
            mark += f" pred_err={p['prediction_error']}"
        print(f"  #{p['seq']:>3} {p.get('knobs_hash')} "
              f"score={p.get('score')}{mark} "
              f"trial={p.get('trial_id')}  [{why}]")
    ca = doc.get("curve_advisor") or {}
    if any(ca.get(k) for k in ("n_predicts", "n_kills",
                               "n_speculations")):
        print(f"  curve advisor: predicts={ca.get('n_predicts')} "
              f"kills={ca.get('n_kills')} "
              f"false_kills={ca.get('n_false_kills')} "
              f"speculations={ca.get('n_speculations')} "
              f"corrections={ca.get('n_corrections')} "
              f"precision={ca.get('kill_precision')} "
              f"recall={ca.get('kill_recall')} "
              f"mean_abs_pred_err={ca.get('mean_abs_prediction_error')}")
    if doc.get("advisor_lift") is not None:
        print(f"  lift vs random: {doc['advisor_lift']} "
              f"[{doc.get('lift_ci_low')}, {doc.get('lift_ci_high')}] "
              f"(n={doc.get('lift', {}).get('n')}, seeded bootstrap)")
    lin = doc.get("lineage") or {}
    print(f"  lineage: trials={lin.get('n_trials')} "
          f"evictions={lin.get('n_evictions')} "
          f"resumes={lin.get('n_resumes')} "
          f"backfilled={lin.get('n_backfilled')} "
          f"orphans={len(lin.get('orphans') or [])}")


def cmd_sweep(args, log_dir: str, as_json: bool) -> int:
    from rafiki_tpu.obs import journal as journal_mod
    from rafiki_tpu.obs.search import reconstruct as rec_mod

    records = journal_mod.read_dir(log_dir)
    if not any(r.get("kind") == "advisor" for r in records):
        print(f"no advisor records under {log_dir} (did the sweep "
              f"journal? see docs/search_anatomy.md)", file=sys.stderr)
        return 1
    doc = rec_mod.reconstruct(records, job=args.job,
                              boot_seed=args.boot_seed)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rec_mod.artifact(doc), f)
    if as_json:
        print(json.dumps(doc, default=str))
    else:
        _print_sweep(doc)
    recon = doc.get("reconciliation") or {}
    if not recon.get("ok"):
        print("SWEEP RECONCILIATION FAILED — advisor decisions escaped "
              "the audit trail:", file=sys.stderr)
        for e in recon.get("errors") or []:
            print(f"  {e['type']}: group={e.get('group')} "
                  f"knobs_hash={e.get('knobs_hash')} — {e.get('detail')}",
                  file=sys.stderr)
        return 1
    return 0


def cmd_lineage(args, log_dir: str, as_json: bool) -> int:
    from rafiki_tpu.obs import journal as journal_mod
    from rafiki_tpu.obs.search import lineage as lineage_mod

    records = journal_mod.read_dir(log_dir)
    trials = lineage_mod.build(records)
    if not trials:
        print(f"no trial lifecycle records under {log_dir}",
              file=sys.stderr)
        return 1
    if args.trial:
        t = lineage_mod.walk(trials, args.trial)
        if t is None:
            print(f"no unique trial matching {args.trial!r} "
                  f"({len(trials)} trials known)", file=sys.stderr)
            return 1
        if as_json:
            print(json.dumps(t, default=str))
            return 0
        _print_trial(t)
        return 0
    orphans = lineage_mod.reconcile(trials)
    if as_json:
        print(json.dumps({"trials": trials, "orphans": orphans},
                         default=str))
    else:
        for tid in sorted(trials):
            t = trials[tid]
            back = " backfilled" if t["backfilled"] else ""
            print(f"trial {tid}: {t['status']}{back} "
                  f"incarnations={t['n_incarnations']} "
                  f"workers={t['workers']} "
                  f"evictions={t['n_evictions']} "
                  f"resumes={t['n_resumes']}")
        print(f"-- {len(trials)} trials, {len(orphans)} orphaned")
    if args.check and orphans:
        print("LINEAGE RECONCILIATION FAILED — orphaned incarnations "
              "(started, never resolved):", file=sys.stderr)
        for o in orphans:
            print(f"  trial {o['trial_id']} incarnation "
                  f"{o['incarnation']} on {o['worker_id']} — last event "
                  f"{o['last_event']}", file=sys.stderr)
        return 1
    return 0


def _resume_timeline(records, job: str = None) -> Dict[str, Any]:
    """The recovery story, assembled from journals alone — no store,
    no WAL file. Selects supervisor lifecycle, injected faults,
    recovery/* verdicts and the post-resume advisor continuation, in
    timestamp order."""
    def _match(r) -> bool:
        return job is None or job in str(r.get("job_id") or "")

    picked = []
    for r in records:
        kind, name = r.get("kind"), r.get("name")
        if kind == "recovery" and _match(r):
            picked.append(r)
        elif kind == "mesh" and name in (
                "supervisor_started", "sweep_started", "host_lost",
                "chip_lost", "repack", "repack_failed") and _match(r):
            picked.append(r)
        elif kind == "chaos":
            # Fault records carry no job id; scoped by the log dir.
            picked.append(r)
        elif (kind == "event" and name in ("trial_orphan_detected",
                                           "sweep_resumed")):
            picked.append(r)
        elif kind == "advisor" and name == "propose_batch" and _match(r):
            picked.append(r)
    picked.sort(key=lambda r: r.get("ts", 0.0))
    generations = sorted({r.get("generation") for r in picked
                          if r.get("kind") == "mesh"
                          and r.get("name") == "supervisor_started"
                          and r.get("generation") is not None})
    finished = [r for r in picked if r.get("kind") == "recovery"
                and r.get("name") == "resume_finished"]
    return {
        "n_records": len(picked),
        "generations": generations,
        "resumes": len(finished),
        "outcome": finished[-1] if finished else None,
        "timeline": picked,
    }


def cmd_resume(args, log_dir: str, as_json: bool) -> int:
    from rafiki_tpu.obs import journal as journal_mod

    records = journal_mod.read_dir(log_dir)
    doc = _resume_timeline(records, job=args.job)
    has_recovery = any(r.get("kind") == "recovery"
                       for r in doc["timeline"])
    if not has_recovery:
        print(f"no recovery records under {log_dir}"
              + (f" for job {args.job!r}" if args.job else "")
              + " (was resume_sweep ever run? see docs/recovery.md)",
              file=sys.stderr)
        return 1
    if as_json:
        print(json.dumps(doc, default=str))
        return 0
    t0 = doc["timeline"][0].get("ts", 0.0)
    print(f"resume timeline: {doc['n_records']} records, "
          f"supervisor generations {doc['generations']}, "
          f"{doc['resumes']} resume(s)")
    for r in doc["timeline"]:
        dt = (r.get("ts") or 0.0) - t0
        kind, name = r.get("kind"), r.get("name")
        extra = " ".join(
            f"{k}={r[k]}" for k in (
                "generation", "site", "mode", "key", "host", "chip",
                "ok", "n_claims", "n_in_doubt", "errors",
                "n_observations", "n_from_store", "n_from_journal",
                "routed", "score", "adopted", "salvaged", "restarted",
                "continuation", "strategy", "trial_id", "wall_s")
            if r.get(k) not in (None, [], ""))
        print(f"  +{dt:8.3f}s {kind}/{name}"
              + (f"  [{extra}]" if extra else ""))
    out = doc["outcome"]
    if out is not None:
        print(f"-- resumed: adopted={out.get('adopted')} "
              f"salvaged={out.get('salvaged')} "
              f"restarted={out.get('restarted')} "
              f"continuation={out.get('continuation')} "
              f"wall={out.get('wall_s')}s")
    return 0


def _print_trial(t: Dict[str, Any]) -> None:
    back = " backfilled" if t["backfilled"] else ""
    print(f"trial {t['trial_id']}: {t['status']}{back} "
          f"knobs_hash={t['knobs_hash']} "
          f"epoch_evals={t['n_epoch_evals']}")
    if t["repacked_from"]:
        print(f"  repacked off chip(s) {t['repacked_from']}")
    for inc in t["incarnations"]:
        syn = " (synthetic start)" if inc.get("synthetic") else ""
        print(f"  incarnation {inc['seq']} on {inc['worker_id']}"
              f"{syn}: terminal={inc['terminal']}")
        t0 = inc.get("started_ts") or 0.0
        for e in inc["events"]:
            dt = (e.get("ts") or 0.0) - t0
            extra = " ".join(
                f"{k}={e[k]}" for k in ("epoch", "from_epoch", "reason",
                                        "score", "divergence", "error")
                if e.get(k) is not None)
            print(f"    +{dt:8.3f}s {e['event']}"
                  + (f"  [{extra}]" if extra else ""))
