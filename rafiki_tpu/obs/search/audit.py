"""Advisor decision audit: every propose/feedback leaves a record.

The helpers here are the *only* supported way an advisor implementation
journals its decisions — the RF011 checker (docs/static_analysis.md)
errors when a ``_propose*``/``_feedback`` body in the advisor package
returns without calling into this module, so a new engine cannot
silently opt out of the audit trail.

Record shapes, all ``kind="advisor"``:

``advisor/propose``
    one chosen knob assignment: ``engine``/``advisor_id``/``job_id``/
    ``seed``, the full ``knobs`` dict, its ``knobs_hash``, history and
    pending sizes, and the engine's ``acquisition`` breakdown (the
    "why": EI value + posterior mean/std + pool size for GP, KDE
    log-ratio + pool for TPE, warmup/epsilon markers, GP fit wall-time).

``advisor/propose_batch``
    one q-batch draft: ``n``, the drafting ``strategy`` (sequential vs
    constant-liar), liar state, and the member hashes.

``advisor/feedback``
    one observed score: ``knobs_hash``, ``score``, ``best_so_far``,
    history size, and whether the ledger saw the trial doomed.

``advisor/predict`` / ``advisor/kill`` / ``advisor/speculate`` /
``advisor/correct`` / ``advisor/false_kill``
    the learning-curve plane (docs/early_kill.md): one extrapolator
    fit consulted at an epoch boundary, one early-kill verdict, one
    speculative score fed to the engine, one speculative score
    replaced by the truth, one hindsight false-kill verdict. Each
    carries the fit slice (``CurveFit.to_record``: family, decay,
    n_obs, rmse, predicted, band, lo/hi, horizon) plus ``knobs_hash``
    and the kill knobs in force, so PR 15's rehydration can replay
    uncorrected speculations to byte-identical post-resume proposals.

The join key is ``knobs_hash`` — a sha256 prefix over the canonical
JSON of the full knob assignment. Workers already journal the same
dict on ``event/trial_started``, so a reader hashes that side too and
stitches proposal -> trial_id -> ``trial/epoch_eval`` curves without
the advisor ever learning trial ids (it never does in-process either).
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, Optional, Sequence

from rafiki_tpu.obs.journal import journal
from rafiki_tpu.obs.search.ledger import search_ledger

KIND = "advisor"


def knobs_hash(knobs: Dict[str, Any]) -> str:
    """Canonical 16-hex digest of a full knob assignment. Knob values
    are JSON natives (knobs.py samples/decodes to float/int/str), so
    ``sort_keys`` JSON is a stable canonical form on both the writer
    side and the journal-reader side."""
    blob = json.dumps(knobs, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def note_doomed(knobs: Dict[str, Any]) -> None:
    """Worker error paths call this BEFORE the consolation
    ``advisor.feedback(0.0, knobs)`` so the ledger charges the trial's
    wall to the doomed bucket and the feedback record carries
    ``doomed=True`` (errored/diverged/lost — proposed but never
    scored for real)."""
    search_ledger.note_doomed(knobs_hash(knobs))


def _ident(advisor: Any) -> Dict[str, Any]:
    return {
        "engine": getattr(advisor, "engine", type(advisor).__name__),
        "advisor_id": getattr(advisor, "advisor_id", None),
        "job_id": getattr(advisor, "job_id", None),
        "seed": getattr(advisor, "seed", None),
    }


def record_propose(advisor: Any, knobs: Dict[str, Any],
                   acquisition: Optional[Dict[str, Any]] = None) -> str:
    """Journal one chosen assignment; returns its hash so callers can
    thread it into a batch record."""
    h = knobs_hash(knobs)
    search_ledger.note_propose(h)
    journal.record(
        KIND, "propose",
        knobs=dict(knobs),
        knobs_hash=h,
        n_observations=len(getattr(advisor, "history", ())),
        n_pending=len(getattr(advisor, "_pending", ())),
        acquisition=dict(acquisition or {"phase": "unknown"}),
        **_ident(advisor),
    )
    return h


def record_propose_batch(advisor: Any,
                         n: int,
                         knobs_list: Sequence[Dict[str, Any]],
                         strategy: str,
                         liar: Optional[Dict[str, Any]] = None) -> None:
    """Journal one q-batch draft. Members were each journaled by
    ``record_propose`` already; this record carries the batch-level
    state (constant-liar value, how many lies were planted)."""
    journal.record(
        KIND, "propose_batch",
        n=int(n),
        strategy=strategy,
        knobs_hashes=[knobs_hash(k) for k in knobs_list],
        liar=dict(liar) if liar else None,
        **_ident(advisor),
    )


def record_feedback(advisor: Any, score: float,
                    knobs: Dict[str, Any]) -> None:
    h = knobs_hash(knobs)
    doomed = search_ledger.note_feedback(h, float(score))
    best = None
    hist = getattr(advisor, "history", None)
    if hist:
        try:
            best = max(s for _, s in hist)
        except (TypeError, ValueError):
            best = None
    journal.record(
        KIND, "feedback",
        knobs_hash=h,
        score=float(score),
        best_so_far=best,
        doomed=doomed,
        n_observations=len(hist or ()),
        **_ident(advisor),
    )


# -- learning-curve plane (advisor/curve.py, docs/early_kill.md) -------------

def record_predict(knobs: Dict[str, Any], fit: Dict[str, Any],
                   epoch: int, best_so_far: Optional[float],
                   trial_id: Optional[str] = None) -> None:
    """Journal one extrapolator consultation at an epoch boundary.
    ``fit`` is ``CurveFit.to_record()``."""
    journal.record(
        KIND, "predict",
        knobs_hash=knobs_hash(knobs),
        epoch=int(epoch),
        best_so_far=best_so_far,
        trial_id=trial_id,
        **fit,
    )


def record_kill(knobs: Dict[str, Any], fit: Dict[str, Any],
                epoch: int, best_so_far: float,
                config: Dict[str, Any],
                trial_id: Optional[str] = None) -> None:
    """Journal one early-kill verdict: the fit that condemned the
    trial plus the ``RAFIKI_CURVE_KILL*`` knobs in force (``config``),
    so `obs sweep` can audit every kill against the rule that made it.
    Callers still route the trial through ``note_doomed`` + the
    consolation feedback — this record is the *why*, the ledger charge
    is the *cost*."""
    search_ledger.note_kill()
    journal.record(
        KIND, "kill",
        knobs_hash=knobs_hash(knobs),
        epoch=int(epoch),
        best_so_far=float(best_so_far),
        config=dict(config),
        trial_id=trial_id,
        **fit,
    )


def record_speculate(advisor: Any, predicted: float,
                     knobs: Dict[str, Any],
                     fit: Optional[Dict[str, Any]] = None) -> None:
    """Journal one speculative score entering the engine's training
    set. A later ``advisor/feedback`` for the same hash supersedes it
    (the correction); rehydration replays only speculations with no
    such feedback — see advisor/rehydrate.py."""
    search_ledger.note_speculation()
    journal.record(
        KIND, "speculate",
        knobs_hash=knobs_hash(knobs),
        knobs=dict(knobs),
        predicted=float(predicted),
        fit=dict(fit) if fit else None,
        n_observations=len(getattr(advisor, "history", ())),
        **_ident(advisor),
    )


def record_correct(advisor: Any, knobs: Dict[str, Any],
                   predicted: float, actual: float) -> None:
    """Journal one speculative score replaced by the trial's true
    score (the engine refits). The paired ``advisor/feedback`` record
    carries the authoritative score; this one carries the error the
    `obs sweep` prediction-quality roll-up wants."""
    search_ledger.note_correction()
    journal.record(
        KIND, "correct",
        knobs_hash=knobs_hash(knobs),
        predicted=float(predicted),
        actual=float(actual),
        error=float(actual) - float(predicted),
        **_ident(advisor),
    )


def record_false_kill(knobs: Dict[str, Any], killed_predicted: float,
                      sibling_score: float, best_so_far: float) -> None:
    """Hindsight verdict from a ground-truth checker (sweep smoke
    re-runs each killed trial's knobs to completion): the sibling
    finished above best-so-far, so the kill cost the search a
    contender."""
    search_ledger.note_false_kill()
    journal.record(
        KIND, "false_kill",
        knobs_hash=knobs_hash(knobs),
        killed_predicted=float(killed_predicted),
        sibling_score=float(sibling_score),
        best_so_far=float(best_so_far),
    )
