"""Seeded bootstrap statistics shared by the sweep reconstruction and
``bench.py``'s ``detail.search`` block.

One implementation so the CI printed by ``obs sweep`` and the CI
gated by ``bench_report --sweep`` cannot drift apart. Deterministic
under a fixed seed — tests and the sweep smoke assert byte-equality
across runs.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

DEFAULT_N_BOOT = 1000


def bootstrap_ci(diffs: Sequence[float], n_boot: int = DEFAULT_N_BOOT,
                 seed: int = 0, alpha: float = 0.05) -> Dict[str, Any]:
    """Percentile bootstrap CI of the mean of ``diffs``.

    ``diffs`` are paired per-position score differences (advisor minus
    random); the interval answers "is the lift real or seed noise".
    Returns ``{"mean", "lo", "hi", "n", "n_boot", "seed"}``; degenerate
    inputs (fewer than 2 points) collapse the interval onto the mean.
    """
    import numpy as np

    arr = np.asarray(list(diffs), dtype=float)
    n = int(arr.size)
    if n == 0:
        return {"mean": None, "lo": None, "hi": None, "n": 0,
                "n_boot": int(n_boot), "seed": int(seed)}
    mean = float(arr.mean())
    if n == 1:
        return {"mean": round(mean, 6), "lo": round(mean, 6),
                "hi": round(mean, 6), "n": 1,
                "n_boot": int(n_boot), "seed": int(seed)}
    rng = np.random.default_rng(int(seed))
    idx = rng.integers(0, n, size=(int(n_boot), n))
    means = arr[idx].mean(axis=1)
    lo, hi = np.quantile(means, [alpha / 2.0, 1.0 - alpha / 2.0])
    return {"mean": round(mean, 6), "lo": round(float(lo), 6),
            "hi": round(float(hi), 6), "n": n,
            "n_boot": int(n_boot), "seed": int(seed)}


def regret_curve(scores: Sequence[float]) -> Dict[str, Any]:
    """Best-so-far and regret trajectories for an ordered score list.

    ``regret[t] = max(scores) - best_so_far[t]`` — non-increasing by
    construction and 0 at the end; ``mean_regret`` (the area under the
    curve, normalised by length) is the scalar the SWEEP artifact
    trends: a sharper advisor front-loads good proposals and shrinks
    it at equal final best.
    """
    best_so_far = []
    best = None
    for s in scores:
        best = s if best is None else max(best, s)
        best_so_far.append(best)
    if best is None:
        return {"best_so_far": [], "regret": [], "mean_regret": None,
                "best_score": None}
    regret = [round(best - b, 6) for b in best_so_far]
    return {
        "best_so_far": [round(b, 6) for b in best_so_far],
        "regret": regret,
        "mean_regret": round(sum(regret) / len(regret), 6),
        "best_score": round(best, 6),
    }
