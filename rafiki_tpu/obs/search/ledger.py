"""Effective-throughput ledger: what did the search loop's wall buy?

The goodput ledger (:mod:`rafiki_tpu.obs.ledger`) splits a *trial's*
wall into compile/step/feed; this ledger splits the *sweep's* wall by
outcome: time charged to completed-and-scored trials vs time sunk into
proposed-but-doomed ones (errored, diverged, evicted-and-never-
backfilled). The roll-up is the ROADMAP's learning-curve success
metric — ``search.effective_trials_per_hour`` at equal final best —
plus ``search.regret`` and ``search.best_score``, exposed as the
``search`` telemetry collector so it rides every ``GET /metrics``
snapshot and ``bench.py`` detail.

Charging is keyed by the audit plane's knobs-hash: ``note_propose``
opens the meter for a hash, the worker's error paths call
``note_doomed`` *before* sending the advisor its consolation
``feedback(0.0)``, and ``note_feedback`` (called from the audit
helpers) closes the meter into the scored or doomed bucket. Scope is
per process, like every telemetry collector.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from rafiki_tpu import telemetry


class SearchLedger:
    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with getattr(self, "_lock", threading.Lock()):
            self._t0: Optional[float] = None
            self._last: Optional[float] = None
            self._open: Dict[str, List[float]] = {}  # hash -> propose times
            self._doomed_hashes: set = set()
            self._scores: List[float] = []
            self.n_proposed = 0
            self.n_scored = 0
            self.n_doomed = 0
            self.scored_wall_s = 0.0
            self.doomed_wall_s = 0.0
            self.best_score: Optional[float] = None
            # Curve-advisor outcomes (docs/early_kill.md). Kills are a
            # subset of doomed; false kills are hindsight verdicts a
            # ground-truth checker (sweep smoke's sibling re-runs)
            # establishes after the fact.
            self.n_killed = 0
            self.n_false_kills = 0
            self.n_speculations = 0
            self.n_corrections = 0

    # -- writes --------------------------------------------------------------

    def note_propose(self, knobs_hash: str) -> None:
        now = time.monotonic()
        with self._lock:
            if self._t0 is None:
                self._t0 = now
            self._last = now
            self._open.setdefault(knobs_hash, []).append(now)
            self.n_proposed += 1

    def note_doomed(self, knobs_hash: str) -> None:
        """Flag a proposal as doomed (errored/diverged/lost) so the
        *next* feedback for this hash — the worker's consolation
        ``feedback(0.0)`` — charges the doomed bucket, not the scored
        one."""
        with self._lock:
            self._doomed_hashes.add(knobs_hash)

    def note_kill(self) -> None:
        """One trial early-killed off a curve prediction. Callers pair
        this with ``note_doomed`` — the kill counter explains *why* the
        doomed bucket grew."""
        with self._lock:
            self.n_killed += 1
            n = self.n_killed
        telemetry.set_gauge("search.kills", float(n))

    def note_false_kill(self) -> None:
        """Hindsight verdict: a killed trial's sibling re-run finished
        above best-so-far (sweep smoke's false-kill gate)."""
        with self._lock:
            self.n_false_kills += 1
            n = self.n_false_kills
        telemetry.set_gauge("search.false_kills", float(n))

    def note_speculation(self) -> None:
        """One in-flight trial fed the advisor a predicted score. The
        propose meter stays open — the trial is still running."""
        with self._lock:
            self.n_speculations += 1
            n = self.n_speculations
        telemetry.set_gauge("search.speculations", float(n))

    def note_correction(self) -> None:
        """One speculative score replaced by the trial's true score."""
        with self._lock:
            self.n_corrections += 1
            n = self.n_corrections
        telemetry.set_gauge("search.corrections", float(n))

    def note_feedback(self, knobs_hash: str, score: float) -> bool:
        """Close the meter for one proposal. Returns True when the
        trial was doomed (callers stamp that onto the journal record)."""
        now = time.monotonic()
        with self._lock:
            self._last = now
            opened = self._open.get(knobs_hash)
            wall = (now - opened.pop(0)) if opened else 0.0
            if opened is not None and not opened:
                self._open.pop(knobs_hash, None)
            doomed = knobs_hash in self._doomed_hashes
            self._doomed_hashes.discard(knobs_hash)
            if doomed:
                self.n_doomed += 1
                self.doomed_wall_s += wall
            else:
                self.n_scored += 1
                self.scored_wall_s += wall
                self._scores.append(float(score))
                if self.best_score is None or score > self.best_score:
                    self.best_score = float(score)
            snap = self._snapshot_locked()
        telemetry.set_gauge("search.effective_trials_per_hour",
                            snap["effective_trials_per_hour"] or 0.0)
        telemetry.set_gauge("search.regret", snap["regret"] or 0.0)
        telemetry.set_gauge("search.best_score", snap["best_score"] or 0.0)
        return doomed

    # -- reads ---------------------------------------------------------------

    def _snapshot_locked(self) -> Dict[str, Any]:
        # Elapsed is frozen at the last write (first→last event, the same
        # window `obs sweep` reports as span_s) rather than read off the
        # live clock: an idle ledger must snapshot byte-identically, or
        # every /metrics scrape (and the prom determinism gate) would
        # disagree with the previous one.
        elapsed = ((self._last - self._t0)
                   if self._t0 is not None and self._last is not None
                   else 0.0)
        eff = (round(self.n_scored / (elapsed / 3600.0), 4)
               if elapsed > 0.0 and self.n_scored else None)
        # Running mean regret vs the best score this process has seen —
        # same definition the journal reconstruction uses, so the live
        # gauge and `obs sweep` agree on a finished sweep.
        regret = None
        if self._scores:
            best_so_far, best = [], None
            for s in self._scores:
                best = s if best is None else max(best, s)
                best_so_far.append(best)
            final = best_so_far[-1]
            regret = round(sum(final - b for b in best_so_far)
                           / len(best_so_far), 6)
        return {
            "n_proposed": self.n_proposed,
            "n_scored": self.n_scored,
            "n_doomed": self.n_doomed,
            "n_pending": sum(len(v) for v in self._open.values()),
            "scored_wall_s": round(self.scored_wall_s, 6),
            "doomed_wall_s": round(self.doomed_wall_s, 6),
            "elapsed_s": round(elapsed, 6),
            "effective_trials_per_hour": eff,
            "regret": regret,
            "best_score": (round(self.best_score, 6)
                           if self.best_score is not None else None),
            "n_killed": self.n_killed,
            "n_false_kills": self.n_false_kills,
            "n_speculations": self.n_speculations,
            "n_corrections": self.n_corrections,
        }

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able roll-up; this is the ``search`` collector."""
        with self._lock:
            return self._snapshot_locked()


#: Process-global search ledger (telemetry scope rules: per process).
search_ledger = SearchLedger()

telemetry.register_collector("search", search_ledger.snapshot)
