"""Trace context: one id that survives process boundaries.

A *trace* is one logical unit of work as the user sees it — one gateway
query, one trial, one train job — regardless of how many processes it
crosses. The context here is deliberately tiny: a ``trace_id`` string
carried in (a) a per-thread slot for in-process propagation, (b) bus
message envelopes for the serving path, and (c) the ``RAFIKI_TRACE_ID``
environment variable for spawned worker processes.

This module is dependency-free (stdlib only) on purpose: telemetry
imports it to stamp span records, so it must not import telemetry back.

Usage::

    from rafiki_tpu.obs import context

    with context.trace():                 # new trace at the edge
        ...                               # spans/journal records inherit it

    with context.trace(incoming_id):      # continue a propagated trace
        ...

    context.set_process_trace(tid)        # whole-process default (workers)
"""

from __future__ import annotations

import contextlib
import os
import threading
import uuid
from typing import Iterator, Optional

ENV_VAR = "RAFIKI_TRACE_ID"

_tls = threading.local()
#: Process-wide default, used when no thread-local trace is active —
#: spawned workers inherit the job trace this way (set from ENV_VAR).
_process_trace: Optional[str] = None


def new_trace_id() -> str:
    """A fresh 32-hex-char trace id."""
    return uuid.uuid4().hex


def current_trace_id() -> Optional[str]:
    """The active trace id: thread-local first, then the process
    default, else None (untraced work)."""
    tid = getattr(_tls, "trace_id", None)
    if tid is not None:
        return tid
    return _process_trace


def set_process_trace(trace_id: Optional[str]) -> None:
    """Set the process-wide default trace (worker startup)."""
    global _process_trace
    _process_trace = trace_id


@contextlib.contextmanager
def trace(trace_id: Optional[str] = None) -> Iterator[str]:
    """Bind ``trace_id`` (or a fresh one) to this thread for the
    duration of the block. Nesting restores the outer binding."""
    tid = trace_id or current_trace_id() or new_trace_id()
    prev = getattr(_tls, "trace_id", None)
    _tls.trace_id = tid
    try:
        yield tid
    finally:
        _tls.trace_id = prev


def configure_from_env() -> None:
    """Adopt the spawning process's trace via RAFIKI_TRACE_ID."""
    tid = os.environ.get(ENV_VAR)
    if tid:
        set_process_trace(tid)


# -- tenant context -----------------------------------------------------------
# The tenant id rides exactly like the trace id: bound at the serving
# edge (gateway / HTTP header), carried per-thread, stamped into bus
# envelopes by queues._current_trace so worker-side journal records can
# attribute work to a tenant (docs/multitenancy.md). Unlike traces,
# there is no fresh-id fallback — untagged work stays tenant-less.

def current_tenant() -> Optional[str]:
    """The active tenant id, or None for untagged work."""
    return getattr(_tls, "tenant_id", None)


@contextlib.contextmanager
def tenant_scope(tenant: Optional[str]) -> Iterator[Optional[str]]:
    """Bind ``tenant`` to this thread for the duration of the block
    (None binds nothing but still restores the outer value)."""
    prev = getattr(_tls, "tenant_id", None)
    _tls.tenant_id = tenant if tenant is not None else prev
    try:
        yield tenant
    finally:
        _tls.tenant_id = prev
