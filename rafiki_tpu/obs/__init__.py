"""Sweep-wide observability plane (docs/observability.md).

Four layers on top of :mod:`rafiki_tpu.telemetry`:

* :mod:`~rafiki_tpu.obs.context` — trace ids propagated across threads,
  bus envelopes and worker-spawn env;
* :mod:`~rafiki_tpu.obs.journal` — bounded per-process JSONL journals
  under ``RAFIKI_LOG_DIR`` that spans/events/chaos decisions flush into;
* :mod:`~rafiki_tpu.obs.ledger` — goodput/cost accounting (compile vs
  step vs feed vs checkpoint vs downtime) per trial/pack/job;
* :mod:`~rafiki_tpu.obs.recorder` — flight recorder dumping the last-N
  ring to disk on fatal/interrupt;
* :mod:`~rafiki_tpu.obs.perf` — perf sentinel: per-program cost
  profiling, SLO burn-rate alerting, step-time anomaly detection
  (docs/perf.md);
* :mod:`~rafiki_tpu.obs.search` — search anatomy: advisor decision
  audit, trial lineage, effective-trials-per-hour ledger
  (docs/search_anatomy.md);

plus :mod:`~rafiki_tpu.obs.prom` (Prometheus text exposition of the
registry snapshot) and the ``python -m rafiki_tpu.obs`` CLI
(:mod:`~rafiki_tpu.obs.cli`) that merges journals across processes.

Import discipline: this package's eager surface (context, journal) is
stdlib-only so telemetry can import it without a cycle; ledger/prom/
recorder/cli import telemetry and load lazily via ``__getattr__``.
"""

from __future__ import annotations

import importlib

from rafiki_tpu.obs import context, journal  # noqa: F401  (eager, dep-free)

_LAZY = ("anatomy", "ledger", "perf", "prom", "recorder", "search",
         "twin", "cli")

__all__ = ["context", "journal", *_LAZY, "configure_from_env"]


def __getattr__(name: str):
    if name in _LAZY:
        mod = importlib.import_module(f"rafiki_tpu.obs.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def configure_from_env(role=None) -> bool:
    """One call a process makes at startup: adopt RAFIKI_TRACE_ID and,
    when RAFIKI_LOG_DIR is set, open this process's journal. Returns
    True when a journal was configured."""
    return journal.configure_from_env(role=role)
