"""Durable per-process event journal: a bounded JSONL ring on disk.

Telemetry (PR 1) keeps spans and counters in memory — which is exactly
the evidence that dies with the process when chaos (PR 5) kills it.
The journal is the durable complement: every finished span, structured
event, chaos injection and gateway decision appends one JSON line to a
per-process file under ``RAFIKI_LOG_DIR``:

    <log_dir>/journal-<role>-<pid>.jsonl

One file per process means no cross-process write interleaving and no
locking beyond the in-process handle lock; readers (``python -m
rafiki_tpu.obs``, the chaos runner's reconstruction checks) merge the
files and sort by timestamp.

*Bounded*: after ``RAFIKI_JOURNAL_MAX`` lines (default 4096) the file
rotates to ``<name>.1`` (overwriting the previous generation), so a
journal never holds more than 2×max records — same philosophy as the
in-memory span ring, applied to disk.

Every record carries ``ts``/``pid``/``role``/``kind``/``name`` plus the
active ``trace_id`` (from :mod:`rafiki_tpu.obs.context`), which is what
lets one gateway query be stitched back together across the gateway
process, the bus, and k inference workers.

Unconfigured, ``record`` is a no-op — library code journals
unconditionally, hosts opt in via ``configure``/``RAFIKI_LOG_DIR``.
This module is dependency-free (stdlib only): telemetry flushes spans
into it, so it must not import telemetry back.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional

from rafiki_tpu.obs import context

ENV_VAR = "RAFIKI_LOG_DIR"
ENV_MAX = "RAFIKI_JOURNAL_MAX"
DEFAULT_MAX = 4096


class Journal:
    """Bounded per-process JSONL journal (see module docstring)."""

    def __init__(self, log_dir: Optional[str | os.PathLike] = None,
                 role: str = "proc", max_records: Optional[int] = None):
        self._lock = threading.Lock()
        self._path: Optional[Path] = None
        self._fh = None
        self._count = 0
        self.role = role
        self.max_records = max_records or int(
            os.environ.get(ENV_MAX, DEFAULT_MAX))
        if log_dir is not None:
            self.configure(log_dir, role=role)

    # -- configuration -------------------------------------------------------

    def configure(self, log_dir: str | os.PathLike,
                  role: Optional[str] = None) -> "Journal":
        with self._lock:
            if role:
                self.role = role
            if self._fh is not None:
                self._fh.close()
            d = Path(log_dir)
            d.mkdir(parents=True, exist_ok=True)
            self._path = d / f"journal-{self.role}-{os.getpid()}.jsonl"
            # Re-configuring onto an existing file (same pid, e.g. a
            # worker that re-execs configure) keeps the ring bound.
            if self._path.exists():
                with open(self._path, "rb") as f:
                    self._count = sum(1 for _ in f)
            else:
                self._count = 0
            self._fh = open(self._path, "a", buffering=1)
        return self

    @property
    def path(self) -> Optional[Path]:
        return self._path

    @property
    def log_dir(self) -> Optional[Path]:
        return self._path.parent if self._path is not None else None

    @property
    def configured(self) -> bool:
        return self._fh is not None

    # -- writes --------------------------------------------------------------

    def record(self, kind: str, name: str, **fields: Any) -> None:
        """Append one record; no-op when unconfigured. ``trace_id`` is
        stamped from the active context unless the caller passes one."""
        with self._lock:
            if self._fh is None:
                return
            rec: Dict[str, Any] = {
                "ts": fields.pop("ts", None) or time.time(),
                "pid": os.getpid(),
                "role": self.role,
                "kind": kind,
                "name": name,
            }
            tid = fields.pop("trace_id", None) or context.current_trace_id()
            if tid:
                rec["trace_id"] = tid
            rec.update(fields)
            if self._count >= self.max_records:
                self._rotate_locked()
            self._fh.write(json.dumps(rec, default=str) + "\n")
            self._count += 1

    def _rotate_locked(self) -> None:
        """Shift the live file to the ``.1`` generation (overwriting the
        previous one) and start fresh — bounds disk at 2×max lines."""
        self._fh.close()
        old = self._path.with_name(self._path.name + ".1")
        os.replace(self._path, old)
        self._fh = open(self._path, "a", buffering=1)
        self._count = 0

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    # -- reads ---------------------------------------------------------------

    def tail(self, n: int = 64) -> List[Dict[str, Any]]:
        """The last ``n`` records of THIS process's journal (both
        generations), oldest first. Used by the flight recorder."""
        if self._path is None:
            return []
        records: List[Dict[str, Any]] = []
        old = self._path.with_name(self._path.name + ".1")
        for p in (old, self._path):
            records.extend(_read_file(p))
        return records[-n:]


def _read_file(path: Path) -> Iterator[Dict[str, Any]]:
    if not path.exists():
        return
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except json.JSONDecodeError:
                continue  # torn line from a crashed writer


def read_dir(log_dir: str | os.PathLike) -> List[Dict[str, Any]]:
    """Merge every journal file (all processes, all generations) under
    ``log_dir``, sorted by timestamp. The CLI and the chaos runner's
    journal-reconstruction checks read through this."""
    records: List[Dict[str, Any]] = []
    for p in sorted(glob.glob(str(Path(log_dir) / "journal-*.jsonl*"))):
        records.extend(_read_file(Path(p)))
    records.sort(key=lambda r: r.get("ts", 0.0))
    return records


#: Process-global journal; subsystems record into it unconditionally,
#: hosts opt in via ``journal.configure(dir)`` / RAFIKI_LOG_DIR.
journal = Journal()


def configure_from_env(role: Optional[str] = None) -> bool:
    """Subprocess workers inherit the sink via RAFIKI_LOG_DIR (the
    trace default rides along via RAFIKI_TRACE_ID). Returns True when
    a journal was configured."""
    context.configure_from_env()
    d = os.environ.get(ENV_VAR)
    if d:
        journal.configure(d, role=role)
        return True
    return False
