"""Train-twin-vs-real validation: replay a captured mesh sweep through
the simulator and score predicted against measured throughput.

Both sides derive from the same journal directory, keeping the
comparison honest:

* **measured** — the training window reconstructed from the packed
  ``perf/step`` records: wall clock spans the first epoch start
  (``ts - dt``) to the last epoch end (``ts``); the trial count comes
  from ``mesh/sweep_started`` (falling back to the distinct member ids
  in ``mesh/pack_formed``).
* **replayed placement** — the literal packs ``mesh/pack_formed``
  recorded, so the simulator runs the schedule the scheduler actually
  produced, not a re-derivation.
* **calibration** — per-(packing_key, k) epoch samples + the fitted
  epoch overhead from the very same run.

Prediction error is relative for BOTH trials/hour and total wall:
``|predicted - measured| / measured``; the gate passes only if both
are within tolerance. ``scales`` deliberately mis-calibrates (e.g.
``step=2.0``) — the negative polarity in scripts/train_twin_smoke.py
proves the gate actually fails when the model is wrong.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from rafiki_tpu.obs import journal as journal_mod
from rafiki_tpu.obs.twin.train.calibration import (TrainCalibration,
                                                   TrainCalibrationError)
from rafiki_tpu.obs.twin.train.engine import (TrainTwinConfig,
                                              packs_from_calibration,
                                              simulate)

TRAIN_VALIDATE_SCHEMA_VERSION = 1

#: Default relative-error gate — the acceptance bar: predicted
#: trials/hour and wall within 25% of measured. The twin is a capacity
#: model; it must catch a doubled step time, not a 5% drift.
DEFAULT_TOLERANCE = 0.25

#: Minimum measured trials for a throughput comparison to mean much.
MIN_TRIALS = 2


def measured_from_records(records: List[Dict[str, Any]]
                          ) -> Tuple[int, Optional[float]]:
    """(n_trials, wall_s) of the captured sweep's training window."""
    steps = [r for r in records
             if r.get("kind") == "perf" and r.get("name") == "step"
             and r.get("packing_key")
             and isinstance(r.get("ts"), (int, float))
             and isinstance(r.get("dt"), (int, float))]
    wall = None
    if len(steps) >= 2:
        wall = (max(float(r["ts"]) for r in steps)
                - min(float(r["ts"]) - float(r["dt"]) for r in steps))
    elif len(steps) == 1:
        wall = float(steps[0]["dt"])
    n = 0
    member_ids = set()
    for r in records:
        if r.get("kind") != "mesh":
            continue
        if r.get("name") == "sweep_started" and r.get("n_trials"):
            n = int(r["n_trials"])
        elif r.get("name") == "pack_formed":
            member_ids.update(r.get("trial_ids") or [])
    return (n or len(member_ids)), wall


def validate(log_dir, seed: int = 0,
             tolerance: float = DEFAULT_TOLERANCE,
             scales: Optional[Dict[str, float]] = None) -> Dict[str, Any]:
    """Score the train twin against one captured sweep. Returns the
    gate artifact (the TRAINTWIN_r*.json / ``bench_report
    --train-twin`` ledger format); ``ok`` is the verdict. Raises
    :class:`TrainCalibrationError` if the journals can't calibrate and
    ``ValueError`` when too few trials were measured."""
    records = journal_mod.read_dir(log_dir)
    if not records:
        raise TrainCalibrationError(
            ["perf/step", "mesh/pack_formed"], str(log_dir))
    cal = TrainCalibration.from_records(records, source=str(log_dir))
    if scales:
        cal = cal.scaled(scales)
    n_meas, wall_meas = measured_from_records(records)
    if n_meas < MIN_TRIALS or not wall_meas or wall_meas <= 0:
        raise ValueError(
            f"only {n_meas} measured trial(s) over "
            f"{wall_meas if wall_meas else 0:.3f}s in {log_dir}; need "
            f">= {MIN_TRIALS} trials with packed perf/step records "
            f"(run scripts/train_twin_smoke.py --capture DIR)")
    packs = packs_from_calibration(cal)
    cfg = TrainTwinConfig.from_calibration(cal)
    res = simulate(cal, cfg, packs=packs, seed=seed)
    tph_meas = n_meas / wall_meas * 3600.0
    measured = {"trials": n_meas,
                "wall_s": round(wall_meas, 4),
                "trials_per_hour": round(tph_meas, 4)}
    predicted = {"trials": res["completed"],
                 "wall_s": res["makespan_s"],
                 "trials_per_hour": res["trials_per_hour"],
                 "utilization": res["utilization"],
                 "status": res["status"]}
    tph_err = _rel_err(res["trials_per_hour"], tph_meas)
    wall_err = _rel_err(res["makespan_s"], wall_meas)
    ok = (tph_err is not None and wall_err is not None
          and tph_err <= tolerance and wall_err <= tolerance)
    return {
        "train_twin_schema_version": TRAIN_VALIDATE_SCHEMA_VERSION,
        "source": str(log_dir),
        "seed": seed,
        "tolerance": tolerance,
        "scales": dict(scales or {}),
        "measured": measured,
        "predicted": predicted,
        "tph_err": None if tph_err is None else round(tph_err, 4),
        "wall_err": None if wall_err is None else round(wall_err, 4),
        "ok": ok,
        "event_log_sha1": res["event_log_sha1"],
        "config": res["config"],
        # Wall stamp for the TRAINTWIN_r*.json trend ledger — metadata
        # only, never an input to the simulation itself.
        "created_ts": round(time.time(), 3),  # lint: disable=RF010 — artifact timestamp, not simulation state; determinism covers everything above
    }


def _rel_err(pred: Optional[float], meas: Optional[float]
             ) -> Optional[float]:
    if pred is None or meas is None or meas <= 0:
        return None
    return abs(pred - meas) / meas
