"""Sweep-planning what-ifs over the train twin (docs/twin.md): the
questions a sweep owner should answer BEFORE chips are claimed.

* :func:`best_k` — the best ``RAFIKI_TRIAL_PACK`` width per packing
  key: larger packs amortize one compile over more trials but pay a
  wider (slower) step; the calibrated step/compile distributions
  arbitrate, per key.
* :func:`split_search` — many-small-chips vs big-trial-groups: the
  same trial budget simulated across (chips, k) splits, ranked by
  predicted trials/hour (HBM headroom reported alongside — a winning
  split that does not fit is not a winner).
* :func:`member_forecast` — predicted trials/hour and HBM headroom for
  a PROPOSED zoo member that was never trained: roofline step time
  from its ``perf/cost`` row at an assumed MFU.
* :func:`group_width_forecast` — the sharded-lane question
  (docs/sharding.md): one trial of a family run as a width-w chip
  group, per candidate width — measured group epoch walls where the
  calibration has ``@groupw`` buckets, per-chip HBM share, and the
  smallest width that fits (the same solve shard/plan.py performs
  live, answered from the twin before chips are claimed).
* :func:`sweep` — a generic config grid (chips/k/n_trials), one
  simulation per combination — the ``obs twin train sweep`` verb.

Everything here is deterministic per seed (the engine's contract) and
pure planning: nothing mutates the live sweep.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Sequence, Tuple

from rafiki_tpu.obs.twin.train.calibration import TrainCalibration
from rafiki_tpu.obs.twin.train.engine import TrainTwinConfig, simulate
from rafiki_tpu.obs.twin.whatif import parse_grid  # noqa: F401  (CLI reuse)

#: Default pack widths best_k scans.
DEFAULT_KS = (1, 2, 4, 8)

#: Default (chips, k) splits split_search ranks.
DEFAULT_SPLITS = ((1, 8), (2, 4), (4, 2), (8, 1), (2, 2), (4, 4))

#: Above this predicted HBM fraction a row is flagged as not fitting.
HBM_CEILING = 0.9


def _headline(res: Dict[str, Any]) -> Dict[str, Any]:
    return {f: res.get(f) for f in
            ("trials_per_hour", "makespan_s", "completed", "utilization",
             "compile_s", "step_s", "hbm_frac", "status")}


def best_k(cal: TrainCalibration, chips: int,
           ks: Sequence[int] = DEFAULT_KS, n_trials: Optional[int] = None,
           seed: int = 0) -> Dict[str, Any]:
    """Per packing key: simulate the same trial count at each pack
    width and rank by trials/hour. Ties break toward the SMALLER k —
    when the model cannot tell the widths apart, the narrower pack is
    the safer claim (less HBM, finer eviction granularity)."""
    out: Dict[str, Any] = {}
    for pk in cal.packing_keys():
        epochs = cal.epochs_for(pk)
        rows = []
        for k in ks:
            n = int(n_trials or chips * k)
            trials = [{"id": f"t{i:03d}", "packing_key": pk,
                       "epochs": epochs} for i in range(n)]
            cfg = TrainTwinConfig(chips=chips, k=int(k), n_trials=n)
            res = simulate(cal, cfg, trials=trials, seed=seed)
            hbm = cal.hbm_frac(k=int(k))
            rows.append(dict(_headline(res), k=int(k), n_trials=n,
                             hbm_frac=hbm,
                             fits=(hbm is None or hbm <= HBM_CEILING)))
        fitting = [r for r in rows if r["fits"]] or rows
        best = max(fitting,
                   key=lambda r: (r["trials_per_hour"] or 0.0, -r["k"]))
        out[pk] = {"best_k": best["k"],
                   "trials_per_hour": best["trials_per_hour"],
                   "rows": rows}
    return out


def split_search(cal: TrainCalibration, n_trials: int,
                 splits: Sequence[Tuple[int, int]] = DEFAULT_SPLITS,
                 seed: int = 0) -> Dict[str, Any]:
    """Rank (chips, k) splits for one trial budget: the many-small-
    chips vs big-trial-groups question. Each split drafts the same
    synthesized trial mix (seeded), so rows differ only in placement."""
    rows = []
    for chips, k in splits:
        cfg = TrainTwinConfig(chips=int(chips), k=int(k),
                              n_trials=int(n_trials))
        res = simulate(cal, cfg, seed=seed)
        hbm = cal.hbm_frac(k=int(k))
        rows.append(dict(_headline(res), chips=int(chips), k=int(k),
                         slots=cfg.slots(), hbm_frac=hbm,
                         fits=(hbm is None or hbm <= HBM_CEILING)))
    fitting = [r for r in rows if r["fits"]] or rows
    best = max(fitting, key=lambda r: (r["trials_per_hour"] or 0.0,
                                       -r["chips"] * r["k"]))
    return {"n_trials": int(n_trials), "rows": rows,
            "best": {"chips": best["chips"], "k": best["k"],
                     "trials_per_hour": best["trials_per_hour"],
                     "makespan_s": best["makespan_s"]}}


def member_forecast(cal: TrainCalibration, key_hash_prefix: str,
                    k: int = 1, epochs: int = 3,
                    steps_per_epoch: int = 100,
                    mfu: float = 0.3) -> Dict[str, Any]:
    """Roofline forecast for a proposed zoo member never trained here:
    predicted step/epoch walls from its ``perf/cost`` row, single-chip
    trials/hour at pack width ``k``, and the HBM-headroom verdict."""
    step_s = cal.roofline_step_s(key_hash_prefix, k=k, mfu=mfu)
    epoch_s = step_s * max(1, int(steps_per_epoch))
    trial_s = epoch_s * max(1, int(epochs))
    hbm = cal.hbm_frac(k=k, key_hash_prefix=key_hash_prefix)
    return {
        "key_hash_prefix": key_hash_prefix,
        "k": int(k), "epochs": int(epochs),
        "steps_per_epoch": int(steps_per_epoch), "mfu": mfu,
        "step_s": round(step_s, 9),
        "epoch_s": round(epoch_s, 9),
        "trials_per_hour": (round(int(k) * 3600.0 / trial_s, 4)
                            if trial_s > 0 else None),
        "hbm_frac": hbm,
        "hbm_headroom_frac": (None if hbm is None
                              else round(max(0.0, 1.0 - hbm), 4)),
        "fits": hbm is None or hbm <= HBM_CEILING,
    }


#: Default group widths group_width_forecast scans.
DEFAULT_WIDTHS = (1, 2, 4, 8)


def group_width_forecast(cal: TrainCalibration, packing_key: str,
                         widths: Sequence[int] = DEFAULT_WIDTHS,
                         hbm_bytes: Optional[int] = None,
                         epochs: Optional[int] = None) -> Dict[str, Any]:
    """What happens if ONE trial of ``packing_key`` runs as a width-w
    sharded group, per candidate width: the measured group epoch wall
    where the calibration holds a ``@groupw`` bucket for that width
    (group walls are kept out of the single-chip pools, so this is the
    only place they surface), the per-chip HBM share, and the smallest
    width that fits under the ceiling — the same solve shard/plan.py
    performs when the trial is placed for real.

    ``hbm_bytes`` is the trial's whole-state residency estimate
    (``ShardPlan.hbm_bytes``); absent that the calibration's captured
    single-chip fraction seeds the share math, and absent THAT the
    fit column reads unknown-but-permissive (None → fits)."""
    from rafiki_tpu.obs.twin.calibration import HBM_BYTES_PER_CHIP
    from rafiki_tpu.obs.twin.train.calibration import GROUP_KEY_MARK

    n_epochs = int(epochs or cal.epochs_for(packing_key))
    if hbm_bytes:
        base_frac: Optional[float] = float(hbm_bytes) / HBM_BYTES_PER_CHIP
    else:
        base_frac = cal.hbm_frac(k=1)
    rows = []
    for w in widths:
        w = int(w)
        key = packing_key if w <= 1 else (
            f"{packing_key}{GROUP_KEY_MARK}{w}")
        by_k = cal.steps.get(key) or {}
        xs = sorted(x for samples in by_k.values() for x in samples)
        epoch_s = xs[len(xs) // 2] if xs else None  # median warm wall
        frac = None if base_frac is None else base_frac / w
        trial_s = epoch_s * n_epochs if epoch_s else None
        rows.append({
            "width": w,
            "measured": bool(xs),
            "epoch_s": round(epoch_s, 9) if epoch_s else None,
            "trials_per_hour": (round(3600.0 / trial_s, 4)
                                if trial_s else None),
            "hbm_frac": None if frac is None else round(frac, 6),
            "fits": frac is None or frac <= HBM_CEILING,
        })
    solved = min((r["width"] for r in rows if r["fits"]), default=None)
    return {"packing_key": packing_key, "epochs": n_epochs,
            "rows": rows, "solved_width": solved}


def sweep(cal: TrainCalibration, base: TrainTwinConfig,
          grid: Dict[str, List[Any]], seed: int = 0,
          chaos_spec: Optional[str] = None) -> List[Dict[str, Any]]:
    """One simulation per grid combination. Grid knobs are
    TrainTwinConfig field names (``chips``, ``k``/``pack``,
    ``n_trials``); rows carry the knobs plus the headline."""
    knobs = sorted(grid)
    rows = []
    for combo in itertools.product(*(grid[kn] for kn in knobs)):
        overrides = {("k" if kn == "pack" else kn): v
                     for kn, v in zip(knobs, combo)}
        cfg = TrainTwinConfig(**{**base.__dict__, **overrides})
        cfg.chips, cfg.k = max(1, int(cfg.chips)), max(1, int(cfg.k))
        res = simulate(cal, cfg, seed=seed, chaos_spec=chaos_spec)
        row = dict(zip(knobs, combo))
        row.update(_headline(res))
        row["event_log_sha1"] = res["event_log_sha1"]
        rows.append(row)
    return rows
