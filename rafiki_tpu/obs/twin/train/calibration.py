"""Train-twin calibration bundles: everything the sweep simulator runs
on, in one versioned JSON artifact (docs/twin.md).

A bundle is extracted from a journal directory — the durable side
channel every mesh sweep leaves under ``RAFIKI_LOG_DIR`` — and carries
four ingredient classes:

* **epoch samples** — per-(packing_key, k) warm/cold epoch walls from
  ``perf/step`` records (``packing_key`` and ``k`` are stamped there by
  ``profiler.note_epoch``). Cold epochs pay XLA compilation; warm
  epochs are the steady-state step cost. The twin draws warm epochs
  from the sampled distribution and assigns cold epochs by descending
  order statistic (the first pack of a (packing_key, k) pays the true
  compile; later packs hit the process-wide program cache).
* **pack composition** — ``mesh/pack_formed`` records (chip id,
  packing_key, k, fill ratio, epochs, member trial ids), the literal
  placement the scheduler produced, so ``validate`` replays the real
  sweep rather than re-guessing it.
* **sweep shape** — the ``mesh/sweep_started`` record (chips,
  trials_per_chip, n_trials), the simulator's default topology.
* **cost rows** — ``perf/cost`` XLA cost-model captures keyed by key
  hash: the roofline source for zoo members that were never measured,
  and the HBM-headroom answer for pack-width what-ifs.

``epoch_overhead_s`` is a fitted residual: the captured wall clock
minus the per-chip sum of epoch compute, spread over epoch boundaries.
It folds per-epoch eval/feedback/wiring time — which ``perf/step``
deliberately excludes — into the twin's epoch model without a second
record kind.

Extraction fails LOUDLY, listing every missing record kind, instead of
silently defaulting: a twin calibrated on air would predict air.
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Any, Dict, List, Optional, Tuple

from rafiki_tpu.obs import journal as journal_mod
from rafiki_tpu.obs.twin.calibration import (HBM_BW_BYTES_S,
                                             HBM_BYTES_PER_CHIP,
                                             CalibrationError, _cap)

TRAIN_CALIBRATION_VERSION = 1

#: Record kinds a train bundle cannot be built without (kind/name keys
#: as they appear in the journals).
REQUIRED_KINDS = ("perf/step", "mesh/pack_formed")

#: Segments :meth:`TrainCalibration.scaled` may doctor — the
#: deliberate mis-calibration knob the validation smoke uses.
SCALABLE_SEGMENTS = ("step", "compile")

#: Multiplier spread for :meth:`TrainCalibration.nominal` warm epochs —
#: mild right skew, same philosophy as the serving bundle's grid.
_NOMINAL_SPREAD = (0.90, 0.94, 0.97, 1.00, 1.00, 1.03, 1.06, 1.10)

#: Bucket-key marker for group-sharded epoch samples (``perf/step``
#: records stamped with ``group_width`` > 1 by the sharded loop). A
#: width-w epoch's wall includes per-step all-gathers, so its samples
#: live under ``<packing_key>@groupw<w>`` and never mix into the
#: single-chip pools — not even via the unknown-key pooled fallback.
GROUP_KEY_MARK = "@groupw"


class TrainCalibrationError(CalibrationError):
    """A journal dir missing required TRAIN record kinds. ``missing``
    lists every absent kind so the operator fixes the capture once.
    Subclasses the serving :class:`CalibrationError` so existing
    ``except CalibrationError`` handlers (CLI, smokes) catch both."""

    def __init__(self, missing: List[str], source: str = ""):
        self.missing = list(missing)
        self.source = source
        ValueError.__init__(
            self,
            "cannot calibrate the train twin from %r: missing journal "
            "record kind(s): %s — run a mesh sweep with RAFIKI_LOG_DIR "
            "set (e.g. scripts/train_twin_smoke.py --capture DIR) so "
            "the sweep plane journals them"
            % (source or "<records>", ", ".join(self.missing)))


def _nearest_k(by_k: Dict[str, List[float]], k: int
               ) -> Optional[Tuple[int, List[float]]]:
    """The measured pack width closest to ``k`` in log space (ties to
    the smaller width — underestimating a pack is the safer error)."""
    widths = sorted(int(w) for w in by_k if by_k[w])
    if not widths:
        return None
    if k in widths:
        return k, by_k[str(k)]
    best = min(widths, key=lambda w: (abs(math.log(max(k, 1) / w)), w))
    return best, by_k[str(best)]


@dataclasses.dataclass
class TrainCalibration:
    """One loaded train bundle. ``steps``/``compiles`` map
    packing_key -> str(pack width k) -> sorted epoch-wall samples
    (seconds, warm vs cold); ``packs`` is the captured pack-formation
    log; ``sweep`` the captured topology; ``cost`` key_hash -> XLA cost
    row."""

    steps: Dict[str, Dict[str, List[float]]]
    compiles: Dict[str, Dict[str, List[float]]]
    packs: List[Dict[str, Any]]
    sweep: Dict[str, Any]
    cost: Dict[str, Dict[str, Any]] = dataclasses.field(default_factory=dict)
    epoch_overhead_s: float = 0.0
    source: str = ""
    version: int = TRAIN_CALIBRATION_VERSION
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)

    # -- construction --------------------------------------------------------

    @classmethod
    def from_records(cls, records: List[Dict[str, Any]],
                     source: str = "") -> "TrainCalibration":
        """Build from already-merged journal records (read_dir output).
        Raises :class:`TrainCalibrationError` listing every missing
        kind."""
        steps: Dict[str, Dict[str, List[float]]] = {}
        compiles: Dict[str, Dict[str, List[float]]] = {}
        packs: List[Dict[str, Any]] = []
        sweep: Dict[str, Any] = {}
        cost: Dict[str, Dict[str, Any]] = {}
        step_rows: List[Dict[str, Any]] = []
        for r in records:
            kind, name = r.get("kind"), r.get("name")
            if kind == "perf" and name == "step":
                pk = r.get("packing_key")
                dt = r.get("dt")
                if not pk or not isinstance(dt, (int, float)) or dt < 0:
                    continue
                step_rows.append(r)
                gw = int(r.get("group_width") or 0)
                if gw > 1:
                    pk = f"{pk}{GROUP_KEY_MARK}{gw}"
                w = str(int(r.get("k") or 1))
                dest = compiles if r.get("cold") else steps
                dest.setdefault(pk, {}).setdefault(w, []).append(float(dt))
            elif kind == "mesh" and name == "pack_formed":
                packs.append({f: r.get(f) for f in
                              ("chip", "packing_key", "k", "fill_ratio",
                               "epochs", "trial_ids", "knobs_hashes",
                               "job_id")})
            elif kind == "mesh" and name == "sweep_started":
                sweep = {f: r.get(f) for f in
                         ("chips", "trials_per_chip", "n_trials", "job_id")}
            elif kind == "perf" and name == "cost":
                kh = r.get("key_hash")
                if kh:
                    cost[kh] = {f: r.get(f) for f in
                                ("key", "program_kind", "k", "flops",
                                 "bytes_accessed", "peak_hbm_bytes")}
        missing = []
        if not step_rows:
            missing.append("perf/step")
        if not packs:
            missing.append("mesh/pack_formed")
        if missing:
            raise TrainCalibrationError(missing, source)
        overhead = _fit_epoch_overhead(step_rows,
                                       int(sweep.get("chips") or 1))
        return cls(
            steps={pk: {w: _cap(xs) for w, xs in by_k.items()}
                   for pk, by_k in steps.items()},
            compiles={pk: {w: _cap(xs) for w, xs in by_k.items()}
                      for pk, by_k in compiles.items()},
            packs=packs, sweep=sweep, cost=cost,
            epoch_overhead_s=overhead, source=source,
            meta={"step_records": len(step_rows),
                  "group_step_records": sum(
                      1 for r in step_rows
                      if int(r.get("group_width") or 0) > 1),
                  "pack_records": len(packs),
                  "cost_rows": len(cost)})

    @classmethod
    def from_journal_dir(cls, log_dir) -> "TrainCalibration":
        records = journal_mod.read_dir(log_dir)
        if not records:
            raise TrainCalibrationError(list(REQUIRED_KINDS), str(log_dir))
        return cls.from_records(records, source=str(log_dir))

    @classmethod
    def nominal(cls, step_s: float = 0.5, compile_s: float = 2.0,
                epochs: int = 3, chips: int = 2, k: int = 2
                ) -> "TrainCalibration":
        """A synthetic bundle for pre-gaming without captured journals
        (the autoscale pre-gate default): one packing key, warm epochs
        spread around ``step_s``, a single ``compile_s`` cold sample."""
        pk = "nominal"
        return cls(
            steps={pk: {str(k): sorted(step_s * m
                                       for m in _NOMINAL_SPREAD)}},
            compiles={pk: {str(k): [compile_s]}},
            packs=[], sweep={"chips": chips, "trials_per_chip": k,
                             "n_trials": chips * k, "epochs": epochs},
            source="nominal",
            meta={"step_s": step_s, "compile_s": compile_s,
                  "epochs": epochs})

    # -- (de)serialization ---------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        rounded = lambda d: {pk: {w: [round(x, 9) for x in xs]
                                  for w, xs in by_k.items()}
                             for pk, by_k in d.items()}
        return {"train_calibration_version": self.version,
                "source": self.source, "sweep": self.sweep,
                "steps": rounded(self.steps),
                "compiles": rounded(self.compiles),
                "packs": self.packs, "cost": self.cost,
                "epoch_overhead_s": round(self.epoch_overhead_s, 9),
                "meta": self.meta}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TrainCalibration":
        v = d.get("train_calibration_version")
        if v != TRAIN_CALIBRATION_VERSION:
            raise ValueError(
                f"unsupported train_calibration_version {v!r} "
                f"(this build reads {TRAIN_CALIBRATION_VERSION})")
        load = lambda key: {pk: {w: sorted(float(x) for x in xs)
                                 for w, xs in (by_k or {}).items()}
                            for pk, by_k in (d.get(key) or {}).items()}
        return cls(steps=load("steps"), compiles=load("compiles"),
                   packs=list(d.get("packs") or []),
                   sweep=dict(d.get("sweep") or {}),
                   cost=dict(d.get("cost") or {}),
                   epoch_overhead_s=float(d.get("epoch_overhead_s") or 0.0),
                   source=d.get("source") or "", version=v,
                   meta=dict(d.get("meta") or {}))

    def save(self, path) -> None:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
            f.write("\n")

    @classmethod
    def load(cls, path) -> "TrainCalibration":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    # -- derived views -------------------------------------------------------

    def packing_keys(self) -> List[str]:
        return sorted(set(self.steps) | set(self.compiles)
                      | {p.get("packing_key") for p in self.packs
                         if p.get("packing_key")})

    def epochs_for(self, packing_key: str) -> int:
        """Member epoch count for one packing key — from the captured
        pack_formed rows, falling back to the sweep/nominal default."""
        for p in self.packs:
            if p.get("packing_key") == packing_key and p.get("epochs"):
                return int(p["epochs"])
        return int(self.sweep.get("epochs") or 1)

    def step_samples(self, packing_key: str, k: int
                     ) -> Tuple[List[float], float]:
        """(samples, scale) for one warm epoch of a width-``k`` pack of
        ``packing_key``. Exact (packing_key, k) samples scale by 1.0;
        a nearest-width fallback scales linearly in width (a packed
        step does k× the member FLOPs); an unknown packing key pools
        every measured key's samples."""
        by_k = self.steps.get(packing_key) or self._pooled(self.steps)
        got = _nearest_k(by_k, k)
        if got is None:
            raise TrainCalibrationError(["perf/step"], self.source)
        k0, xs = got
        return xs, (float(k) / float(k0) if k0 else 1.0)

    def compile_samples(self, packing_key: str, k: int) -> List[float]:
        """Cold-epoch (compile-paying) samples for a width-``k`` pack,
        DESCENDING — the engine assigns them in pack order so the first
        pack of a (packing_key, k) pays the slowest observed cold epoch
        (the true compile) and later packs the faster ones (program
        cache hits). Width fallback is unscaled: XLA compile time is
        dominated by the trace, not the vmap width."""
        by_k = self.compiles.get(packing_key) or self._pooled(self.compiles)
        got = _nearest_k(by_k, k)
        if got is None:
            # No cold epoch captured anywhere: compile cost reads as a
            # warm epoch (resumable caches make this the common warm-
            # process case, not an error).
            xs, scale = self.step_samples(packing_key, k)
            return sorted((x * scale for x in xs), reverse=True)[:1]
        _k0, xs = got
        return sorted(xs, reverse=True)

    @staticmethod
    def _pooled(d: Dict[str, Dict[str, List[float]]]
                ) -> Dict[str, List[float]]:
        pooled: Dict[str, List[float]] = {}
        for pk, by_k in d.items():
            if GROUP_KEY_MARK in pk:
                continue  # group-sharded walls never model a chip
            for w, xs in by_k.items():
                pooled.setdefault(w, []).extend(xs)
        return {w: sorted(xs) for w, xs in pooled.items()}

    def scaled(self, scales: Dict[str, float]) -> "TrainCalibration":
        """A copy with named segments multiplied — the deliberate
        mis-calibration knob the validation smoke uses to prove the
        gate fails when the model is wrong."""
        unknown = set(scales) - set(SCALABLE_SEGMENTS)
        if unknown:
            raise ValueError(
                f"unknown segment(s) to scale: {sorted(unknown)}; "
                f"one of {SCALABLE_SEGMENTS}")
        mul = lambda d, f: {pk: {w: [x * f for x in xs]
                                 for w, xs in by_k.items()}
                            for pk, by_k in d.items()}
        return dataclasses.replace(
            self,
            steps=mul(self.steps, scales.get("step", 1.0)),
            compiles=mul(self.compiles, scales.get("compile", 1.0)),
            meta=dict(self.meta, scaled={s: f for s, f in scales.items()}))

    def roofline_step_s(self, key_hash_prefix: str, k: int = 1,
                        mfu: float = 0.3,
                        peak_flops: Optional[float] = None) -> float:
        """Roofline per-step prediction for an UNMEASURED program at
        pack width ``k``: max(compute, memory) seconds at an assumed
        MFU, FLOPs scaled from the captured row's width."""
        rows = [r for kh, r in sorted(self.cost.items())
                if kh.startswith(key_hash_prefix)]
        if not rows:
            raise KeyError(
                f"no perf/cost row with key_hash prefix "
                f"{key_hash_prefix!r} in this calibration "
                f"({len(self.cost)} row(s) present)")
        row = rows[0]
        if peak_flops is None:
            from rafiki_tpu.obs.perf.profiler import PEAK_FLOPS_V5E_BF16
            peak_flops = PEAK_FLOPS_V5E_BF16
        width = max(1, int(row.get("k") or 1))
        ratio = float(k) / float(width)
        compute_s = (float(row.get("flops") or 0.0) * ratio
                     / (peak_flops * mfu))
        memory_s = (float(row.get("bytes_accessed") or 0.0) * ratio
                    / HBM_BW_BYTES_S)
        return max(compute_s, memory_s)

    def hbm_frac(self, k: int = 1,
                 key_hash_prefix: str = "") -> Optional[float]:
        """Predicted peak-HBM occupancy fraction of one v5e chip for a
        width-``k`` pack: the captured per-member peak times ``k``
        (stacked members each hold params/opt state/activations).
        None without cost rows."""
        per_member = []
        for kh, r in sorted(self.cost.items()):
            if key_hash_prefix and not kh.startswith(key_hash_prefix):
                continue
            peak = float(r.get("peak_hbm_bytes") or 0.0)
            width = max(1, int(r.get("k") or 1))
            if peak > 0:
                per_member.append(peak / width)
        if not per_member:
            return None
        return max(per_member) * max(1, int(k)) / HBM_BYTES_PER_CHIP


def _fit_epoch_overhead(step_rows: List[Dict[str, Any]],
                        chips: int) -> float:
    """Residual per-epoch overhead (eval/feedback/wiring) fitted from
    the capture: wall span minus per-chip epoch compute, spread over
    the per-chip epoch count. Clamped at zero — a parallel-idle capture
    must not produce negative overhead."""
    times = [r for r in step_rows
             if isinstance(r.get("ts"), (int, float))]
    if len(times) < 2:
        return 0.0
    span = (max(float(r["ts"]) for r in times)
            - min(float(r["ts"]) - float(r["dt"]) for r in times))
    chips = max(1, chips)
    compute_per_chip = sum(float(r["dt"]) for r in times) / chips
    epochs_per_chip = max(1.0, len(times) / chips)
    return max(0.0, (span - compute_per_chip) / epochs_per_chip)
