"""Sweep-admission placement consultation (docs/twin.md).

With ``RAFIKI_TWIN_PLACEMENT`` set, ``MeshSweepScheduler.run_sweep``
calls :func:`consult` at admission — before any budget slot is claimed
— and the twin answers from the journal history: the best pack width
per observed packing key and the best (chips, k) split for this
sweep's trial budget.

The contract is ADVISORY-ONLY, by construction:

* the answer is journaled as ``twin/placement`` and returned, never
  applied — the operator (or a future policy layer) closes the loop;
* any failure (no calibration captured yet, stale bundle, engine
  error) raises out of :func:`consult`, and the scheduler's caller
  wraps it: the error lands in a ``twin/placement`` record with an
  ``error`` field and the sweep proceeds untouched. Observability
  never breaks the workload it observes.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from rafiki_tpu.obs.journal import journal as _journal

#: Fields of the what-if rows worth journaling per candidate.
_ROW_FIELDS = ("chips", "k", "trials_per_hour", "makespan_s", "hbm_frac",
               "fits")


def consult(job_id: str, chips: int, k: int,
            budget: Optional[Dict[str, Any]] = None,
            log_dir: Optional[str] = None,
            seed: int = 0) -> Dict[str, Any]:
    """Ask the twin for a pack/split recommendation at sweep admission.
    Calibrates from ``log_dir`` (default: the active journal dir /
    ``RAFIKI_LOG_DIR``), journals the answer as ``twin/placement``,
    and returns it. Raises when no calibration is available — the
    caller treats that as advice unavailable, never as a sweep error."""
    from rafiki_tpu.obs.twin.train import whatif
    from rafiki_tpu.obs.twin.train.calibration import TrainCalibration

    src = log_dir or _active_log_dir()
    if not src:
        raise RuntimeError(
            "twin placement: no journal dir to calibrate from "
            "(set RAFIKI_LOG_DIR)")
    cal = TrainCalibration.from_journal_dir(src)
    budget = budget or {}
    max_trials = budget.get("MODEL_TRIAL_COUNT")
    n_trials = int(chips) * int(k)
    if max_trials is not None:
        n_trials = min(n_trials, int(max_trials))
    ks = sorted({1, 2, 4} | {int(k)})
    per_key = whatif.best_k(cal, chips=int(chips), ks=ks, seed=seed)
    # Candidate splits: the requested shape plus its halved-fleet and
    # doubled-fleet neighbours at every scanned width.
    splits = sorted({(c, kk)
                     for c in {max(1, int(chips) // 2), int(chips),
                               int(chips) * 2}
                     for kk in ks})
    split = whatif.split_search(cal, n_trials=n_trials, splits=splits,
                                seed=seed)
    rec = {
        "best_k": {pk: v["best_k"] for pk, v in per_key.items()},
        "best_split": split["best"],
        "candidates": [{f: r.get(f) for f in _ROW_FIELDS}
                       for r in split["rows"]],
        "calibration_source": cal.source,
    }
    _journal.record("twin", "placement", job_id=job_id, advisory=True,
                    chips=int(chips), k=int(k), n_trials=n_trials,
                    recommendation=rec)
    return rec


def _active_log_dir() -> Optional[str]:
    d = _journal.log_dir
    if d is not None:
        return str(d)
    return os.environ.get("RAFIKI_LOG_DIR") or None
