"""Training/sweep digital twin (docs/twin.md).

A deterministic discrete-event simulator of the sweep chain —
propose_batch → pack formation by packing key → chip assignment →
packed epochs (compile-vs-step costs from captured ``perf/step``
samples) → eviction/backfill → feedback — calibrated from the same
journal substrate the serving twin uses, plus the ``mesh/pack_formed``
records the scheduler journals at pack formation.

Layers:

* :mod:`~rafiki_tpu.obs.twin.train.calibration` — the versioned
  bundle: per-(packing_key, k) step/compile samples, pack shapes, the
  fitted epoch overhead, ``perf/cost`` rows for roofline forecasts;
* :mod:`~rafiki_tpu.obs.twin.train.engine` — the event-heap sweep
  simulator (chips, packed epochs, eviction, chaos repack);
* :mod:`~rafiki_tpu.obs.twin.train.whatif` — best pack width per key,
  the chips-vs-pack split search, proposed-member forecasts;
* :mod:`~rafiki_tpu.obs.twin.train.validate` — predicted-vs-measured
  gating against a captured mesh sweep (TRAINTWIN_r*.json);
* :mod:`~rafiki_tpu.obs.twin.train.placement` — the advisory
  sweep-admission consultation behind ``RAFIKI_TWIN_PLACEMENT``;
* :mod:`~rafiki_tpu.obs.twin.train.pregate` — SweepChipLane autoscale
  pre-gate + chaos forecasts at the sweep sites.

Same determinism contract as the parent package: one seed reproduces
the event log bit-for-bit, and RF010 covers this subpackage too — no
ambient clocks, no OS-entropy RNG.
"""

from __future__ import annotations

import importlib

#: Public surface -> defining submodule; resolved lazily for the same
#: reason as the parent package (the obs CLI mounts parsers eagerly).
_EXPORTS = {
    "TrainCalibration": "calibration",
    "TrainCalibrationError": "calibration",
    "TrainTwinConfig": "engine", "simulate": "engine",
}
_LAZY_MODULES = ("calibration", "engine", "whatif", "validate",
                 "placement", "pregate", "cli")

__all__ = [*_EXPORTS, *_LAZY_MODULES]


def __getattr__(name: str):
    if name in _EXPORTS:
        mod = importlib.import_module(
            f"rafiki_tpu.obs.twin.train.{_EXPORTS[name]}")
        val = getattr(mod, name)
        globals()[name] = val
        return val
    if name in _LAZY_MODULES:
        mod = importlib.import_module(f"rafiki_tpu.obs.twin.train.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
