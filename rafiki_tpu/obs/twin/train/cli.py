"""CLI verbs for the train twin: ``python -m rafiki_tpu.obs twin train
run|sweep|validate`` (docs/twin.md).

Mounted by :mod:`rafiki_tpu.obs.twin.cli` under the ``twin`` verb.
Module-level imports stay stdlib-only for the same reason as the
parent: the obs CLI builds its parser tree unconditionally, and the
engine/chaos imports must not tax ``obs tail``. Everything heavy loads
inside the verb bodies.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict


def attach(tsub: argparse._SubParsersAction) -> None:
    """Mount ``train`` (with its run/sweep/validate verbs) on the twin
    subparser tree."""
    tp = tsub.add_parser(
        "train", help="training/sweep twin: simulate a mesh sweep, "
                      "plan pack/split, validate vs a captured run "
                      "(docs/twin.md)")
    trsub = tp.add_subparsers(dest="train_cmd", required=True)

    def common(sp):
        sp.add_argument("--calibration", default=None,
                        help="train calibration bundle JSON "
                             "(scripts/twin_calibrate.py --train); "
                             "default: calibrate from the journal dir, "
                             "falling back to the nominal synthetic "
                             "bundle")
        sp.add_argument("--seed", type=int, default=0)
        sp.add_argument("--chaos", default=None, metavar="SPEC",
                        help="RAFIKI_CHAOS-grammar fault spec "
                             "(scheduler.preempt / host.loss sites)")
        sp.add_argument("--scale", action="append", default=[],
                        metavar="SEG=FACTOR",
                        help="mis-calibrate a segment (repeatable), "
                             "e.g. step=2.0 or compile=0.5")

    sp = trsub.add_parser("run", help="one sweep simulation")
    common(sp)
    sp.add_argument("--chips", type=int, default=None)
    sp.add_argument("--pack", type=int, default=None,
                    help="RAFIKI_TRIAL_PACK slots per chip (k)")
    sp.add_argument("--trials", type=int, default=None)
    sp.add_argument("--chips-per-host", type=int, default=0,
                    help="group chips into hosts for the host.loss "
                         "chaos site")
    sp.add_argument("--events", action="store_true",
                    help="carry the (capped) event log in the output")

    sp = trsub.add_parser(
        "sweep", help="config grid -> predicted trials/hour per row, "
                      "plus best-k per packing key and the chips-vs-"
                      "pack split search")
    common(sp)
    sp.add_argument("--grid", action="append", default=[],
                    metavar="KNOB=V1,V2,...",
                    help="sweep axis (repeatable): chips=1,2,4 "
                         "pack=1,2,4 n_trials=8")
    sp.add_argument("--best-k", action="store_true",
                    help="also rank pack widths per packing key")
    sp.add_argument("--split", action="store_true",
                    help="also run the many-small-chips vs big-trial-"
                         "groups split search")
    sp.add_argument("--trials", type=int, default=None,
                    help="trial budget for --split (default: the "
                         "calibrated sweep's)")
    sp.add_argument("--member", default=None, metavar="KEY_HASH_PREFIX",
                    help="roofline forecast for a proposed zoo member "
                         "by perf/cost key-hash prefix")
    sp.add_argument("--member-k", type=int, default=1)
    sp.add_argument("--mfu", type=float, default=0.3)

    sp = trsub.add_parser(
        "validate", help="replay a captured mesh sweep; gate predicted"
                         "-vs-measured trials/hour and wall clock")
    common(sp)
    sp.add_argument("--tolerance", type=float, default=None,
                    help="relative-error gate (default 0.25)")
    sp.add_argument("--out", default=None,
                    help="write the TRAINTWIN artifact JSON here (the "
                         "bench_report --train-twin ledger format)")


def _load_calibration(args, log_dir):
    from rafiki_tpu.obs.twin.cli import _parse_scales
    from rafiki_tpu.obs.twin.train.calibration import (TrainCalibration,
                                                       TrainCalibrationError)
    if args.calibration:
        cal = TrainCalibration.load(args.calibration)
    else:
        try:
            cal = TrainCalibration.from_journal_dir(log_dir)
        except TrainCalibrationError as e:
            print(f"note: {e}; using the nominal synthetic bundle",
                  file=sys.stderr)
            cal = TrainCalibration.nominal()
    scales = _parse_scales(args.scale)
    return cal.scaled(scales) if scales else cal


def dispatch(args, log_dir: str, as_json: bool) -> int:
    if args.train_cmd == "run":
        return cmd_run(args, log_dir, as_json)
    if args.train_cmd == "sweep":
        return cmd_sweep(args, log_dir, as_json)
    return cmd_validate(args, log_dir, as_json)


def cmd_run(args, log_dir: str, as_json: bool) -> int:
    from rafiki_tpu.obs.twin.train.engine import TrainTwinConfig, simulate
    cal = _load_calibration(args, log_dir)
    overrides: Dict[str, Any] = {"chips_per_host": args.chips_per_host}
    if args.chips is not None:
        overrides["chips"] = args.chips
    if args.pack is not None:
        overrides["k"] = args.pack
    if args.trials is not None:
        overrides["n_trials"] = args.trials
    cfg = TrainTwinConfig.from_calibration(cal, **overrides)
    res = simulate(cal, cfg, seed=args.seed, chaos_spec=args.chaos,
                   record_events=args.events)
    if as_json:
        print(json.dumps(res, default=str))
    else:
        print(f"{res['trials']} trial(s) on {res['chips']} chip(s) x "
              f"k={res['k']}: status={res['status']} "
              f"completed={res['completed']}")
        print(f"  makespan={res['makespan_s']}s "
              f"trials/hour={res['trials_per_hour']} "
              f"utilization={res['utilization']} "
              f"compile={res['compile_s']}s step={res['step_s']}s")
        print(f"  chaos: fired={res['chaos_fired']} "
              f"chips_lost={res['chips_lost']} repacks={res['repacks']}; "
              f"hbm_frac={res['hbm_frac']}")
        print(f"  event log: {res['event_log_len']} events, "
              f"sha1 {res['event_log_sha1'][:12]}")
    return 0


def cmd_sweep(args, log_dir: str, as_json: bool) -> int:
    from rafiki_tpu.obs.twin.train import whatif
    from rafiki_tpu.obs.twin.train.engine import TrainTwinConfig
    cal = _load_calibration(args, log_dir)
    base = TrainTwinConfig.from_calibration(cal)
    grid = (whatif.parse_grid(args.grid)
            or {"chips": [1, 2, 4], "pack": [1, 2, 4]})
    rows = whatif.sweep(cal, base, grid, seed=args.seed,
                        chaos_spec=args.chaos)
    doc: Dict[str, Any] = {"grid": {k: list(v) for k, v in grid.items()},
                           "seed": args.seed, "rows": rows}
    if args.best_k:
        doc["best_k"] = whatif.best_k(cal, chips=base.chips,
                                      seed=args.seed)
    if args.split:
        n = int(args.trials or base.n_trials or base.slots())
        doc["split"] = whatif.split_search(cal, n_trials=n,
                                           seed=args.seed)
    if args.member:
        doc["member"] = whatif.member_forecast(
            cal, args.member, k=args.member_k, mfu=args.mfu)
    if as_json:
        print(json.dumps(doc, default=str))
        return 0
    knobs = sorted(grid)
    for row in rows:
        knobstr = " ".join(f"{k}={row[k]}" for k in knobs)
        print(f"{knobstr:<28} trials/hour={row['trials_per_hour']:>10} "
              f"makespan={row['makespan_s']}s "
              f"util={row['utilization']} status={row['status']}")
    if "best_k" in doc:
        for pk, v in sorted(doc["best_k"].items()):
            print(f"best k for {pk[:52]}: {v['best_k']} "
                  f"({v['trials_per_hour']} trials/hour)")
    if "split" in doc:
        b = doc["split"]["best"]
        print(f"best split for {doc['split']['n_trials']} trial(s): "
              f"{b['chips']} chip(s) x k={b['k']} "
              f"({b['trials_per_hour']} trials/hour, "
              f"{b['makespan_s']}s)")
    if "member" in doc:
        m = doc["member"]
        print(f"member {m['key_hash_prefix']}: step={m['step_s']}s "
              f"trials/hour={m['trials_per_hour']} "
              f"hbm={m['hbm_frac']} fits={m['fits']}")
    return 0


def cmd_validate(args, log_dir: str, as_json: bool) -> int:
    from rafiki_tpu.obs.twin.cli import _parse_scales
    from rafiki_tpu.obs.twin.train import validate as validate_mod
    kwargs: Dict[str, Any] = {"seed": args.seed}
    if args.tolerance is not None:
        kwargs["tolerance"] = args.tolerance
    scales = _parse_scales(args.scale)
    if scales:
        kwargs["scales"] = scales
    try:
        doc = validate_mod.validate(log_dir, **kwargs)
    except (ValueError, OSError) as e:
        print(f"twin train validate: {e}", file=sys.stderr)
        return 2
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
    if as_json:
        print(json.dumps(doc, default=str))
    else:
        m, pr = doc["measured"], doc["predicted"]
        print(f"measured : {m['trials']} trial(s) in {m['wall_s']}s "
              f"-> {m['trials_per_hour']} trials/hour")
        print(f"predicted: {pr['trials']} trial(s) in {pr['wall_s']}s "
              f"-> {pr['trials_per_hour']} trials/hour "
              f"(status {pr['status']})")
        print(f"error    : tph={doc['tph_err']} wall={doc['wall_err']} "
              f"tolerance={doc['tolerance']} -> "
              f"{'OK' if doc['ok'] else 'FAIL'}")
    return 0 if doc["ok"] else 1
