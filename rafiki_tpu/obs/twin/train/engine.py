"""The train twin's discrete-event sweep simulator (docs/twin.md).

Simulates the mesh sweep chain the way ``scheduler/mesh.py`` runs it:

* **draft** — the trial list stands in for the one batched
  ``propose_batch(chips*k)`` draft; trials carry a packing key and an
  epoch count (captured, synthesized, or hand-built).
* **pack formation** — trials bucket by packing key in first-appearance
  order, then a GLOBAL round-robin cursor distributes each bucket's
  rows across chips — byte-for-byte the assignment loop in
  ``MeshSweepScheduler._run_sub``, so the predicted placement is the
  one the scheduler would produce.
* **packed epochs** — each chip drains its pack queue FIFO. A pack of
  width w runs ``epochs`` epochs: the first is COLD (compile-paying;
  cold samples are assigned by descending order statistic per
  (packing_key, w) — the first pack pays the true compile, later packs
  the program-cache hits), the rest WARM (drawn from the calibrated
  per-(packing_key, w) distribution by the seeded service stream).
  Every epoch also pays the calibrated ``epoch_overhead_s`` residual
  (eval/feedback/wiring).
* **eviction** — an optional per-member-epoch early-stop probability
  (the ``evict`` stream): an evicted member counts COMPLETED at that
  boundary (early stop is a verdict, not a loss) and the pack narrows.
* **chaos** — the live sweep's fault grammar at the live sites:
  ``scheduler.preempt`` keyed ``chip<i>`` is consulted at every epoch
  boundary (the live supervisor also lands the abort at an epoch
  boundary); ``host.loss`` keyed ``g0h<h>`` at every supervisor tick
  when ``chips_per_host`` groups chips into hosts. Host 0 carries the
  supervisor: losing it aborts the sweep (the resume path's job, not
  the twin's).
* **re-pack/backfill** — a lost chip's unfinished trials re-assign
  round-robin to survivors and resume SERIALLY from their epoch
  boundary (the checkpoint contract), paying a fresh cold epoch.

Determinism contract: named seeded streams (``{seed}:service``,
``{seed}:evict``, ``{seed}:propose``) and zero ambient clocks (RF010
enforces this), so one seed reproduces the event log bit-for-bit;
``event_log_sha1`` fingerprints it.
"""

from __future__ import annotations

import dataclasses
import heapq
import json
import random
from hashlib import sha1
from typing import Any, Dict, List, Optional, Tuple

from rafiki_tpu.obs.twin.train.calibration import TrainCalibration

RESULT_SCHEMA_VERSION = 1

#: Event-log safety cap (record_events=True only).
EVENT_CAP = 200_000

#: Hard ceiling on simulated epochs — a runaway what-if config must
#: fail loudly, not spin.
EPOCH_CAP = 1_000_000


@dataclasses.dataclass
class TrainTwinConfig:
    """Simulated sweep topology. ``k`` is the RAFIKI_TRIAL_PACK slot
    count per chip; ``n_trials`` defaults to ``chips * k`` (the one
    batched draft fills every slot)."""

    chips: int = 2
    k: int = 2
    n_trials: Optional[int] = None
    chips_per_host: int = 0
    supervisor_tick_s: float = 1.0
    evict_prob: float = 0.0

    @classmethod
    def from_calibration(cls, cal: TrainCalibration,
                         **overrides: Any) -> "TrainTwinConfig":
        base: Dict[str, Any] = {
            "chips": int(cal.sweep.get("chips") or 2),
            "k": int(cal.sweep.get("trials_per_chip") or 2),
            "n_trials": cal.sweep.get("n_trials"),
        }
        base.update(overrides)
        cfg = cls(**base)
        cfg.chips = max(1, int(cfg.chips))
        cfg.k = max(1, int(cfg.k))
        return cfg

    def slots(self) -> int:
        return self.chips * self.k


def synthesize_trials(cal: TrainCalibration, n: int, seed: int = 0
                      ) -> List[Dict[str, Any]]:
    """A drafted trial list: packing keys drawn from the calibration's
    observed keys (weighted by captured pack membership when packs were
    captured, uniform otherwise) via the seeded ``propose`` stream."""
    keys = cal.packing_keys()
    if not keys:
        raise ValueError("calibration has no packing keys to draft from")
    weights = {k: 1 for k in keys}
    for p in cal.packs:
        pk = p.get("packing_key")
        if pk in weights:
            weights[pk] += len(p.get("trial_ids") or []) or int(
                p.get("k") or 1)
    rng = random.Random(f"{seed}:propose")
    pool = [k for k in keys for _ in range(weights[k])]
    out = []
    for i in range(int(n)):
        pk = pool[rng.randrange(len(pool))]
        out.append({"id": f"t{i:03d}", "packing_key": pk,
                    "epochs": cal.epochs_for(pk)})
    return out


def packs_from_calibration(cal: TrainCalibration) -> List[Dict[str, Any]]:
    """The CAPTURED placement, one dict per pack, for validate's
    replay: the simulator skips its own assignment and runs exactly the
    packs ``mesh/pack_formed`` recorded."""
    packs = []
    for p in cal.packs:
        members = list(p.get("trial_ids") or [])
        if not members:
            continue
        pk = p.get("packing_key") or "?"
        packs.append({"chip": int(p.get("chip") or 0),
                      "packing_key": pk,
                      "epochs": int(p.get("epochs") or cal.epochs_for(pk)),
                      "members": members})
    return packs


def _assign(trials: List[Dict[str, Any]], chips: int, k: int
            ) -> List[Dict[str, Any]]:
    """Mirror of MeshSweepScheduler._run_sub's bucket + global
    round-robin cursor assignment."""
    buckets: Dict[str, List[Dict[str, Any]]] = {}
    order: List[str] = []
    for t in trials:
        pk = t["packing_key"]
        if pk not in buckets:
            order.append(pk)
            buckets[pk] = []
        buckets[pk].append(t)
    assign: List[List[List[Dict[str, Any]]]] = [
        [[] for _ in order] for _ in range(chips)]
    cursor = 0
    for b, pk in enumerate(order):
        for row in buckets[pk]:
            assign[cursor % chips][b].append(row)
            cursor += 1
    packs = []
    for c in range(chips):
        for b, rows in enumerate(assign[c]):
            if rows:
                packs.append({"chip": c, "packing_key": order[b],
                              "epochs": max(int(t.get("epochs") or 1)
                                            for t in rows),
                              "members": [t["id"] for t in rows]})
    return packs


class _Pack:
    __slots__ = ("chip", "pk", "epochs", "members", "done_epochs")

    def __init__(self, chip: int, pk: str, epochs: int,
                 members: List[str], done_epochs: int = 0):
        self.chip = chip
        self.pk = pk
        self.epochs = max(1, int(epochs))
        self.members = list(members)
        self.done_epochs = int(done_epochs)


class _Chip:
    __slots__ = ("index", "queue", "current", "dead")

    def __init__(self, index: int):
        self.index = index
        self.queue: List[_Pack] = []
        self.current: Optional[_Pack] = None
        self.dead = False


class _Sim:
    def __init__(self, cal: TrainCalibration, cfg: TrainTwinConfig,
                 packs: List[Dict[str, Any]], seed: int,
                 chaos_spec: Optional[str], record_events: bool):
        from rafiki_tpu.chaos.plane import FaultPlane

        self.cal = cal
        self.cfg = cfg
        self.rng = random.Random(f"{seed}:service")
        self.rng_evict = random.Random(f"{seed}:evict")
        self.plane = (FaultPlane.from_spec(chaos_spec)
                      if chaos_spec else None)
        self.record_events = record_events
        self.now = 0.0
        self.horizon = 0.0
        self._heap: List[Tuple[float, int, str, Any]] = []
        self._seq = 0
        self._hash = sha1()
        self.events: List[Tuple[float, str, str]] = []
        self.n_events = 0
        self.n_epochs = 0
        chip_ids = sorted({p["chip"] for p in packs} | set(
            range(cfg.chips)))
        self.chips = {c: _Chip(c) for c in chip_ids}
        for p in packs:
            self.chips[p["chip"]].queue.append(
                _Pack(p["chip"], p["packing_key"], p["epochs"],
                      p["members"]))
        self.n_trials = sum(len(p["members"]) for p in packs)
        # Program cache: cold-sample order statistic per (pk, width).
        self._cold_i: Dict[Tuple[str, int], int] = {}
        self.completed = 0
        self.evicted = 0
        self.repacks = 0
        self.chips_lost: List[int] = []
        self.hosts_lost: List[int] = []
        self.chaos_fired = 0
        self.compile_s = 0.0
        self.step_s = 0.0
        self.status = "ok"
        self._rr = 0  # round-robin cursor for re-packed resumes

    # -- plumbing ------------------------------------------------------------

    def _push(self, t: float, kind: str, payload: Any) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (t, self._seq, kind, payload))

    def _log(self, kind: str, detail: str) -> None:
        self.horizon = max(self.horizon, self.now)
        ev = (round(self.now, 7), kind, detail)
        self._hash.update(repr(ev).encode())
        self.n_events += 1
        if self.record_events and len(self.events) < EVENT_CAP:
            self.events.append(ev)

    def _decide(self, site: str, key: str):
        if self.plane is None:
            return None
        d = self.plane.decide(site, key)
        if d is not None:
            self.chaos_fired += 1
        return d

    def _warm_s(self, pk: str, width: int) -> float:
        xs, scale = self.cal.step_samples(pk, width)
        return xs[self.rng.randrange(len(xs))] * scale

    def _cold_s(self, pk: str, width: int) -> float:
        xs = self.cal.compile_samples(pk, width)
        i = self._cold_i.get((pk, width), 0)
        self._cold_i[(pk, width)] = i + 1
        return xs[min(i, len(xs) - 1)]

    # -- the chain -----------------------------------------------------------

    def _chip_next(self, c: int) -> None:
        chip = self.chips[c]
        if chip.dead or chip.current is not None:
            return
        if not chip.queue:
            self._log("chip_idle", f"chip{c}")
            return
        pack = chip.queue.pop(0)
        chip.current = pack
        width = len(pack.members)
        cold = pack.done_epochs == 0
        dt = ((self._cold_s(pack.pk, width) if cold
               else self._warm_s(pack.pk, width))
              + self.cal.epoch_overhead_s)
        self._log("pack_start", f"chip{c} w={width} "
                                f"pk={pack.pk[:40]} epochs={pack.epochs}")
        self._book(cold, dt)
        self._push(self.now + dt, "epoch_end", (c, cold))

    def _book(self, cold: bool, dt: float) -> None:
        self.n_epochs += 1
        if self.n_epochs > EPOCH_CAP:
            raise RuntimeError(
                f"train twin exceeded {EPOCH_CAP} simulated epochs; "
                f"check the what-if config")
        if cold:
            self.compile_s += dt
        else:
            self.step_s += dt

    def _epoch_end(self, c: int, was_cold: bool) -> None:
        chip = self.chips[c]
        pack = chip.current
        if chip.dead or pack is None:
            return
        pack.done_epochs += 1
        self._log("epoch_end", f"chip{c} e={pack.done_epochs}"
                               f"/{pack.epochs} w={len(pack.members)}")
        # Chip preemption probe — the supervisor's site, consulted at
        # the epoch boundary where the live abort would also land.
        d = self._decide("scheduler.preempt", f"chip{c}")
        if d is not None and d.mode in ("kill", "term", "preempt"):
            self._lose_chip(c)
            return
        # Eviction: a member early-stopping at this boundary counts
        # completed (an early verdict) and the pack narrows.
        if self.cfg.evict_prob > 0 and pack.members:
            kept = []
            for m in pack.members:
                if (pack.done_epochs < pack.epochs
                        and self.rng_evict.random() < self.cfg.evict_prob):
                    self.evicted += 1
                    self.completed += 1
                    self._log("evict", f"chip{c} {m}")
                else:
                    kept.append(m)
            pack.members = kept
        if pack.done_epochs >= pack.epochs or not pack.members:
            self.completed += len(pack.members)
            self._log("pack_done", f"chip{c} w={len(pack.members)}")
            chip.current = None
            self._chip_next(c)
            return
        width = len(pack.members)
        dt = self._warm_s(pack.pk, width) + self.cal.epoch_overhead_s
        self._book(False, dt)
        self._push(self.now + dt, "epoch_end", (c, False))

    def _lose_chip(self, c: int) -> None:
        chip = self.chips[c]
        if chip.dead:
            return
        chip.dead = True
        self.chips_lost.append(c)
        self._log("chip_lost", f"chip{c}")
        # Orphans: the in-flight pack's members (resuming from their
        # epoch-boundary checkpoints) plus every queued pack's members.
        orphans: List[Tuple[str, str, int]] = []
        if chip.current is not None:
            p = chip.current
            orphans += [(m, p.pk, p.epochs - p.done_epochs)
                        for m in p.members]
            chip.current = None
        for p in chip.queue:
            orphans += [(m, p.pk, p.epochs) for m in p.members]
        chip.queue = []
        survivors = [ch for ch in self.chips.values() if not ch.dead]
        if not survivors:
            self.status = "all_chips_lost"
            self._log("sweep_aborted", f"{len(orphans)} trial(s) stranded")
            return
        # Serial resume on survivors: width-1 packs, round-robin — the
        # supervisor's re-pack path.
        for (m, pk, remaining) in orphans:
            target = survivors[self._rr % len(survivors)]
            self._rr += 1
            target.queue.append(_Pack(target.index, pk,
                                      max(1, remaining), [m]))
            self.repacks += 1
            self._log("repack", f"{m} -> chip{target.index}")
        for ch in survivors:
            self._chip_next(ch.index)

    def _tick(self) -> None:
        """Supervisor cadence: host.loss probes over the simulated host
        topology. Host 0 carries the supervisor — losing it aborts the
        sweep (crash-recovery's job, not the twin's)."""
        per_host = self.cfg.chips_per_host
        if per_host > 0 and self.plane is not None:
            hosts = sorted({c // per_host for c, ch in self.chips.items()
                            if not ch.dead})
            for h in hosts:
                d = self._decide("host.loss", f"g0h{h}")
                if d is None or d.mode not in ("kill", "term", "preempt"):
                    continue
                self.hosts_lost.append(h)
                self._log("host_lost", f"h{h}")
                if h == 0:
                    self.status = "supervisor_lost"
                    return
                for c in [c for c, ch in self.chips.items()
                          if not ch.dead and c // per_host == h]:
                    self._lose_chip(c)
        if self._active():
            self._push(self.now + self.cfg.supervisor_tick_s, "tick", None)

    def _active(self) -> bool:
        return any(not ch.dead and (ch.current or ch.queue)
                   for ch in self.chips.values())

    def run(self) -> None:
        self._log("sweep_start", f"chips={len(self.chips)} "
                                 f"trials={self.n_trials}")
        for c in sorted(self.chips):
            self._chip_next(c)
        if self.plane is not None and self.cfg.chips_per_host > 0:
            self._push(self.cfg.supervisor_tick_s, "tick", None)
        while self._heap:
            t, _seq, kind, payload = heapq.heappop(self._heap)
            self.now = t
            if self.status != "ok":
                break
            if kind == "epoch_end":
                self._epoch_end(*payload)
            elif kind == "tick":
                self._tick()
            if not self._active() and not any(
                    k == "epoch_end" for _, _, k, _ in self._heap):
                break
        self._log("sweep_done", f"completed={self.completed}")


def simulate(cal: TrainCalibration, cfg: TrainTwinConfig,
             trials: Optional[List[Dict[str, Any]]] = None,
             packs: Optional[List[Dict[str, Any]]] = None,
             seed: int = 0, chaos_spec: Optional[str] = None,
             record_events: bool = False) -> Dict[str, Any]:
    """One deterministic sweep simulation. Give ``packs`` to replay a
    captured placement (validate), ``trials`` to let the engine form
    packs the scheduler's way, or neither to synthesize a draft that
    fills the config's slots."""
    if packs is None:
        if trials is None:
            n = int(cfg.n_trials or cfg.slots())
            trials = synthesize_trials(cal, min(n, cfg.slots()), seed=seed)
        packs = _assign(trials, cfg.chips, cfg.k)
    sim = _Sim(cal, cfg, packs, seed, chaos_spec, record_events)
    sim.run()
    makespan = round(sim.horizon, 7)
    tph = (round(sim.completed / makespan * 3600.0, 4)
           if makespan > 0 and sim.completed else 0.0)
    busy = sim.compile_s + sim.step_s
    util = (round(busy / (makespan * max(1, len(sim.chips))), 4)
            if makespan > 0 else None)
    widths = sorted({len(p["members"]) for p in packs}) or [cfg.k]
    res: Dict[str, Any] = {
        "result_schema_version": RESULT_SCHEMA_VERSION,
        "status": sim.status,
        "trials": sim.n_trials,
        "completed": sim.completed,
        "evicted": sim.evicted,
        "chips": cfg.chips,
        "k": cfg.k,
        "packs": len(packs),
        "makespan_s": makespan,
        "trials_per_hour": tph,
        "compile_s": round(sim.compile_s, 7),
        "step_s": round(sim.step_s, 7),
        "utilization": util,
        "repacks": sim.repacks,
        "chips_lost": sim.chips_lost,
        "hosts_lost": sim.hosts_lost,
        "chaos_fired": sim.chaos_fired,
        "hbm_frac": cal.hbm_frac(k=max(widths)),
        "seed": seed,
        "chaos_spec": chaos_spec,
        "event_log_len": sim.n_events,
        "event_log_sha1": sim._hash.hexdigest(),
        "config": dataclasses.asdict(cfg),
    }
    if record_events:
        res["events"] = sim.events
    return res


def result_fingerprint(result: Dict[str, Any]) -> str:
    """Stable fingerprint of a simulation result (replay identity)."""
    return sha1(json.dumps(result, sort_keys=True).encode()).hexdigest()
