"""Sweep-lane pre-gate forecasts: the train twin's answer to "what
would this autoscale decision (or this fault) buy?" before the
controller actuates or the chaos runner injects (docs/twin.md,
docs/autoscale.md).

Two mirrors of the serving twin's pre-gates:

* :func:`forecast` — chip-count what-if: the same drafted sweep
  simulated at the current and the target chip count; deltas in
  trials/hour and makespan ride back to the caller. A scale-UP the
  twin predicts buys nothing (no trials/hour gain) is VETOED — the
  one non-advisory bit, honored by ``AutoscaleController``'s pre-gate
  contract exactly like the serving ``twin_forecast``.
* :func:`chaos_forecast` — fault what-if: baseline vs faulted
  simulation under the same ``RAFIKI_CHAOS`` grammar the live sweep
  parses, at the sweep sites (``scheduler.preempt``, ``host.loss``).

Both degrade to ``None``/no-veto on any forecasting failure: a broken
model must never block a controller that was working without it.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from rafiki_tpu.obs.twin.train.calibration import TrainCalibration
from rafiki_tpu.obs.twin.train.engine import (TrainTwinConfig, simulate,
                                              synthesize_trials)

TRAIN_FORECAST_SCHEMA_VERSION = 1

#: Fault sites the train twin models; a spec touching none of these
#: gets no chaos forecast.
SWEEP_SITES = ("scheduler.preempt", "host.loss")

#: Minimum predicted trials/hour gain for a scale-up to be worth its
#: chips (relative to baseline).
MIN_SCALE_UP_GAIN = 0.02


def spec_touches_sweep(spec: str) -> bool:
    """Does a raw RAFIKI_CHAOS spec name any sweep-lane site?"""
    return any(site in spec for site in SWEEP_SITES)


def _headline(res: Dict[str, Any]) -> Dict[str, Any]:
    return {f: res.get(f) for f in
            ("trials_per_hour", "makespan_s", "completed", "utilization",
             "chips_lost", "repacks", "status")}


def forecast(current: int, target: int,
             calibration: Optional[TrainCalibration] = None,
             n_trials: Optional[int] = None,
             seed: int = 0) -> Dict[str, Any]:
    """Chip-count what-if for the sweep lane: the same drafted trial
    set simulated at ``current`` and ``target`` chips. Deterministic:
    one (calibration, seed) pair always forecasts the same deltas."""
    cal = calibration or TrainCalibration.nominal()
    cur = TrainTwinConfig.from_calibration(cal, chips=max(1, int(current)))
    tgt = TrainTwinConfig.from_calibration(cal, chips=max(1, int(target)))
    n = int(n_trials or cal.sweep.get("n_trials")
            or max(cur.slots(), tgt.slots()))
    trials = synthesize_trials(cal, n, seed=seed)
    base = simulate(cal, cur, trials=trials, seed=seed)
    after = simulate(cal, tgt, trials=trials, seed=seed)
    d_tph = ((after.get("trials_per_hour") or 0.0)
             - (base.get("trials_per_hour") or 0.0))
    veto = False
    veto_reason = None
    base_tph = base.get("trials_per_hour") or 0.0
    if target > current and base_tph > 0:
        if d_tph / base_tph < MIN_SCALE_UP_GAIN:
            veto = True
            veto_reason = (
                f"twin predicts {d_tph / base_tph:+.1%} trials/hour for "
                f"{current}->{target} chips (< {MIN_SCALE_UP_GAIN:.0%} "
                f"gain): the sweep is not chip-bound")
    return {
        "forecast_schema_version": TRAIN_FORECAST_SCHEMA_VERSION,
        "lane": "sweep",
        "current": int(current),
        "target": int(target),
        "n_trials": n,
        "seed": seed,
        "baseline": _headline(base),
        "target_forecast": _headline(after),
        "delta_trials_per_hour": round(d_tph, 4),
        "delta_makespan_s": round((after.get("makespan_s") or 0.0)
                                  - (base.get("makespan_s") or 0.0), 4),
        "veto": veto,
        "veto_reason": veto_reason,
    }


def chaos_forecast(spec: str,
                   calibration: Optional[TrainCalibration] = None,
                   chips: Optional[int] = None,
                   chips_per_host: int = 0,
                   seed: int = 0) -> Optional[Dict[str, Any]]:
    """Baseline-vs-faulted forecast for one RAFIKI_CHAOS spec at the
    sweep sites, or None when the spec touches none of them."""
    if not spec_touches_sweep(spec):
        return None
    cal = calibration or TrainCalibration.nominal()
    overrides: Dict[str, Any] = {"chips_per_host": int(chips_per_host)}
    if chips is not None:
        overrides["chips"] = max(1, int(chips))
    cfg = TrainTwinConfig.from_calibration(cal, **overrides)
    trials = synthesize_trials(cal, int(cfg.n_trials or cfg.slots()),
                               seed=seed)
    base = simulate(cal, cfg, trials=trials, seed=seed)
    faulted = simulate(cal, cfg, trials=trials, seed=seed,
                       chaos_spec=spec)
    return {
        "forecast_schema_version": TRAIN_FORECAST_SCHEMA_VERSION,
        "spec": spec,
        "seed": seed,
        "baseline": _headline(base),
        "faulted": _headline(faulted),
        "delta_trials_per_hour": round(
            (faulted.get("trials_per_hour") or 0.0)
            - (base.get("trials_per_hour") or 0.0), 4),
        "delta_makespan_s": round((faulted.get("makespan_s") or 0.0)
                                  - (base.get("makespan_s") or 0.0), 4),
        "chips_lost": faulted.get("chips_lost") or [],
        "hosts_lost": faulted.get("hosts_lost") or [],
        "repacks": faulted.get("repacks") or 0,
        "chaos_fired": faulted.get("chaos_fired", 0),
    }


def sweep_chip_pregate(calibration: Optional[TrainCalibration] = None,
                       log_dir: Optional[str] = None,
                       seed: int = 0
                       ) -> Callable[..., Optional[Dict[str, Any]]]:
    """A ``pregate_fn`` for ``AutoscaleController(pregate_fn=...)``
    over the SweepChipLane: forecasts every sweep-lane decision before
    actuation, mirroring the serving ``twin_forecast``. Lanes other
    than ``sweep`` get None (no opinion); so does any forecasting
    failure — the controller's exception guard records it either way."""

    def pregate_fn(lane: str, current: int, target: int,
                   sensors: Optional[Dict[str, Any]] = None
                   ) -> Optional[Dict[str, Any]]:
        if lane != "sweep" or target == current:
            return None
        cal = calibration
        if cal is None and log_dir:
            cal = TrainCalibration.from_journal_dir(log_dir)
        return forecast(current, target, calibration=cal, seed=seed)

    return pregate_fn
