"""Digital-twin capacity plane (docs/twin.md).

A deterministic discrete-event simulator of the serving chain —
gateway admission/queue/shed → bus enqueue/dequeue → k-way worker
forward → quorum gather → reply — with service times drawn from
captured hop histograms (``serving/hops``) or, for unmeasured
configurations, from ``perf/cost`` roofline predictions. Load is
replayed from ``serving/ts`` journals or synthesized
(constant/ramp/spike/diurnal), and faults are injected from the same
``RAFIKI_CHAOS`` spec grammar the live plane parses, so chaos
scenarios can be pre-gamed offline.

Layers:

* :mod:`~rafiki_tpu.obs.twin.calibration` — the versioned bundle the
  simulator runs on: hop-segment samples, gateway knobs, cost rows;
* :mod:`~rafiki_tpu.obs.twin.load` — arrival processes (synthetic
  shapes + ``serving/ts`` replay);
* :mod:`~rafiki_tpu.obs.twin.engine` — the event-heap simulator;
* :mod:`~rafiki_tpu.obs.twin.whatif` — knob sweeps, the
  ``RAFIKI_SLO``-aware smallest-fleet search;
* :mod:`~rafiki_tpu.obs.twin.validate` — predicted-vs-measured gating
  against a real ``bench_serving`` run;
* :mod:`~rafiki_tpu.obs.twin.pregate` — the chaos runner's offline
  fault forecast.

Determinism contract: one seed reproduces the event log bit-for-bit
(RF010 enforces no ambient clocks or unseeded RNG in this package),
exactly like chaos schedules. The admission/quorum/breaker constants
are IMPORTED from the live gateway/predictor modules, never copied,
so the model cannot silently drift from the code it predicts.
"""

from __future__ import annotations

import importlib

#: Public surface -> defining submodule. Resolved lazily: the obs CLI
#: imports this package just to mount the argparse verbs, and must not
#: pay for the engine's gateway/predictor/chaos imports on every
#: ``obs tail``.
_EXPORTS = {
    "Calibration": "calibration", "CalibrationError": "calibration",
    "SAMPLED_SEGMENTS": "calibration",
    "TwinConfig": "engine", "simulate": "engine",
}
_LAZY_MODULES = ("calibration", "load", "engine", "whatif", "validate",
                 "pregate", "cli", "train")

__all__ = [*_EXPORTS, *_LAZY_MODULES]


def __getattr__(name: str):
    if name in _EXPORTS:
        mod = importlib.import_module(
            f"rafiki_tpu.obs.twin.{_EXPORTS[name]}")
        val = getattr(mod, name)
        globals()[name] = val
        return val
    if name in _LAZY_MODULES:
        mod = importlib.import_module(f"rafiki_tpu.obs.twin.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
