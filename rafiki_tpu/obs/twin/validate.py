"""Twin-vs-real validation: replay a captured serving run through the
simulator and score predicted against measured latency.

The protocol keeps both sides honest by deriving EVERYTHING from the
same journal directory:

* **measured** — gateway-side end-to-end latencies from the
  ``serving/request`` records (the independent per-request stopwatch
  the gateway journals for hop-sum reconciliation);
* **replayed load** — each request's arrival reconstructed as
  ``wall_ts - e2e_s`` (when its predict() began), normalized to the
  earliest, with its actual ``queries`` microbatch size carried along;
* **calibration** — hop histograms + the journaled ``gateway/config``
  knobs from the very same run.

Prediction error is relative: ``|predicted - measured| / measured``
for p50 and p99. The gate passes only if BOTH are within tolerance.
``scales`` deliberately mis-calibrates named segments (e.g. forward
halved) — the negative polarity scripts/twin_smoke.py proves the gate
actually fails when the model is wrong.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from rafiki_tpu.obs import journal as journal_mod
from rafiki_tpu.obs.twin.calibration import Calibration, CalibrationError
from rafiki_tpu.obs.twin.engine import TwinConfig, simulate

VALIDATE_SCHEMA_VERSION = 1

#: Default relative-error gate. Generous on purpose: the twin is a
#: capacity model, not a cycle simulator — it must catch a halved or
#: doubled service time, not a 10% drift.
DEFAULT_TOLERANCE = 0.40

#: Minimum measured requests for percentile errors to mean anything.
MIN_REQUESTS = 20


def measured_from_records(records: List[Dict[str, Any]]
                          ) -> Tuple[List[Tuple[float, int]], List[float]]:
    """(arrivals, latencies) from ``serving/request`` journal records.
    Arrivals are (offset_s, queries) with the earliest request at 0."""
    rows = [r for r in records
            if r.get("kind") == "serving" and r.get("name") == "request"
            and isinstance(r.get("e2e_s"), (int, float))
            and isinstance(r.get("ts"), (int, float))]
    if not rows:
        return [], []
    starts = [(float(r["ts"]) - float(r["e2e_s"]),
               int(r.get("queries") or 1)) for r in rows]
    t0 = min(s for s, _ in starts)
    arrivals = sorted((s - t0, q) for s, q in starts)
    latencies = sorted(float(r["e2e_s"]) for r in rows)
    return arrivals, latencies


def _pct_ms(xs: List[float], p: float) -> float:
    last = len(xs) - 1
    return xs[min(last, int(last * p / 100))] * 1000.0


def validate(log_dir, seed: int = 0,
             tolerance: float = DEFAULT_TOLERANCE,
             scales: Optional[Dict[str, float]] = None) -> Dict[str, Any]:
    """Score the twin against one captured run. Returns the gate
    artifact (see docs/twin.md); ``ok`` is the verdict. Raises
    :class:`CalibrationError` if the journals can't calibrate, and
    ``ValueError`` if too few requests were measured."""
    records = journal_mod.read_dir(log_dir)
    cal = Calibration.from_journal_dir(log_dir)
    if scales:
        cal = cal.scaled(scales)
    arrivals, latencies = measured_from_records(records)
    if len(latencies) < MIN_REQUESTS:
        raise ValueError(
            f"only {len(latencies)} serving/request record(s) in "
            f"{log_dir}; need >= {MIN_REQUESTS} for a meaningful "
            f"percentile comparison (run bench_serving --smoke with "
            f"RAFIKI_LOG_DIR set)")
    cfg = TwinConfig.from_calibration(cal)
    res = simulate(cal, cfg, arrivals, seed=seed)
    measured = {"p50_ms": round(_pct_ms(latencies, 50), 3),
                "p99_ms": round(_pct_ms(latencies, 99), 3),
                "requests": len(latencies)}
    predicted = {"p50_ms": res["p50_ms"], "p99_ms": res["p99_ms"],
                 "requests": res["requests"], "ok": res["ok"],
                 "shed": res["shed"],
                 "first_saturating": res["first_saturating"]}
    p50_err = _rel_err(predicted["p50_ms"], measured["p50_ms"])
    p99_err = _rel_err(predicted["p99_ms"], measured["p99_ms"])
    ok = (p50_err is not None and p99_err is not None
          and p50_err <= tolerance and p99_err <= tolerance)
    return {
        "twin_schema_version": VALIDATE_SCHEMA_VERSION,
        "source": str(log_dir),
        "seed": seed,
        "tolerance": tolerance,
        "scales": dict(scales or {}),
        "measured": measured,
        "predicted": predicted,
        "p50_err": None if p50_err is None else round(p50_err, 4),
        "p99_err": None if p99_err is None else round(p99_err, 4),
        "ok": ok,
        "event_log_sha1": res["event_log_sha1"],
        "config": res["config"],
        # Wall stamp for the TWIN_r*.json trend ledger — metadata only,
        # never an input to the simulation itself.
        "created_ts": round(time.time(), 3),  # lint: disable=RF010 — artifact timestamp, not simulation state; determinism covers everything above
    }


def _rel_err(pred: Optional[float], meas: Optional[float]
             ) -> Optional[float]:
    if pred is None or meas is None or meas <= 0:
        return None
    return abs(pred - meas) / meas


# -- per-tenant validation (docs/multitenancy.md) --------------------------

#: Per-tenant percentile gates need fewer points than the global gate:
#: a --tenants capture splits the same run across tenants, and the
#: skewed (aggressor) side would otherwise dominate the floor.
MIN_TENANT_REQUESTS = 10


def tenant_measured_from_records(records: List[Dict[str, Any]]):
    """(arrivals, per-tenant latencies, tenant→tier) from a
    ``--tenants`` capture. Arrivals are (offset_s, queries, tenant)
    3-tuples — the tenant-aware wire shape engine.simulate accepts;
    tiers come from the ``tenant/admit`` accounting records."""
    rows = [r for r in records
            if r.get("kind") == "serving" and r.get("name") == "request"
            and isinstance(r.get("e2e_s"), (int, float))
            and isinstance(r.get("ts"), (int, float))]
    if not rows:
        return [], {}, {}
    starts = [(float(r["ts"]) - float(r["e2e_s"]),
               int(r.get("queries") or 1), r.get("tenant")) for r in rows]
    t0 = min(s for s, _, _ in starts)
    arrivals = sorted((s - t0, q, t) for s, q, t in starts)
    lats: Dict[Optional[str], List[float]] = {}
    for r in rows:
        lats.setdefault(r.get("tenant"), []).append(float(r["e2e_s"]))
    for xs in lats.values():
        xs.sort()
    tiers: Dict[str, str] = {}
    for r in records:
        if (r.get("kind") == "tenant" and r.get("name") == "admit"
                and r.get("tenant") and r.get("tier")):
            tiers[str(r["tenant"])] = str(r["tier"])
    return arrivals, lats, tiers


def validate_tenants(log_dir, seed: int = 0,
                     tolerance: float = DEFAULT_TOLERANCE,
                     scales: Optional[Dict[str, float]] = None
                     ) -> Dict[str, Any]:
    """Score the twin's weighted-admission model against a captured
    ``bench_serving --tenants`` run: replay the per-tenant arrival
    trains through the simulator with the capture's own tier weights
    and gate each tenant's predicted p99 against its measured p99.
    This is the model-fidelity check behind the new-job pre-gate
    (tenancy.arbiter.JobAdmissionGate): a gate that forecasts with an
    unvalidated model is just a random number generator with a journal.
    """
    from rafiki_tpu.tenancy.qos import DEFAULT_TIER, TIERS

    records = journal_mod.read_dir(log_dir)
    cal = Calibration.from_journal_dir(log_dir)
    if scales:
        cal = cal.scaled(scales)
    arrivals, lats, tier_names = tenant_measured_from_records(records)
    total = sum(len(xs) for xs in lats.values())
    if total < MIN_REQUESTS:
        raise ValueError(
            f"only {total} serving/request record(s) in {log_dir}; need "
            f">= {MIN_REQUESTS} (run bench_serving --smoke --tenants "
            f"with RAFIKI_LOG_DIR set)")
    tiers = TIERS()
    classes = {t: {"weight": tiers.get(tier_names.get(t, ""),
                                       tiers[DEFAULT_TIER]).weight}
               for t in lats if t is not None}
    cfg = TwinConfig.from_calibration(cal, tenants=classes)
    res = simulate(cal, cfg, arrivals, seed=seed)
    per_tenant: Dict[str, Any] = {}
    gated = 0
    ok = True
    for tenant, xs in sorted((t, x) for t, x in lats.items()
                             if t is not None):
        meas_p99 = round(_pct_ms(xs, 99), 3)
        pred = (res.get("tenants", {}).get(tenant, {}) or {})
        err = _rel_err(pred.get("p99_ms"), meas_p99)
        scored = len(xs) >= MIN_TENANT_REQUESTS
        if scored:
            gated += 1
            ok = ok and err is not None and err <= tolerance
        per_tenant[tenant] = {
            "tier": tier_names.get(tenant, DEFAULT_TIER),
            "measured_requests": len(xs),
            "measured_p99_ms": meas_p99,
            "predicted_p99_ms": pred.get("p99_ms"),
            "predicted_shed": pred.get("shed"),
            "p99_err": None if err is None else round(err, 4),
            "gated": scored,
        }
    ok = ok and gated > 0
    return {
        "twin_schema_version": VALIDATE_SCHEMA_VERSION,
        "source": str(log_dir),
        "seed": seed,
        "tolerance": tolerance,
        "scales": dict(scales or {}),
        "tenants": per_tenant,
        "gated_tenants": gated,
        "ok": ok,
        "event_log_sha1": res["event_log_sha1"],
        "config": res["config"],
        "created_ts": round(time.time(), 3),  # lint: disable=RF010 — artifact timestamp, not simulation state; determinism covers everything above
    }
