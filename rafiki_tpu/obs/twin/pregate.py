"""Chaos pre-gate: forecast a fault spec's serving impact offline.

Before the chaos runner injects a spec into a live scenario, it can
ask the twin what the spec WOULD do: two simulations — baseline and
faulted — over the same synthetic load and seed, differing only in
the ``RAFIKI_CHAOS`` spec. The deltas (p99, shed rate, dead workers,
breaker trips) ride in the scenario report as ``twin_forecast``, so a
surprising live result can be compared against the model's
expectation: a live blast radius far beyond the forecast is itself a
finding.

The forecast is advisory — it never blocks a scenario, and any
forecasting failure degrades to ``None`` rather than poisoning the
run (the chaos plane's own guarantee is that observability never
breaks the workload it observes).
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional

from rafiki_tpu.obs.twin import load as load_mod
from rafiki_tpu.obs.twin.calibration import Calibration
from rafiki_tpu.obs.twin.engine import TwinConfig, simulate

FORECAST_SCHEMA_VERSION = 1

#: Fault sites the twin models; a spec touching none of these gets no
#: forecast (faulting e.g. checkpoint.save tells the twin nothing).
SERVING_SITES = ("gateway.predict", "bus.add_query", "bus.put_prediction",
                 "inference.forward")

DEFAULT_QPS = 50.0
DEFAULT_DURATION_S = 8.0


def spec_touches_serving(spec: str) -> bool:
    """Does a raw RAFIKI_CHAOS spec name any serving-chain site?"""
    return any(site in spec for site in SERVING_SITES)


def _min_fleet_for(spec: str) -> int:
    """Smallest worker count under which every ``match=w<N>`` filter in
    the spec can actually select a twin worker. Twin workers are named
    ``w0..w{n-1}`` (the scenario-harness convention); a forecast fleet
    smaller than the filtered id silently simulates the fault never
    firing — a zero-delta forecast that looks like a prediction."""
    ids = [int(m) for m in re.findall(r"match=w(\d+)", spec)]
    return max(ids) + 1 if ids else 0


def forecast(spec: str, calibration: Optional[Calibration] = None,
             qps: float = DEFAULT_QPS,
             duration_s: float = DEFAULT_DURATION_S,
             seed: int = 0) -> Optional[Dict[str, Any]]:
    """Baseline-vs-faulted forecast for one spec, or None when the
    spec touches no serving site. Deterministic: the same spec, seed
    and calibration always forecast the same deltas."""
    if not spec_touches_serving(spec):
        return None
    cal = calibration or Calibration.nominal()
    cfg = TwinConfig.from_calibration(cal)
    floor = _min_fleet_for(spec)
    if cfg.workers < floor:
        cfg = TwinConfig.from_calibration(cal, workers=floor)
    arrivals = load_mod.synthesize("constant", qps=qps,
                                   duration_s=duration_s, seed=seed)
    base = simulate(cal, cfg, arrivals, seed=seed)
    faulted = simulate(cal, cfg, arrivals, seed=seed, chaos_spec=spec)
    return {
        "forecast_schema_version": FORECAST_SCHEMA_VERSION,
        "spec": spec,
        "qps": qps,
        "duration_s": duration_s,
        "seed": seed,
        "baseline": _headline(base),
        "faulted": _headline(faulted),
        "delta_p99_ms": _delta(faulted.get("p99_ms"), base.get("p99_ms")),
        "delta_shed_rate": _delta(faulted.get("shed_rate"),
                                  base.get("shed_rate")),
        "workers_dead": faulted.get("workers_dead") or [],
        "breaker_transitions": len(faulted.get("breaker_transitions")
                                   or []),
        "chaos_fired": faulted.get("chaos_fired", 0),
    }


def _headline(res: Dict[str, Any]) -> Dict[str, Any]:
    return {k: res.get(k) for k in ("qps", "p50_ms", "p99_ms",
                                    "shed_rate", "ok", "shed", "errors",
                                    "first_saturating")}


def _delta(after: Optional[float], before: Optional[float]
           ) -> Optional[float]:
    if after is None or before is None:
        return None
    return round(after - before, 4)
