"""The discrete-event serving simulator.

One :func:`simulate` call replays an arrival sequence through a model
of the full serving chain and returns headline metrics plus a
deterministic event log:

    arrive → admission (inflight budget / bounded queue / deadline
    shed) → route (breaker-filtered fan-out, policy) → per-worker FIFO
    service (sampled batch_wait + forward + reply_publish) → per-query
    quorum gather with hedge grace → request done → breaker feedback →
    slot release → next waiter admitted.

Fidelity rules:

* **Constants are imported, not copied.** Admission caps and the
  deadline-reserve rule come from the run's :class:`TwinConfig`
  (mirroring ``GatewayConfig`` field-for-field), the reserve fraction
  and EWMA weight from ``rafiki_tpu.gateway.gateway``, the quorum
  formula from ``rafiki_tpu.predictor`` — and the per-worker breakers
  are the LIVE :class:`~rafiki_tpu.gateway.breaker.CircuitBreaker`
  class running on the sim clock, so open/half-open/close transitions
  fire at exactly the thresholds production uses.
* **Queueing is emergent, service is sampled.** ``admission_wait`` and
  ``bus_queue`` come out of the simulated queues; ``route`` /
  ``batch_wait`` / ``forward`` / ``reply_publish`` / ``gather_decide``
  are drawn from the calibration's captured samples (or a cost-model
  roofline point).
* **Deterministic.** One ``random.Random(seed)`` stream for service
  sampling, seeded streams in the load generator and the chaos plane,
  no ambient clocks (RF010): same seed + same calibration → the same
  event log, bit for bit.

Chaos: a ``RAFIKI_CHAOS``-grammar spec parses into a private
:class:`~rafiki_tpu.chaos.plane.FaultPlane` consulted at the same
sites the live path hooks — ``gateway.predict`` (frontend stall /
poisoned request), ``bus.add_query`` (dropped envelope),
``inference.forward`` (slow / erroring / killed worker). Only
``decide`` is used — a simulated SIGKILL marks the model worker dead,
it does not signal anyone.
"""

from __future__ import annotations

import dataclasses
import heapq
import json
import math
import random
from hashlib import sha1
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from rafiki_tpu.chaos.plane import FaultPlane
from rafiki_tpu.gateway.breaker import CircuitBreaker, OPEN
from rafiki_tpu.gateway.gateway import (DEADLINE_RESERVE_FRAC,
                                        GatewayConfig, LATENCY_EWMA_ALPHA)
from rafiki_tpu.obs.twin.calibration import Calibration
from rafiki_tpu.predictor.predictor import default_quorum

RESULT_SCHEMA_VERSION = 1

#: Resources the saturation report ranks, in tie-break priority order.
RESOURCES = ("worker", "gateway_inflight", "queue", "breaker", "hbm")

#: Cap on the events list carried in the result; the log hash always
#: covers ALL events regardless.
EVENT_CAP = 200_000


@dataclasses.dataclass
class TwinConfig:
    """The knob set one simulation runs under — a field-for-field
    mirror of the live ``GatewayConfig`` admission/gather knobs plus
    the fleet shape. Build via :meth:`from_calibration` to simulate
    the captured run, then override knobs for what-ifs."""

    workers: int = 2
    queries_per_request: int = 1     # the microbatch knob
    max_inflight: int = 8
    max_queue: int = 32
    deadline_s: float = 2.0
    min_replies: Optional[int] = None   # None → default_quorum(fan-out)
    hedge_grace_s: float = 0.25
    policy: str = "replicate-all"
    breaker_failures: int = 3
    breaker_cooldown_s: float = 5.0
    #: Micro-batch cap per forward — InferenceWorker's batch_size
    #: (bus.pop_queries max_n). Not a gateway knob, so not captured in
    #: gateway/config; override when the fleet runs a non-default cap.
    worker_batch: int = 64
    #: Gateway dynamic microbatcher (GatewayConfig.max_batch /
    #: max_batch_wait_ms, in SECONDS here like every sim knob): >1
    #: models the post-admission batch former — requests accumulate
    #: until max_batch queries or the deadline-aware wait expires, then
    #: ONE fan-out serves the whole batch. 1 = per-request fan-out.
    max_batch: int = 1
    max_batch_wait_s: float = 0.005
    #: Per-tenant QoS classes (docs/multitenancy.md): tenant id →
    #: ``{"weight": w}``. None → tenant-blind admission (the
    #: pre-tenancy gateway), byte-identical to earlier results. With
    #: tenants set, admission mirrors TenantAdmissionController:
    #: per-tenant queue/inflight quotas at ``tenant_quota_frac`` of
    #: capacity and weighted-fair granting by inflight/weight charge.
    tenants: Optional[Dict[str, Dict[str, float]]] = None
    #: Mirror of TenantDirectory.quota_frac.
    tenant_quota_frac: float = 0.5

    @classmethod
    def from_gateway(cls, g: GatewayConfig, workers: int,
                     **overrides) -> "TwinConfig":
        base = dict(workers=workers,
                    max_inflight=g.max_inflight, max_queue=g.max_queue,
                    deadline_s=g.default_deadline_s or 2.0,
                    min_replies=g.min_replies,
                    hedge_grace_s=g.hedge_grace_s, policy=g.policy,
                    breaker_failures=g.breaker_failures,
                    breaker_cooldown_s=g.breaker_cooldown_s,
                    max_batch=g.max_batch,
                    max_batch_wait_s=g.max_batch_wait_ms / 1000.0)
        base.update(overrides)
        return cls(**base)

    @classmethod
    def from_calibration(cls, cal: Calibration, **overrides) -> "TwinConfig":
        g = cal.gateway
        base = dict(workers=cal.workers,
                    max_inflight=int(g.get("max_inflight", 8)),
                    max_queue=int(g.get("max_queue", 32)),
                    deadline_s=float(g.get("default_deadline_s") or 2.0),
                    min_replies=g.get("min_replies"),
                    hedge_grace_s=float(g.get("hedge_grace_s", 0.25)),
                    policy=g.get("policy") or "replicate-all",
                    breaker_failures=int(g.get("breaker_failures", 3)),
                    breaker_cooldown_s=float(g.get("breaker_cooldown_s",
                                                   5.0)),
                    max_batch=int(g.get("max_batch", 1)),
                    max_batch_wait_s=float(g.get("max_batch_wait_ms",
                                                 5.0)) / 1000.0)
        base.update(overrides)
        return cls(**base)


class _Worker:
    __slots__ = ("wid", "queue", "busy", "alive", "warm", "busy_s")

    def __init__(self, wid: str):
        self.wid = wid
        self.queue: List[Tuple[Any, int]] = []   # (request, query index)
        self.busy = False
        self.alive = True
        self.warm = False
        self.busy_s = 0.0


class _Request:
    __slots__ = ("rid", "arrival", "queries", "deadline", "admit_deadline",
                 "admit_t", "join_t", "fanset", "quorum", "replies",
                 "decided", "done_q", "timeouts", "outcome", "done_t",
                 "replied_by", "tenant")

    def __init__(self, rid: int, arrival: float, queries: int,
                 tenant: Optional[str] = None):
        self.rid = rid
        self.arrival = arrival
        self.queries = queries
        self.tenant = tenant
        self.admit_t: Optional[float] = None
        self.join_t: Optional[float] = None   # microbatch former entry
        self.fanset: List[str] = []
        self.quorum = 1
        self.replies: List[List[float]] = []   # per query: reply times
        self.decided: List[bool] = []
        self.done_q: List[float] = []
        self.timeouts = 0
        self.outcome: Optional[str] = None
        self.done_t: Optional[float] = None
        self.replied_by: set = set()


class _Sim:
    def __init__(self, cal: Calibration, cfg: TwinConfig,
                 arrivals: Sequence[Union[float, Tuple[float, int]]],
                 seed: int, chaos_spec: Optional[str],
                 record_events: bool):
        self.cal = cal
        self.cfg = cfg
        self.rng = random.Random(f"{seed}:service")
        self.plane = (FaultPlane.from_spec(chaos_spec)
                      if chaos_spec else None)
        self.record_events = record_events
        self.now = 0.0
        self._heap: List[Tuple[float, int, str, Any]] = []
        self._seq = 0
        self.workers = {f"w{i}": _Worker(f"w{i}")
                        for i in range(cfg.workers)}
        self.order = sorted(self.workers)
        self.breakers = {w: CircuitBreaker(cfg.breaker_failures,
                                           cfg.breaker_cooldown_s,
                                           clock=lambda: self.now)
                         for w in self.order}
        self._breaker_open_since: Dict[str, float] = {}
        self.breaker_open_s = 0.0
        self.breaker_transitions: List[Tuple[float, str, str, str]] = []
        # Admission state (mirrors gateway/admission.py semantics).
        self.inflight = 0
        self.waiting: List[_Request] = []
        self.queue_peak = 0
        self.ewma: Optional[float] = None
        # Microbatch former state (mirrors gateway/microbatch.py when
        # cfg.max_batch > 1). The gateway's blackout re-route is NOT
        # modeled — it only engages on total fan-out death, which the
        # twin surfaces directly as worker_dead + breaker feedback.
        self.batch_pending: List[_Request] = []
        self.batch_flushes: Dict[str, int] = {}
        self.batch_sizes: List[int] = []
        # Metrics.
        self.requests: List[_Request] = []
        self.shed: Dict[str, int] = {}
        self.events: List[Tuple[float, str, str]] = []
        self.n_events = 0
        self.horizon = 0.0   # last REAL activity; stale deadline events
        #                      advance `now` but must not stretch duration
        self._hash = sha1()
        self._inflight_area = 0.0
        self._inflight_mark = 0.0
        # Arrivals normalized to (t, n_queries, tenant) — plain floats
        # and 2-tuples stay tenant-less (back-compat wire shapes).
        self.arrivals: List[Tuple[float, int, Optional[str]]] = [
            (float(a), cfg.queries_per_request, None)
            if isinstance(a, (int, float))
            else (float(a[0]), int(a[1]),
                  a[2] if len(a) > 2 else None)
            for a in arrivals]
        self.arrivals.sort(key=lambda p: p[0])
        # Per-tenant admission state (mirrors tenancy/admission.py);
        # inert when cfg.tenants is None.
        self.tenant_inflight: Dict[Optional[str], int] = {}
        self.tenant_shed: Dict[Tuple[Optional[str], str], int] = {}
        if cfg.tenants:
            frac = min(1.0, max(0.05, cfg.tenant_quota_frac))
            self.quota_inflight = max(1, int(math.ceil(
                cfg.max_inflight * frac)))
            self.quota_queue = (max(1, int(math.ceil(cfg.max_queue * frac)))
                                if cfg.max_queue else 0)

    # -- plumbing ------------------------------------------------------------

    def _push(self, t: float, kind: str, payload: Any) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (t, self._seq, kind, payload))

    def _log(self, kind: str, detail: str) -> None:
        self.horizon = max(self.horizon, self.now)
        ev = (round(self.now, 7), kind, detail)
        self._hash.update(repr(ev).encode())
        self.n_events += 1
        if self.record_events and len(self.events) < EVENT_CAP:
            self.events.append(ev)

    def _sample(self, segment: str) -> float:
        xs = self.cal.dist(segment)
        if not xs:
            return 0.0
        return xs[self.rng.randrange(len(xs))]

    def _decide(self, site: str, key: str):
        return self.plane.decide(site, key) if self.plane else None

    def _track_inflight(self, delta: int) -> None:
        self._inflight_area += self.inflight * (self.now -
                                                self._inflight_mark)
        self._inflight_mark = self.now
        self.inflight += delta

    def _feed_breaker(self, w: str, ok: bool, latency: float) -> None:
        br = self.breakers[w]
        before = br.state
        if ok:
            br.record_success(latency_s=latency)
        else:
            br.record_failure()
        after = br.state
        if after != before:
            self.breaker_transitions.append((round(self.now, 7), w,
                                             before, after))
            self._log("breaker_" + after.replace("-", "_"), w)
            if after == OPEN:
                self._breaker_open_since[w] = self.now
            elif before == OPEN or w in self._breaker_open_since:
                self.breaker_open_s += (self.now -
                                        self._breaker_open_since.pop(w,
                                                                     self.now))

    # -- admission (mirrors AdmissionController.admit) -----------------------

    def _weight(self, tenant: Optional[str]) -> float:
        spec = (self.cfg.tenants or {}).get(tenant or "", {})
        return max(float(spec.get("weight", 1.0)), 1e-9)

    def _arrive(self, req: _Request) -> None:
        self._log("arrive", f"r{req.rid}")
        reserve = min(self.ewma or 0.0,
                      self.cfg.deadline_s * DEADLINE_RESERVE_FRAC)
        req.deadline = req.arrival + self.cfg.deadline_s
        req.admit_deadline = req.deadline - reserve
        if self.cfg.tenants:
            # Tenant-aware admission (mirrors TenantAdmissionController
            # shed order: tenant_quota before queue_full, so a flooder
            # is charged before it can fill the shared queue).
            t = req.tenant
            if (self.inflight < self.cfg.max_inflight and not self.waiting
                    and self.tenant_inflight.get(t, 0)
                    < self.quota_inflight):
                self._admit(req)
            elif (self.quota_queue
                    and sum(1 for r in self.waiting if r.tenant == t)
                    >= self.quota_queue):
                self._shed(req, "tenant_quota")
            elif len(self.waiting) >= self.cfg.max_queue:
                self._shed(req, "queue_full")
            elif self.now >= req.admit_deadline:
                self._shed(req, "deadline")
            else:
                self.waiting.append(req)
                self.queue_peak = max(self.queue_peak, len(self.waiting))
                self._push(req.admit_deadline, "queue_deadline", req)
            return
        if self.inflight < self.cfg.max_inflight and not self.waiting:
            self._admit(req)
        elif len(self.waiting) >= self.cfg.max_queue:
            self._shed(req, "queue_full")
        elif self.now >= req.admit_deadline:
            self._shed(req, "deadline")
        else:
            self.waiting.append(req)
            self.queue_peak = max(self.queue_peak, len(self.waiting))
            self._push(req.admit_deadline, "queue_deadline", req)

    def _next_waiter(self) -> Optional[_Request]:
        """Weighted-fair grant: the head (FIFO-within-tenant) waiter of
        the eligible tenant with the lowest inflight/weight charge,
        arrival order breaking ties — the same selection rule as
        TenantAdmissionController._chosen_tenant."""
        heads: Dict[Optional[str], _Request] = {}
        for r in self.waiting:
            if r.tenant not in heads:
                heads[r.tenant] = r
        eligible = [r for r in heads.values()
                    if self.tenant_inflight.get(r.tenant, 0)
                    < self.quota_inflight]
        if not eligible:
            return None
        return min(eligible,
                   key=lambda r: (self.tenant_inflight.get(r.tenant, 0)
                                  / self._weight(r.tenant), r.rid))

    def _pump(self) -> None:
        while self.inflight < self.cfg.max_inflight and self.waiting:
            if self.cfg.tenants:
                req = self._next_waiter()
                if req is None:
                    return   # everyone waiting is at their quota
                self.waiting.remove(req)
            else:
                req = self.waiting.pop(0)
            if self.now >= req.admit_deadline:
                self._shed(req, "deadline")
                continue
            self._admit(req)

    def _shed(self, req: _Request, reason: str) -> None:
        if req.outcome is not None:
            return
        req.outcome = "shed:" + reason
        self.shed[reason] = self.shed.get(reason, 0) + 1
        if self.cfg.tenants:
            key = (req.tenant, reason)
            self.tenant_shed[key] = self.tenant_shed.get(key, 0) + 1
        self._log("shed", f"r{req.rid} {reason}")

    def _admit(self, req: _Request) -> None:
        self._track_inflight(+1)
        if self.cfg.tenants:
            self.tenant_inflight[req.tenant] = (
                self.tenant_inflight.get(req.tenant, 0) + 1)
        req.admit_t = self.now
        self._log("admit", f"r{req.rid}")
        fault = self._decide("gateway.predict", f"r{req.rid}")
        if fault is not None and fault.mode == "error":
            # A poisoned frontend request: errors out still holding
            # its slot for zero time (the live hook raises pre-gather).
            req.outcome = "error"
            req.done_t = self.now
            self._log("done", f"r{req.rid} error")
            self._release(req)
            return
        delay = fault.delay_s if (fault is not None
                                  and fault.mode == "delay") else 0.0
        if self.cfg.max_batch > 1:
            self._push(self.now + delay, "batch_join", req)
        else:
            self._route(req, self.now + delay + self._sample("route"))

    def _release(self, req: Optional[_Request] = None) -> None:
        self._track_inflight(-1)
        if self.cfg.tenants and req is not None:
            self.tenant_inflight[req.tenant] = max(
                0, self.tenant_inflight.get(req.tenant, 0) - 1)
        self._pump()

    # -- gateway microbatch former (mirrors gateway/microbatch.py) -----------

    def _batch_join(self, req: _Request) -> None:
        if req.outcome is not None:
            return
        req.join_t = self.now
        self.batch_pending.append(req)
        self._log("batch_join", f"r{req.rid}")
        if self._batch_size() >= self.cfg.max_batch:
            self._batch_flush("size")
        else:
            self._push(self._batch_flush_at(), "batch_flush_check", None)

    def _batch_size(self) -> int:
        return sum(r.queries for r in self.batch_pending)

    def _batch_flush_at(self) -> float:
        """MicroBatcher._flush_at: oldest member's max-wait expiry,
        capped by every member's deadline minus the service reserve."""
        reserve = self.ewma or 0.0
        t = (min(r.join_t for r in self.batch_pending)
             + self.cfg.max_batch_wait_s)
        for r in self.batch_pending:
            t = min(t, r.deadline - reserve)
        return max(t, self.now)

    def _batch_flush_check(self) -> None:
        if not self.batch_pending:
            return   # stale timer: an earlier size flush took everyone
        if self._batch_size() >= self.cfg.max_batch:
            self._batch_flush("size")
        elif self.now >= self._batch_flush_at():
            self._batch_flush("deadline")

    def _batch_flush(self, reason: str) -> None:
        """FIFO members up to max_batch queries (always >= 1 member),
        then ONE fan-out for the whole batch: members share the flush
        instant and route sample, and their queries land on the workers
        at the same t_enq — the worker model's micro-batch drain then
        serves them in one forward, the live stacked worker's
        single-launch shape."""
        batch: List[_Request] = []
        nq = 0
        while self.batch_pending:
            r = self.batch_pending[0]
            if batch and nq + r.queries > self.cfg.max_batch:
                break
            batch.append(self.batch_pending.pop(0))
            nq += r.queries
        self.batch_flushes[reason] = self.batch_flushes.get(reason, 0) + 1
        self.batch_sizes.append(nq)
        self._log("batch_flush", f"n={nq} {reason}")
        t_enq = self.now + self._sample("route")
        for r in batch:
            self._route(r, t_enq)
        if self.batch_pending:
            self._push(self._batch_flush_at(), "batch_flush_check", None)

    # -- routing + worker service (mirrors Gateway._route) -------------------

    def _backlog(self, w: _Worker) -> int:
        return len(w.queue) + (1 if w.busy else 0)

    def _route(self, req: _Request, t_enq: float) -> None:
        allowed = [w for w in self.order if self.breakers[w].allow()]
        if not allowed:
            allowed = list(self.order)   # forced probe, like the gateway
        if self.cfg.policy == "least-loaded":
            allowed = [min(allowed,
                           key=lambda w: (self._backlog(self.workers[w]),
                                          w))]
            req.quorum = 1
        else:
            req.quorum = (self.cfg.min_replies
                          if self.cfg.min_replies is not None
                          else default_quorum(len(allowed)))
        req.fanset = allowed
        req.replies = [[] for _ in range(req.queries)]
        req.decided = [False] * req.queries
        req.done_q = [0.0] * req.queries
        self._push(req.deadline, "request_deadline", req)
        for qi in range(req.queries):
            for w in allowed:
                if self._fault_drops(w, req, qi):
                    continue
                self._push(t_enq, "enqueue", (req, qi, w))

    def _fault_drops(self, w: str, req: _Request, qi: int) -> bool:
        fault = self._decide("bus.add_query", w)
        if fault is not None and fault.mode == "drop":
            self._log("drop", f"r{req.rid}q{qi} {w}")
            return True
        return False

    def _enqueue(self, req: _Request, qi: int, wid: str) -> None:
        wk = self.workers[wid]
        if not wk.alive:
            return
        wk.queue.append((req, qi))
        if not wk.busy:
            self._start_next(wk)

    def _start_next(self, wk: _Worker) -> None:
        """Pop a MICRO-BATCH and run one forward for all of it —
        mirroring InferenceWorker/bus.pop_queries, which drain the
        queue (up to batch_size) after the first query arrives so the
        device sees batches, not query-at-a-time traffic. One sampled
        forward covers the whole batch, exactly as one ``fwd`` hop mark
        is shared by every chain in a live micro-batch."""
        if not wk.queue:
            wk.busy = False
            return
        batch = wk.queue[:self.cfg.worker_batch]
        wk.queue = wk.queue[len(batch):]
        fault = self._decide("inference.forward", wk.wid)
        if fault is not None and fault.mode in ("kill", "term"):
            wk.alive = False
            wk.queue = []
            wk.busy = False
            self._log("worker_dead", wk.wid)
            return
        dur = self._sample("batch_wait")
        if fault is not None and fault.mode == "error":
            pass   # chaos raises before predict; the worker catches
            #        and still publishes (error) payloads per query
        else:
            dur += self._sample("forward_cold" if not wk.warm
                                else "forward")
            if fault is not None and fault.mode == "delay":
                dur += fault.delay_s
        wk.warm = True
        wk.busy = True
        self._log("start", f"{wk.wid} n={len(batch)}")
        # Publishes happen sequentially on the worker thread after the
        # forward; the worker is busy until the last one lands.
        t = self.now + dur
        for req, qi in batch:
            t += self._sample("reply_publish")
            self._push(t, "reply", (req, qi, wk.wid))
        wk.busy_s += t - self.now
        self._push(t, "batch_done", wk)

    def _batch_done(self, wk: _Worker) -> None:
        if wk.alive:
            self._start_next(wk)

    # -- gather (mirrors Predictor quorum + hedge semantics) -----------------

    def _reply(self, req: _Request, qi: int, wid: str) -> None:
        if req.outcome is not None or req.decided[qi]:
            return   # late reply: gather already decided
        self._log("reply", f"r{req.rid}q{qi} {wid}")
        req.replies[qi].append(self.now)
        req.replied_by.add(wid)
        n = len(req.replies[qi])
        if n >= len(req.fanset):
            self._decide_query(req, qi)
        elif n == req.quorum:
            self._push(self.now + self.cfg.hedge_grace_s, "hedge",
                       (req, qi))

    def _decide_query(self, req: _Request, qi: int) -> None:
        if req.outcome is not None or req.decided[qi]:
            return
        req.decided[qi] = True
        if not req.replies[qi]:
            req.timeouts += 1
        # No sampled decide cost: the reply→decide span in live hop
        # chains is the quorum/hedge wait, which this engine simulates
        # directly (calibration.EMERGENT_SEGMENTS).
        req.done_q[qi] = self.now
        self._log("decide", f"r{req.rid}q{qi} n={len(req.replies[qi])}")
        if all(req.decided):
            self._finish(req, max(req.done_q))

    def _deadline(self, req: _Request) -> None:
        if req.outcome is not None:
            return
        for qi in range(req.queries):
            if not req.decided[qi]:
                self._decide_query(req, qi)
                if req.outcome is not None:
                    return

    def _finish(self, req: _Request, t_done: float) -> None:
        self.now = max(self.now, t_done)
        req.done_t = t_done
        req.outcome = "ok" if req.timeouts == 0 else "error"
        self._log("done", f"r{req.rid} {req.outcome}")
        latency = t_done - req.admit_t
        for w in req.fanset:
            self._feed_breaker(w, w in req.replied_by, latency)
        req.replied_by = set()
        if req.outcome == "ok":
            a = LATENCY_EWMA_ALPHA
            self.ewma = (latency if self.ewma is None
                         else (1 - a) * self.ewma + a * latency)
        self._release(req)

    # -- main loop -----------------------------------------------------------

    def run(self) -> None:
        for t, n, tenant in self.arrivals:
            req = _Request(len(self.requests), t, n, tenant=tenant)
            self.requests.append(req)
            self._push(t, "arrive", req)
        while self._heap:
            t, _, kind, payload = heapq.heappop(self._heap)
            self.now = max(self.now, t)
            if kind == "arrive":
                self._arrive(payload)
            elif kind == "enqueue":
                self._enqueue(*payload)
            elif kind == "reply":
                self._reply(*payload)
            elif kind == "batch_done":
                self._batch_done(payload)
            elif kind == "batch_join":
                self._batch_join(payload)
            elif kind == "batch_flush_check":
                self._batch_flush_check()
            elif kind == "hedge":
                req, qi = payload
                self._decide_query(req, qi)
            elif kind == "request_deadline":
                self._deadline(payload)
            elif kind == "queue_deadline":
                req = payload
                if req.outcome is None and req.admit_t is None:
                    if req in self.waiting:
                        self.waiting.remove(req)
                    self._shed(req, "deadline")
                    self._pump()


def _pct(xs: List[float], p: float) -> Optional[float]:
    if not xs:
        return None
    last = len(xs) - 1
    return xs[min(last, int(last * p / 100))]


def simulate(cal: Calibration, cfg: TwinConfig,
             arrivals: Sequence[Union[float, Tuple[float, int]]],
             seed: int = 0, chaos_spec: Optional[str] = None,
             record_events: bool = False) -> Dict[str, Any]:
    """Run one simulation; returns the headline result dict (see
    docs/twin.md for the schema). ``record_events`` additionally
    carries the full event log (capped) in ``events``."""
    sim = _Sim(cal, cfg, arrivals, seed, chaos_spec, record_events)
    sim.run()
    reqs = sim.requests
    n = len(reqs)
    ok = [r for r in reqs if r.outcome == "ok"]
    shed = sum(sim.shed.values())
    errors = sum(1 for r in reqs if r.outcome == "error")
    lat = sorted(r.done_t - r.admit_t for r in ok)
    full = sorted(r.done_t - r.arrival for r in ok)
    t0 = reqs[0].arrival if reqs else 0.0
    duration = max(sim.horizon - t0, 1e-9)
    # Close out the open-interval accumulators at the horizon.
    sim.now = sim.horizon
    sim._track_inflight(0)
    for w, since in sim._breaker_open_since.items():
        sim.breaker_open_s += max(0.0, sim.horizon - since)
    util: Dict[str, Optional[float]] = {
        "worker": round(sum(w.busy_s for w in sim.workers.values())
                        / (duration * cfg.workers), 4),
        "gateway_inflight": round(sim._inflight_area
                                  / (duration * cfg.max_inflight), 4),
        "queue": (round(sim.queue_peak / cfg.max_queue, 4)
                  if cfg.max_queue else (1.0 if sim.queue_peak else 0.0)),
        "breaker": round(sim.breaker_open_s / (duration * cfg.workers), 4),
        "hbm": cal.hbm_frac(),
    }
    ranked = sorted(((util[r], -RESOURCES.index(r), r) for r in RESOURCES
                     if util[r] is not None), reverse=True)
    first_saturating = ranked[0][2] if ranked else None
    result: Dict[str, Any] = {
        "schema_version": RESULT_SCHEMA_VERSION,
        "seed": seed,
        "requests": n,
        "ok": len(ok),
        "shed": shed,
        "errors": errors,
        "shed_reasons": dict(sorted(sim.shed.items())),
        "duration_s": round(duration, 6),
        "qps": round(n / duration, 3),
        "p50_ms": _ms(_pct(lat, 50)),
        "p99_ms": _ms(_pct(lat, 99)),
        "mean_ms": _ms(sum(lat) / len(lat) if lat else None),
        "full_p50_ms": _ms(_pct(full, 50)),
        "full_p99_ms": _ms(_pct(full, 99)),
        "shed_rate": round(shed / n, 4) if n else None,
        "utilization": util,
        "first_saturating": first_saturating,
        "breaker_transitions": [list(t) for t in sim.breaker_transitions],
        "workers_dead": sorted(w.wid for w in sim.workers.values()
                               if not w.alive),
        "chaos_fired": (len(sim.plane.schedule()) if sim.plane else 0),
        "event_log_len": sim.n_events,
        "event_log_sha1": sim._hash.hexdigest(),
        "config": dataclasses.asdict(cfg),
    }
    if cfg.tenants is not None:
        tenant_ids = sorted({r.tenant for r in reqs} | set(cfg.tenants),
                            key=lambda t: (t is None, t or ""))
        blocks: Dict[str, Any] = {}
        for tenant in tenant_ids:
            rs = [r for r in reqs if r.tenant == tenant]
            lat_t = sorted(r.done_t - r.admit_t for r in rs
                           if r.outcome == "ok")
            # Caller-observed latency (arrival→done, admission wait
            # included) — the QoS p99 budget is a promise about THIS
            # number, same rule as the gateway's tenant ledger: under
            # contention the queue wait IS the noisy-neighbor signal.
            full_t = sorted(r.done_t - r.arrival for r in rs
                            if r.outcome == "ok")
            shed_t = sum(v for (tt, _), v in sim.tenant_shed.items()
                         if tt == tenant)
            blocks[tenant or ""] = {
                "requests": len(rs),
                "ok": sum(1 for r in rs if r.outcome == "ok"),
                "shed": shed_t,
                "shed_reasons": dict(sorted(
                    (reason, v)
                    for (tt, reason), v in sim.tenant_shed.items()
                    if tt == tenant)),
                "p50_ms": _ms(_pct(lat_t, 50)),
                "p99_ms": _ms(_pct(lat_t, 99)),
                "full_p50_ms": _ms(_pct(full_t, 50)),
                "full_p99_ms": _ms(_pct(full_t, 99)),
                "shed_rate": round(shed_t / len(rs), 4) if rs else None,
            }
        result["tenants"] = blocks
    if cfg.max_batch > 1:
        result["microbatch"] = {
            "flushes": dict(sorted(sim.batch_flushes.items())),
            "mean_size": (round(sum(sim.batch_sizes)
                                / len(sim.batch_sizes), 3)
                          if sim.batch_sizes else None),
        }
    if record_events:
        result["events"] = [list(e) for e in sim.events]
    return result


def _ms(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v * 1000, 3)


def result_fingerprint(result: Dict[str, Any]) -> str:
    """A stable digest of everything deterministic in a result — the
    bit-identical-replay assertion surface (tests, twin_smoke)."""
    return sha1(json.dumps(result, sort_keys=True).encode()).hexdigest()
