"""CLI verbs for the digital twin: ``python -m rafiki_tpu.obs twin
run|sweep|validate`` (docs/twin.md).

Module-level imports stay stdlib-only: the obs CLI builds its parser
tree unconditionally, and the twin's engine imports (gateway,
predictor, chaos) must not tax ``obs tail`` on a host that never
simulates. Everything heavy loads inside the verb bodies.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, Optional

from rafiki_tpu.obs.twin.train import cli as train_cli


def attach(sub: argparse._SubParsersAction) -> None:
    """Mount the ``twin`` verb on the obs CLI's subparser tree."""
    tp = sub.add_parser(
        "twin", help="digital-twin capacity plane: simulate, sweep, "
                     "validate (docs/twin.md)")
    tsub = tp.add_subparsers(dest="twin_cmd", required=True)

    def common(sp):
        sp.add_argument("--calibration", default=None,
                        help="calibration bundle JSON "
                             "(scripts/twin_calibrate.py); default: "
                             "calibrate from the journal dir, falling "
                             "back to the nominal synthetic bundle")
        sp.add_argument("--seed", type=int, default=0)
        sp.add_argument("--chaos", default=None, metavar="SPEC",
                        help="RAFIKI_CHAOS-grammar fault spec to inject")
        sp.add_argument("--scale", action="append", default=[],
                        metavar="SEG=FACTOR",
                        help="mis-calibrate a segment (repeatable), "
                             "e.g. forward=0.5")

    sp = tsub.add_parser("run", help="one simulation over a load shape "
                                     "or replayed serving/ts journal")
    common(sp)
    sp.add_argument("--load", default="constant",
                    help="constant|ramp|spike|diurnal|replay "
                         "(replay reconstructs arrivals from the "
                         "journal dir's serving/ts rows)")
    sp.add_argument("--qps", type=float, default=50.0)
    sp.add_argument("--duration", type=float, default=10.0)
    sp.add_argument("--workers", type=int, default=None)
    sp.add_argument("--queries", type=int, default=None,
                    help="microbatch: queries per request")
    sp.add_argument("--events", action="store_true",
                    help="carry the (capped) event log in the output")

    sp = tsub.add_parser("sweep", help="knob grid -> predicted "
                                       "p50/p99/qps/shed per row, plus "
                                       "the SLO smallest-fleet answer")
    common(sp)
    sp.add_argument("--load", default="constant")
    sp.add_argument("--qps", type=float, default=50.0)
    sp.add_argument("--duration", type=float, default=10.0)
    sp.add_argument("--grid", action="append", default=[],
                    metavar="KNOB=V1,V2,...",
                    help="sweep axis (repeatable), e.g. workers=1,2,4,8")
    sp.add_argument("--fleet", action="store_true",
                    help="also run the RAFIKI_SLO smallest-fleet search")
    sp.add_argument("--suggest-slo", action="store_true",
                    help="emit an auto-tuned RAFIKI_SLO spec set "
                         "anchored at the smallest-fleet knee "
                         "(implies --fleet)")

    sp = tsub.add_parser("validate",
                         help="replay a captured bench_serving run; "
                              "gate predicted-vs-measured p50/p99 error")
    common(sp)
    sp.add_argument("--tolerance", type=float, default=None,
                    help="relative-error gate (default 0.40)")
    sp.add_argument("--out", default=None,
                    help="write the TWIN artifact JSON here (the "
                         "bench_report --twin ledger format)")

    train_cli.attach(tsub)


def _parse_scales(items) -> Dict[str, float]:
    scales: Dict[str, float] = {}
    for item in items:
        seg, eq, val = item.partition("=")
        if not eq:
            raise SystemExit(f"bad --scale {item!r}; want segment=factor")
        scales[seg.strip()] = float(val)
    return scales


def _load_calibration(args, log_dir):
    from rafiki_tpu.obs.twin.calibration import Calibration, CalibrationError
    if args.calibration:
        cal = Calibration.load(args.calibration)
    else:
        try:
            cal = Calibration.from_journal_dir(log_dir)
        except CalibrationError as e:
            print(f"note: {e}; using the nominal synthetic bundle",
                  file=sys.stderr)
            cal = Calibration.nominal()
    scales = _parse_scales(args.scale)
    return cal.scaled(scales) if scales else cal


def _arrivals(args, log_dir):
    from rafiki_tpu.obs.twin import load as load_mod
    if args.load == "replay":
        from rafiki_tpu.obs import journal as journal_mod
        rows = [r for r in journal_mod.read_dir(log_dir)
                if r.get("kind") == "serving" and r.get("name") == "ts"]
        arr = load_mod.replay_from_ts(rows, seed=args.seed)
        if not arr:
            raise SystemExit(f"no serving/ts rows to replay under "
                             f"{log_dir}")
        return arr
    return load_mod.synthesize(args.load, qps=args.qps,
                               duration_s=args.duration, seed=args.seed)


def dispatch(args, log_dir: str, as_json: bool) -> int:
    if args.twin_cmd == "train":
        return train_cli.dispatch(args, log_dir, as_json)
    if args.twin_cmd == "run":
        return cmd_run(args, log_dir, as_json)
    if args.twin_cmd == "sweep":
        return cmd_sweep(args, log_dir, as_json)
    return cmd_validate(args, log_dir, as_json)


def cmd_run(args, log_dir: str, as_json: bool) -> int:
    from rafiki_tpu.obs.twin.engine import TwinConfig
    from rafiki_tpu.obs.twin.whatif import run_once
    cal = _load_calibration(args, log_dir)
    overrides: Dict[str, Any] = {}
    if args.workers is not None:
        overrides["workers"] = args.workers
    if args.queries is not None:
        overrides["queries_per_request"] = args.queries
    cfg = TwinConfig.from_calibration(cal, **overrides)
    res = run_once(cal, cfg, _arrivals(args, log_dir), seed=args.seed,
                   chaos_spec=args.chaos, record_events=args.events)
    if as_json:
        print(json.dumps(res, default=str))
    else:
        u = res["utilization"]
        print(f"{res['requests']} requests @ {res['qps']} qps over "
              f"{res['duration_s']}s: ok={res['ok']} shed={res['shed']} "
              f"errors={res['errors']}")
        print(f"  latency p50={res['p50_ms']}ms p99={res['p99_ms']}ms "
              f"(admit->done); shed_rate={res['shed_rate']}")
        print(f"  first saturating: {res['first_saturating']} "
              f"(worker={u['worker']} inflight={u['gateway_inflight']} "
              f"queue={u['queue']} breaker={u['breaker']} "
              f"hbm={u['hbm']})")
        print(f"  event log: {res['event_log_len']} events, "
              f"sha1 {res['event_log_sha1'][:12]}")
    return 0


def cmd_sweep(args, log_dir: str, as_json: bool) -> int:
    from rafiki_tpu.obs.twin.engine import TwinConfig
    from rafiki_tpu.obs.twin import whatif
    cal = _load_calibration(args, log_dir)
    base = TwinConfig.from_calibration(cal)
    arrivals = _arrivals(args, log_dir)
    grid = whatif.parse_grid(args.grid) or {"workers": [1, 2, 4, 8]}
    rows = whatif.sweep(cal, base, arrivals, grid, seed=args.seed,
                        chaos_spec=args.chaos)
    doc: Dict[str, Any] = {"grid": {k: list(v) for k, v in grid.items()},
                           "seed": args.seed, "rows": rows}
    if args.fleet or args.suggest_slo:
        doc["fleet"] = whatif.fleet_search(cal, base, arrivals,
                                           seed=args.seed)
    if args.suggest_slo:
        doc["suggested_slo"] = whatif.suggest_slo(doc["fleet"])
    if as_json:
        print(json.dumps(doc, default=str))
        return 0
    knobs = sorted(grid)
    for row in rows:
        knobstr = " ".join(f"{k}={row[k]}" for k in knobs)
        print(f"{knobstr:<32} qps={row['qps']:>8} p50={row['p50_ms']}ms "
              f"p99={row['p99_ms']}ms shed={row['shed_rate']} "
              f"saturates={row['first_saturating']}")
    if "fleet" in doc:
        f = doc["fleet"]
        t = f["targets"]
        if f["satisfied"]:
            print(f"fleet: {f['workers']} worker(s) meet p99<="
                  f"{t['p99_ms']}ms shed<={t['shed_rate']} "
                  f"(scanned {len(f['scanned'])})")
        else:
            print(f"fleet: NO worker count up to {len(f['scanned'])} "
                  f"meets p99<={t['p99_ms']}ms shed<={t['shed_rate']}; "
                  f"last saturates {f['first_saturating']}")
    if "suggested_slo" in doc:
        print("suggested RAFIKI_SLO (paste as the env value):")
        print(f"  {json.dumps(doc['suggested_slo'])}")
    return 0


def cmd_validate(args, log_dir: str, as_json: bool) -> int:
    from rafiki_tpu.obs.twin import validate as validate_mod
    kwargs: Dict[str, Any] = {"seed": args.seed}
    if args.tolerance is not None:
        kwargs["tolerance"] = args.tolerance
    scales = _parse_scales(args.scale)
    if scales:
        kwargs["scales"] = scales
    try:
        doc = validate_mod.validate(log_dir, **kwargs)
    except (ValueError, OSError) as e:
        print(f"twin validate: {e}", file=sys.stderr)
        return 2
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
    if as_json:
        print(json.dumps(doc, default=str))
    else:
        m, pr = doc["measured"], doc["predicted"]
        print(f"measured : p50={m['p50_ms']}ms p99={m['p99_ms']}ms "
              f"({m['requests']} requests)")
        print(f"predicted: p50={pr['p50_ms']}ms p99={pr['p99_ms']}ms "
              f"(saturates {pr['first_saturating']})")
        print(f"error    : p50={doc['p50_err']} p99={doc['p99_err']} "
              f"tolerance={doc['tolerance']} -> "
              f"{'OK' if doc['ok'] else 'FAIL'}")
    return 0 if doc["ok"] else 1
