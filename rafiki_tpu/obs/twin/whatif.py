"""What-if capacity planning on top of the twin engine.

Three layers:

* :func:`sweep` — cartesian knob grids (worker count, quorum,
  microbatch, queue depth, policy) simulated against ONE arrival
  sequence under ONE seed, so every row differs only by the knob under
  study. Each row reports predicted p50/p99/qps/shed-rate plus the
  first-saturating resource.
* :func:`slo_targets` — the p99-latency and shed-rate budgets the
  capacity question is asked against, read from the SAME ``RAFIKI_SLO``
  spec set the live burn-rate engine runs (obs/perf/slo.py); the twin
  must not invent its own notion of "good enough".
* :func:`fleet_search` — the smallest-fleet answer: scan worker counts
  ascending and return the first meeting every target, with the full
  scan attached so the operator sees the frontier, not just the pick.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from rafiki_tpu.obs.twin.calibration import Calibration
from rafiki_tpu.obs.twin.engine import TwinConfig, simulate

#: TwinConfig fields sweepable via the CLI grid grammar.
SWEEPABLE = ("workers", "queries_per_request", "min_replies", "max_queue",
             "max_inflight", "hedge_grace_s", "policy", "deadline_s")

#: Result keys copied into each sweep row next to the knob values.
ROW_METRICS = ("qps", "p50_ms", "p99_ms", "shed_rate", "requests", "ok",
               "shed", "errors", "first_saturating")

#: Fleet search scans 1..this many workers before giving up.
MAX_FLEET = 64


def run_once(cal: Calibration, cfg: TwinConfig,
             arrivals: Sequence[Union[float, Tuple[float, int]]],
             seed: int = 0, chaos_spec: Optional[str] = None,
             record_events: bool = False) -> Dict[str, Any]:
    """One simulation — the CLI ``twin run`` body."""
    return simulate(cal, cfg, arrivals, seed=seed, chaos_spec=chaos_spec,
                    record_events=record_events)


def sweep(cal: Calibration, base: TwinConfig,
          arrivals: Sequence[Union[float, Tuple[float, int]]],
          grid: Dict[str, List[Any]], seed: int = 0,
          chaos_spec: Optional[str] = None) -> List[Dict[str, Any]]:
    """Simulate every combination in ``grid`` (knob -> values) over the
    same arrivals and seed. Rows come back in deterministic grid order:
    knobs sorted by name, values in the order given."""
    unknown = set(grid) - set(SWEEPABLE)
    if unknown:
        raise ValueError(f"unsweepable knob(s): {sorted(unknown)}; "
                         f"one of {SWEEPABLE}")
    knobs = sorted(grid)
    rows: List[Dict[str, Any]] = []
    for combo in itertools.product(*(grid[k] for k in knobs)):
        overrides = dict(zip(knobs, combo))
        cfg = dataclasses.replace(base, **overrides)
        res = simulate(cal, cfg, arrivals, seed=seed,
                       chaos_spec=chaos_spec)
        row = dict(overrides)
        row.update({m: res[m] for m in ROW_METRICS})
        row["utilization"] = res["utilization"]
        rows.append(row)
    return rows


def slo_targets() -> Dict[str, float]:
    """The capacity budgets, derived from the active SLO spec set:
    ``p99_ms`` from the gateway p99-latency spec (seconds -> ms) and
    ``shed_rate`` from the shed-ratio spec. Specs disabled via
    ``RAFIKI_SLO=off`` fall back to the defaults — a fleet search with
    no target at all is meaningless."""
    from rafiki_tpu.obs.perf.slo import _specs_from_env, default_specs
    specs = _specs_from_env()
    if not specs:   # None (unset) or [] (disabled) -> defaults
        specs = default_specs()
    targets: Dict[str, float] = {}
    for s in specs:
        if s.source.startswith("hist_p99:gateway.predict"):
            targets["p99_ms"] = float(s.threshold) * 1000.0
        elif s.name == "gateway_shed_rate" or (
                s.source.startswith("ratio:gateway.shed")):
            targets["shed_rate"] = float(s.threshold)
    # Backstop with the default budgets for anything the custom spec
    # set doesn't cover — the search needs both axes.
    for s in default_specs():
        if s.source.startswith("hist_p99:gateway.predict"):
            targets.setdefault("p99_ms", float(s.threshold) * 1000.0)
        elif s.source.startswith("ratio:gateway.shed"):
            targets.setdefault("shed_rate", float(s.threshold))
    return targets


def meets(row: Dict[str, Any], targets: Dict[str, float]) -> bool:
    p99 = row.get("p99_ms")
    if p99 is None:   # nothing completed: saturated, not compliant
        return False
    if p99 > targets["p99_ms"]:
        return False
    # Failed = shed at admission OR timed out past its deadline. An
    # overloaded fleet mostly fails the second way (the p99 over the
    # surviving requests can look deceptively healthy), so both count
    # against the shed budget.
    n = row.get("requests") or 0
    failed = (row.get("shed") or 0) + (row.get("errors") or 0)
    rate = failed / n if n else 1.0
    return rate <= targets["shed_rate"]


def fleet_search(cal: Calibration, base: TwinConfig,
                 arrivals: Sequence[Union[float, Tuple[float, int]]],
                 seed: int = 0,
                 targets: Optional[Dict[str, float]] = None,
                 max_fleet: int = MAX_FLEET) -> Dict[str, Any]:
    """Smallest worker count meeting the SLO targets under this load.
    Scans ascending and stops at the first compliant fleet (capacity
    is monotone enough in practice that first-fit is the answer an
    operator wants); the scanned frontier rides along."""
    targets = dict(targets or slo_targets())
    scanned: List[Dict[str, Any]] = []
    pick: Optional[int] = None
    for w in range(1, max_fleet + 1):
        cfg = dataclasses.replace(base, workers=w)
        res = simulate(cal, cfg, arrivals, seed=seed)
        row = {"workers": w}
        row.update({m: res[m] for m in ROW_METRICS})
        scanned.append(row)
        if meets(row, targets):
            pick = w
            break
    return {"targets": targets, "workers": pick, "scanned": scanned,
            "satisfied": pick is not None,
            "first_saturating": (scanned[-1]["first_saturating"]
                                 if scanned else None)}


#: suggest_slo knee headrooms: the p99 budget sits 25% above the knee
#: fleet's simulated p99 (normal jitter must not page), the shed budget
#: at 2x observed, clamped to [1%, 25%] (a zero-shed sim must not emit
#: an unmeetable 0.0 budget; a melting one must not normalize 40% shed).
P99_HEADROOM = 1.25
SHED_HEADROOM = 2.0
SHED_FLOOR, SHED_CEIL = 0.01, 0.25


def suggest_slo(fleet: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Auto-tuned ``RAFIKI_SLO`` spec dicts from a fleet-search result:
    thresholds anchored at the smallest compliant fleet (the knee),
    where latency is highest among compliant picks — budgets derived
    there hold for any larger fleet. Output round-trips through
    ``SloSpec.from_dict`` / ``RAFIKI_SLO=<json>`` byte-identically for
    the same fleet doc (scripts/twin_smoke.py asserts this), so an
    operator can paste it straight into the live burn-rate engine.

    When no scanned fleet met the default targets, anchor on the best
    scanned p99 instead: the suggestion then documents the gap rather
    than inventing a budget the hardware cannot meet."""
    scanned = fleet.get("scanned") or []
    rows = [r for r in scanned if r.get("p99_ms") is not None]
    if not rows:
        raise ValueError("fleet search completed no requests; "
                         "no knee to tune an SLO against")
    knee = None
    if fleet.get("workers") is not None:
        for r in rows:
            if r.get("workers") == fleet["workers"]:
                knee = r
                break
    if knee is None:
        knee = min(rows, key=lambda r: float(r["p99_ms"]))
    p99_s = round(float(knee["p99_ms"]) * P99_HEADROOM / 1000.0, 6)
    n = knee.get("requests") or 0
    failed = (knee.get("shed") or 0) + (knee.get("errors") or 0)
    observed = failed / n if n else 0.0
    shed = round(min(max(observed * SHED_HEADROOM, SHED_FLOOR),
                     SHED_CEIL), 6)
    anchor = (f"{knee['workers']}-worker knee"
              if fleet.get("workers") is not None
              else f"best scanned fleet ({knee['workers']} workers, "
                   f"targets unmet)")
    return [
        {"name": "gateway_p99_latency",
         "source": "hist_p99:gateway.predict_s",
         "threshold": p99_s, "op": ">",
         "description": f"auto-tuned at the {anchor}: sim p99 "
                        f"{knee['p99_ms']}ms x{P99_HEADROOM} headroom"},
        {"name": "gateway_shed_rate",
         "source": "ratio:gateway.shed/gateway.shed+gateway.admitted",
         "threshold": shed, "op": ">",
         "description": f"auto-tuned at the {anchor}: observed "
                        f"fail rate {round(observed, 6)} "
                        f"x{SHED_HEADROOM}, clamped to "
                        f"[{SHED_FLOOR}, {SHED_CEIL}]"},
    ]


def parse_grid(items: List[str]) -> Dict[str, List[Any]]:
    """CLI grid grammar: ``knob=v1,v2,...`` per item. Values coerce to
    int, then float, then the literal string; ``none`` -> None (the
    min_replies sentinel for default quorum)."""
    grid: Dict[str, List[Any]] = {}
    for item in items:
        knob, eq, vals = item.partition("=")
        if not eq or not vals:
            raise ValueError(f"bad grid item {item!r}; want knob=v1,v2")
        grid[knob.strip()] = [_coerce(v) for v in vals.split(",")]
    return grid


def _coerce(v: str) -> Any:
    v = v.strip()
    if v.lower() in ("none", "null"):
        return None
    for conv in (int, float):
        try:
            return conv(v)
        except ValueError:
            pass
    return v
