"""Calibration bundles: everything the twin engine runs on, in one
versioned JSON artifact.

A bundle is extracted from a journal directory (the durable side
channel every serving run leaves under ``RAFIKI_LOG_DIR``) and carries
three ingredient classes:

* **hop-segment samples** — per-segment service/overhead durations
  harvested from ``serving/hops`` chains (docs/serving_anatomy.md).
  Only the *sampled* segments are kept: ``route``, ``batch_wait``,
  ``forward``/``forward_cold``, ``reply_publish``. The waiting
  segments (``admission_wait``, ``bus_queue``, ``gather_decide``) are
  deliberately DROPPED — the simulator derives those emergently from
  its own queues and quorum/hedge timing, and sampling them too would
  double-count waiting (``gather_decide`` spans reply→decide, i.e. it
  IS the straggler wait the twin simulates).
* **gateway knobs** — the live limits journaled as ``gateway/config``
  by ``Gateway.__init__``, so the twin simulates the admission budget
  the run actually had, not a guessed default.
* **cost rows** — ``perf/cost`` XLA cost-model captures (docs/perf.md)
  keyed by key hash, the service-time source for configurations that
  were never measured (:func:`service_from_cost` roofline).

Extraction fails LOUDLY, listing every missing record kind, instead of
silently defaulting: a twin calibrated on air would predict air.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional

from rafiki_tpu.obs import journal as journal_mod
from rafiki_tpu.obs.anatomy import hops as _hops

CALIBRATION_VERSION = 1

#: Segments whose duration the engine SAMPLES from the bundle. The
#: complement of the emergent set below — together they cover every
#: segment in hops.SEGMENT_OF.
SAMPLED_SEGMENTS = ("route", "batch_wait", "forward", "forward_cold",
                    "reply_publish")

#: Segments the engine derives from its own queue/gather dynamics.
EMERGENT_SEGMENTS = ("admission_wait", "bus_queue", "gather_decide",
                     "gateway_batch_wait")

#: Per-segment sample cap: above this, evenly spaced order statistics
#: of the sorted samples are kept — deterministic, shape-preserving.
SAMPLE_CAP = 512

#: Record kinds a bundle cannot be built without (kind/name keys as
#: they appear in the journals).
REQUIRED_KINDS = ("serving/hops", "gateway/config")

#: v5e roofline constants for the cost-model service path: bf16 peak
#: is shared with obs.perf.profiler; HBM bandwidth is the v5e
#: datasheet number (~819 GB/s).
HBM_BW_BYTES_S = 8.19e11
HBM_BYTES_PER_CHIP = 1.6e10

#: Multiplier spread applied around the nominal forward time by
#: :meth:`Calibration.nominal` — a literal right-skewed grid (p50≈1,
#: long tail) so even the synthetic bundle has believable percentiles.
_NOMINAL_SPREAD = (0.82, 0.86, 0.89, 0.92, 0.94, 0.96, 0.97, 0.98,
                   0.99, 1.00, 1.00, 1.01, 1.02, 1.03, 1.04, 1.05,
                   1.06, 1.08, 1.10, 1.12, 1.15, 1.18, 1.22, 1.27,
                   1.33, 1.40, 1.50, 1.62, 1.80, 2.05, 2.40, 3.00)


class CalibrationError(ValueError):
    """A journal dir missing required record kinds. ``missing`` lists
    every absent kind so the operator fixes the capture once, not one
    error message at a time."""

    def __init__(self, missing: List[str], source: str = ""):
        self.missing = list(missing)
        self.source = source
        super().__init__(
            "cannot calibrate twin from %r: missing journal record "
            "kind(s): %s — run the workload with RAFIKI_LOG_DIR set "
            "(e.g. bench_serving --smoke) so the serving plane journals "
            "them" % (source or "<records>", ", ".join(self.missing)))


def _cap(samples: List[float]) -> List[float]:
    xs = sorted(samples)
    if len(xs) <= SAMPLE_CAP:
        return xs
    last = len(xs) - 1
    return [xs[(i * last) // (SAMPLE_CAP - 1)] for i in range(SAMPLE_CAP)]


@dataclasses.dataclass
class Calibration:
    """One loaded bundle. ``segments`` maps segment name -> sorted
    duration samples (seconds); ``gateway`` carries the live knob dict;
    ``cost`` maps key_hash -> cost row; ``workers`` is the observed
    fleet size."""

    segments: Dict[str, List[float]]
    gateway: Dict[str, Any]
    workers: int
    cost: Dict[str, Dict[str, Any]] = dataclasses.field(default_factory=dict)
    source: str = ""
    version: int = CALIBRATION_VERSION
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)

    # -- construction --------------------------------------------------------

    @classmethod
    def from_records(cls, records: List[Dict[str, Any]],
                     source: str = "") -> "Calibration":
        """Build from already-merged journal records (read_dir output).
        Raises :class:`CalibrationError` listing every missing kind."""
        seg_samples: Dict[str, List[float]] = {s: [] for s in SAMPLED_SEGMENTS}
        gateway_cfg: Optional[Dict[str, Any]] = None
        cost: Dict[str, Dict[str, Any]] = {}
        fanouts: List[int] = []
        for r in records:
            kind, name = r.get("kind"), r.get("name")
            if kind == "serving" and name == "hops":
                chains = r.get("chains") or {}
                fanouts.append(len(chains))
                for marks in chains.values():
                    for seg, dur in _hops.segments(marks):
                        if seg in seg_samples and dur >= 0:
                            seg_samples[seg].append(float(dur))
            elif kind == "gateway" and name == "config":
                gateway_cfg = {k: v for k, v in r.items()
                               if k not in ("ts", "pid", "role", "kind",
                                            "name", "trace_id")}
            elif kind == "perf" and name == "cost":
                kh = r.get("key_hash")
                if kh:
                    cost[kh] = {k: r.get(k) for k in
                                ("key", "program_kind", "k", "flops",
                                 "bytes_accessed", "peak_hbm_bytes")}
            elif kind == "gather" and name == "predictor.gather":
                ws = r.get("workers") or []
                fanouts.append(len(ws))
        missing = []
        if not any(seg_samples[s] for s in ("forward", "forward_cold")):
            missing.append("serving/hops")
        if gateway_cfg is None:
            missing.append("gateway/config")
        if missing:
            raise CalibrationError(missing, source)
        workers = max(fanouts) if fanouts else 1
        return cls(
            segments={s: _cap(xs) for s, xs in seg_samples.items() if xs},
            gateway=gateway_cfg, workers=max(1, workers), cost=cost,
            source=source,
            meta={"hops_records": sum(1 for r in records
                                      if r.get("kind") == "serving"
                                      and r.get("name") == "hops"),
                  "cost_rows": len(cost)})

    @classmethod
    def from_journal_dir(cls, log_dir) -> "Calibration":
        records = journal_mod.read_dir(log_dir)
        if not records:
            raise CalibrationError(list(REQUIRED_KINDS), str(log_dir))
        return cls.from_records(records, source=str(log_dir))

    @classmethod
    def nominal(cls, forward_ms: float = 5.0, workers: int = 2,
                overhead_ms: float = 0.2) -> "Calibration":
        """A synthetic bundle for pre-gaming without captured telemetry
        (the chaos pre-gate default). Forward times spread the literal
        :data:`_NOMINAL_SPREAD` grid around ``forward_ms``; the wiring
        segments get a flat ``overhead_ms``."""
        fwd = sorted(forward_ms / 1000.0 * m for m in _NOMINAL_SPREAD)
        ovh = [overhead_ms / 1000.0 * m for m in (0.5, 0.8, 1.0, 1.2, 2.0)]
        from rafiki_tpu.gateway.gateway import GatewayConfig

        g = GatewayConfig()
        return cls(
            segments={"forward": fwd, "forward_cold": [f * 4 for f in fwd],
                      "route": list(ovh), "batch_wait": list(ovh),
                      "reply_publish": list(ovh)},
            gateway={"max_inflight": g.max_inflight,
                     "max_queue": g.max_queue,
                     "default_deadline_s": g.default_deadline_s,
                     "min_replies": g.min_replies,
                     "hedge_grace_s": g.hedge_grace_s,
                     "policy": g.policy,
                     "breaker_failures": g.breaker_failures,
                     "breaker_cooldown_s": g.breaker_cooldown_s},
            workers=workers, source="nominal",
            meta={"forward_ms": forward_ms})

    # -- (de)serialization ---------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {"calibration_version": self.version, "source": self.source,
                "workers": self.workers, "gateway": self.gateway,
                "segments": {s: [round(x, 9) for x in xs]
                             for s, xs in self.segments.items()},
                "cost": self.cost, "meta": self.meta}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Calibration":
        v = d.get("calibration_version")
        if v != CALIBRATION_VERSION:
            raise ValueError(f"unsupported calibration_version {v!r} "
                             f"(this build reads {CALIBRATION_VERSION})")
        return cls(segments={s: sorted(float(x) for x in xs)
                             for s, xs in (d.get("segments") or {}).items()},
                   gateway=dict(d.get("gateway") or {}),
                   workers=int(d.get("workers") or 1),
                   cost=dict(d.get("cost") or {}),
                   source=d.get("source") or "", version=v,
                   meta=dict(d.get("meta") or {}))

    def save(self, path) -> None:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
            f.write("\n")

    @classmethod
    def load(cls, path) -> "Calibration":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    # -- derived views -------------------------------------------------------

    def dist(self, segment: str) -> List[float]:
        """The (possibly empty) sample list for one segment; forward
        falls back to forward_cold and vice versa so a cold-only or
        warm-only capture still simulates."""
        xs = self.segments.get(segment)
        if xs:
            return xs
        if segment == "forward":
            return self.segments.get("forward_cold") or []
        if segment == "forward_cold":
            return self.segments.get("forward") or []
        return []

    def scaled(self, scales: Dict[str, float]) -> "Calibration":
        """A copy with named segments multiplied — the deliberate
        mis-calibration knob the validation smoke uses to prove the
        gate fails when the model is wrong."""
        unknown = set(scales) - set(SAMPLED_SEGMENTS)
        if unknown:
            raise ValueError(f"unknown segment(s) to scale: "
                             f"{sorted(unknown)}; one of {SAMPLED_SEGMENTS}")
        segs = {s: ([x * scales[s] for x in xs] if s in scales else list(xs))
                for s, xs in self.segments.items()}
        return dataclasses.replace(
            self, segments=segs,
            meta=dict(self.meta, scaled={k: v for k, v in scales.items()}))

    def service_from_cost(self, key_hash_prefix: str,
                          peak_flops: Optional[float] = None,
                          mfu: float = 0.3) -> float:
        """Roofline service-time prediction for an UNMEASURED program:
        max(compute, memory) seconds at an assumed MFU — the path that
        answers capacity questions for configs never run on hardware."""
        rows = [r for kh, r in sorted(self.cost.items())
                if kh.startswith(key_hash_prefix)]
        if not rows:
            raise KeyError(
                f"no perf/cost row with key_hash prefix "
                f"{key_hash_prefix!r} in this calibration "
                f"({len(self.cost)} row(s) present)")
        row = rows[0]
        if peak_flops is None:
            from rafiki_tpu.obs.perf.profiler import PEAK_FLOPS_V5E_BF16
            peak_flops = PEAK_FLOPS_V5E_BF16
        compute_s = float(row.get("flops") or 0.0) / (peak_flops * mfu)
        memory_s = float(row.get("bytes_accessed") or 0.0) / HBM_BW_BYTES_S
        return max(compute_s, memory_s)

    def with_forward_from_cost(self, key_hash_prefix: str,
                               mfu: float = 0.3) -> "Calibration":
        """Replace the forward distribution with the cost-model
        roofline point — single-sample, i.e. deterministic service."""
        svc = self.service_from_cost(key_hash_prefix, mfu=mfu)
        segs = dict(self.segments)
        segs["forward"] = [svc]
        segs.pop("forward_cold", None)
        return dataclasses.replace(
            self, segments=segs,
            meta=dict(self.meta, forward_from_cost=key_hash_prefix, mfu=mfu))

    def hbm_frac(self) -> Optional[float]:
        """Static peak-HBM occupancy fraction of the largest captured
        program, against one v5e chip — None without cost rows."""
        peaks = [float(r.get("peak_hbm_bytes") or 0.0)
                 for r in self.cost.values()]
        if not peaks:
            return None
        return max(peaks) / HBM_BYTES_PER_CHIP
