"""Perf sentinel: continuous profiling, SLOs, and anomaly detection.

Three coupled pieces (docs/perf.md):

* :mod:`~rafiki_tpu.obs.perf.profiler` — per-program XLA cost capture
  joined with observed step times (MFU/roofline), the ``perf``
  telemetry collector and the ``perf/*`` journal records.
* :mod:`~rafiki_tpu.obs.perf.slo` — declarative SLO specs evaluated
  as multi-window burn rates; breaches journal, count, and trip the
  flight recorder.
* :mod:`~rafiki_tpu.obs.perf.anomaly` — the EWMA+MAD detector the
  profiler runs over every program's step/compile times.

Importing this package registers the ``perf`` and ``slo`` telemetry
collectors. It never imports jax at module scope.
"""

from rafiki_tpu.obs.perf import anomaly, profiler, slo

__all__ = ["anomaly", "profiler", "slo"]
