"""Continuous profiler: per-program cost model x observed step times.

Two data feeds, one store:

* **Cost capture** — at a program's first epoch the train loop hands
  its jitted callable (plus example args) to :func:`capture_cost`,
  which AOT-lowers and compiles it and reads XLA's cost analysis:
  flops, bytes accessed, and (where the backend reports it) a peak
  device-memory estimate. One extra compile per program key per
  process — bounded, and switchable via ``RAFIKI_PERF_COST_CAPTURE=0``.
  Captured costs are journaled (``perf/cost``) so they survive the
  process and can be joined cross-process by the CLI.

* **Step sampling** — every epoch the train loop calls
  :func:`note_epoch` with the measured wall split. Warm samples feed a
  per-program :class:`~rafiki_tpu.obs.perf.anomaly.EwmaMad` detector;
  an anomalous epoch journals ``perf/anomaly``, bumps the
  ``perf.anomalies`` counter, and charges the excess wall over the
  expected mean to the goodput ledger's ``badput_s`` bucket — time the
  hardware spent but the baseline says it shouldn't have.

The joined view (model flops / observed step seconds = achieved
FLOP/s, over peak = MFU) is exposed three ways: the ``perf`` telemetry
collector (so ``GET /metrics`` and prom exposition pick it up for
free), the ``perf/cost``+``perf/step`` journal records, and the
``python -m rafiki_tpu.obs profile`` CLI that renders the roofline
join. Program identities are long key reprs; metrics key on a short
sha1 prefix (``key_hash``) and the full repr travels in the journal.

Import-light by design: jax is only touched inside guarded helpers,
so the obs CLI can read journals on boxes with no accelerator stack.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict, deque
from typing import Any, Dict, Optional

from rafiki_tpu import telemetry
from rafiki_tpu.obs.journal import journal
from rafiki_tpu.obs.ledger import ledger
from rafiki_tpu.obs.perf.anomaly import EwmaMad

ENV_COST_CAPTURE = "RAFIKI_PERF_COST_CAPTURE"

#: v5e bf16 peak per chip — the MFU denominator bench.py also uses.
PEAK_FLOPS_V5E_BF16 = 197e12

#: Bounded stores: distinct programs per process / warm samples per program.
MAX_PROGRAMS = 64
STEP_RING = 256


def _key_str(key: Any) -> str:
    return key if isinstance(key, str) else repr(key)


def key_hash(key: Any) -> str:
    return hashlib.sha1(_key_str(key).encode()).hexdigest()[:10]


def cost_capture_enabled() -> bool:
    return os.environ.get(ENV_COST_CAPTURE, "1") not in ("0", "false", "off")


class _ProgramStats:
    """One program's cost model + observed-step reservoir."""

    def __init__(self, key: Any, kind: str, k: int):
        self.key = _key_str(key)
        self.hash = key_hash(key)
        self.kind = kind
        self.k = int(k)
        self.cost: Optional[Dict[str, Any]] = None
        self.warm = deque(maxlen=STEP_RING)
        self.warm_count = 0
        self.warm_sum = 0.0
        self.cold_count = 0
        self.cold_sum = 0.0
        self.feed_sum = 0.0
        self.detector = EwmaMad()
        self.cold_detector = EwmaMad(warmup=2)

    def summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"kind": self.kind, "k": self.k,
                               "epochs": self.warm_count,
                               "cold_epochs": self.cold_count}
        if self.warm_count:
            out["step_mean_s"] = self.warm_sum / self.warm_count
            ordered = sorted(self.warm)
            out["step_p50_s"] = ordered[len(ordered) // 2]
            out["step_min_s"] = ordered[0]
        if self.cold_count:
            out["compile_mean_s"] = self.cold_sum / self.cold_count
        if self.feed_sum:
            out["feed_s"] = self.feed_sum
        if self.cost:
            out.update({k: v for k, v in self.cost.items() if v is not None})
            flops = self.cost.get("flops")
            p50 = out.get("step_p50_s")
            if flops and p50:
                out["achieved_flops_s"] = flops / p50
                peak = _peak_flops()
                if peak:
                    out["mfu"] = flops / p50 / peak
        return out


_lock = threading.Lock()
_programs: "OrderedDict[str, _ProgramStats]" = OrderedDict()
_hbm_peak = 0.0
_mem_broken = False
_peak_cache: Optional[float] = None


def _get(key: Any, kind: str, k: int) -> _ProgramStats:
    ks = _key_str(key)
    stats = _programs.get(ks)
    if stats is None:
        stats = _ProgramStats(key, kind, k)
        _programs[ks] = stats
        while len(_programs) > MAX_PROGRAMS:
            _programs.popitem(last=False)
    return stats


def _peak_flops() -> Optional[float]:
    """Peak FLOP/s for MFU — only claimed on an accelerator backend
    (anything that isn't the host CPU; TPU-backed PJRT plugins register
    under several names). On CPU the v5e constant is meaningless and
    MFU reads as null."""
    global _peak_cache
    if _peak_cache is not None:
        return _peak_cache or None
    try:
        import jax

        _peak_cache = (PEAK_FLOPS_V5E_BF16
                       if jax.default_backend() != "cpu" else 0.0)
    except Exception:
        _peak_cache = 0.0
    return _peak_cache or None


def _sample_device_mem() -> None:
    """Track the process-lifetime peak of device bytes_in_use. CPU
    backends report no memory_stats — one failed probe disables it."""
    global _hbm_peak, _mem_broken
    if _mem_broken:
        return
    try:
        import jax

        total = 0.0
        seen = False
        for dev in jax.local_devices():
            ms = dev.memory_stats()
            if ms and "bytes_in_use" in ms:
                total += float(ms["bytes_in_use"])
                seen = True
        if not seen:
            _mem_broken = True
            return
        if total > _hbm_peak:
            _hbm_peak = total
            telemetry.set_gauge("perf.hbm_peak_bytes", total)
    except Exception:
        _mem_broken = True


def capture_cost(key: Any, jitted: Any, *args: Any,
                 kind: str = "serial", k: int = 1) -> Optional[Dict[str, Any]]:
    """AOT-compile ``jitted(*args)`` and record its XLA cost analysis
    under ``key``. Idempotent per key; never raises (a backend that
    can't lower/compile the AOT path just leaves the cost model empty).
    Returns the captured cost dict, or None."""
    if not cost_capture_enabled():
        return None
    with _lock:
        stats = _get(key, kind, k)
        if stats.cost is not None:
            return stats.cost
        stats.cost = {}  # claim under the lock; compile outside it
    cost: Dict[str, Any] = {}
    try:
        import time as _time

        t0 = _time.monotonic()
        compiled = jitted.lower(*args).compile()
        cost["cost_capture_s"] = _time.monotonic() - t0
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
        cost["flops"] = float(ca.get("flops", 0.0)) or None
        cost["bytes_accessed"] = float(ca.get("bytes accessed", 0.0)) or None
        try:
            ma = compiled.memory_analysis()
            peak = (getattr(ma, "temp_size_in_bytes", 0)
                    + getattr(ma, "argument_size_in_bytes", 0)
                    + getattr(ma, "output_size_in_bytes", 0))
            cost["peak_hbm_bytes"] = float(peak) or None
        except Exception:
            cost["peak_hbm_bytes"] = None
    except Exception:
        cost = {}
    with _lock:
        stats = _get(key, kind, k)
        stats.cost = cost or None
    if cost.get("flops"):
        telemetry.inc("perf.cost_captures")
        journal.record("perf", "cost", key=_key_str(key),
                       key_hash=key_hash(key), program_kind=kind, k=int(k),
                       flops=cost.get("flops"),
                       bytes_accessed=cost.get("bytes_accessed"),
                       peak_hbm_bytes=cost.get("peak_hbm_bytes"),
                       cost_capture_s=cost.get("cost_capture_s"))
    return cost or None


def note_epoch(key: Any, dt: float, feed_s: float = 0.0, cold: bool = False,
               kind: str = "serial", k: int = 1,
               packing_key: Optional[str] = None,
               group_width: Optional[int] = None) -> Optional[Dict[str, float]]:
    """Record one epoch's wall split for ``key``; runs the anomaly
    detector on the compute portion and returns its report (already
    journaled / countered / ledgered) when it fires. ``packing_key``
    (the repr of the model's packing key, when the caller is a packed
    loop) is stamped onto the ``perf/step`` record so the train twin's
    step-time calibration buckets per (packing_key, k) without joining
    through LRU key strings (docs/twin.md). ``group_width`` (set by the
    sharded loop) likewise rides the record so calibration can keep
    group-sharded samples out of the single-chip step-time pools — a
    width-w epoch's wall includes per-step all-gathers and is not a
    single-chip observation."""
    compute_s = max(dt - feed_s, 0.0)
    with _lock:
        stats = _get(key, kind, k)
        if cold:
            stats.cold_count += 1
            stats.cold_sum += compute_s
            report = stats.cold_detector.observe(compute_s)
        else:
            stats.warm_count += 1
            stats.warm_sum += compute_s
            stats.warm.append(compute_s)
            report = stats.detector.observe(compute_s)
        stats.feed_sum += feed_s
        h = stats.hash
    _sample_device_mem()
    journal.record("perf", "step", key_hash=h, dt=dt, feed_s=feed_s,
                   cold=bool(cold), program_kind=kind, k=int(k),
                   packing_key=packing_key,
                   group_width=int(group_width) if group_width else None)
    if report is not None:
        telemetry.inc("perf.anomalies")
        # The wall this epoch spent over its expected mean bought no
        # extra training — book it as badput so degraded goodput and
        # the anomaly stream agree (docs/perf.md).
        ledger.add("badput_s", max(report["value"] - report["mean"], 0.0))
        journal.record("perf", "anomaly", key_hash=h, key=_key_str(key),
                       program_kind=kind,
                       phase="compile" if cold else "step", **report)
    return report


def snapshot() -> Dict[str, Any]:
    """The ``perf`` telemetry collector: per-program joined summaries
    keyed by key_hash, plus process-wide aggregates."""
    with _lock:
        programs = {s.hash: s.summary() for s in _programs.values()}
        out: Dict[str, Any] = {"n_programs": len(programs),
                               "programs": programs}
        if _hbm_peak:
            out["hbm_peak_bytes"] = _hbm_peak
    return out


def reset() -> None:
    """Drop all profiler state (tests)."""
    global _hbm_peak, _mem_broken, _peak_cache
    with _lock:
        _programs.clear()
        _hbm_peak = 0.0
        _mem_broken = False
        _peak_cache = None


telemetry.register_collector("perf", snapshot)
