"""EWMA+MAD anomaly detection for step/compile wall times.

The regression sentinel's core primitive: an exponentially weighted
moving average tracks the expected value of a timing series, and an
EWMA of absolute deviations (a robust MAD stand-in that needs no
sample buffer) tracks its spread. After a warmup count, a sample above

    mean + k * max(mad, floor_frac * mean)

is an anomaly. The MAD floor matters: a perfectly steady series has
mad -> 0, and without the floor any scheduler hiccup would alert.

Anomalous samples are absorbed at a quarter of the normal learning
rate, so a genuine sustained regression *eventually* becomes the new
baseline (one alert per shift, not one per epoch forever) while a
single spike barely moves the stats.

Detectors hold a few floats each; the profiler keys one per program
(bounded by its program-entry cap). No jax, no threads — callers
serialize access (the train loop records epochs from one thread).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

#: Samples absorbed before the detector is allowed to flag.
DEFAULT_WARMUP = 8
#: Threshold multiplier on the deviation estimate.
DEFAULT_K = 4.0
#: EWMA learning rate.
DEFAULT_ALPHA = 0.25
#: Deviation floor as a fraction of the mean (see module docstring).
DEFAULT_FLOOR_FRAC = 0.10

ENV_WARMUP = "RAFIKI_PERF_WARMUP"
ENV_K = "RAFIKI_PERF_K"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


class EwmaMad:
    """One timing series' anomaly state (see module docstring)."""

    def __init__(self, alpha: float = DEFAULT_ALPHA,
                 k: Optional[float] = None,
                 warmup: Optional[int] = None,
                 floor_frac: float = DEFAULT_FLOOR_FRAC):
        self.alpha = alpha
        self.k = k if k is not None else _env_float(ENV_K, DEFAULT_K)
        self.warmup = int(warmup if warmup is not None
                          else _env_float(ENV_WARMUP, DEFAULT_WARMUP))
        self.floor_frac = floor_frac
        self.n = 0
        self.mean: Optional[float] = None
        self.mad = 0.0

    def threshold(self) -> Optional[float]:
        """Current alert threshold, or None before any sample."""
        if self.mean is None:
            return None
        return self.mean + self.k * max(self.mad, self.floor_frac * self.mean)

    def observe(self, value: float) -> Optional[Dict[str, float]]:
        """Absorb one sample; returns an anomaly report dict (value,
        mean, mad, threshold, ratio) when it fires, else None."""
        value = float(value)
        if self.mean is None:
            self.mean = value
            self.n = 1
            return None
        thr = self.threshold()
        anomalous = self.n >= self.warmup and thr is not None and value > thr
        report = None
        if anomalous:
            report = {
                "value": value,
                "mean": self.mean,
                "mad": self.mad,
                "threshold": thr,
                "ratio": value / self.mean if self.mean > 0 else float("inf"),
            }
        a = self.alpha * (0.25 if anomalous else 1.0)
        self.mad = (1 - a) * self.mad + a * abs(value - self.mean)
        self.mean = (1 - a) * self.mean + a * value
        self.n += 1
        return report
