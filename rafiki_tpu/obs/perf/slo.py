"""Declarative SLOs evaluated as multi-window burn rates.

An SLO spec names a *source* in the process's telemetry snapshot, a
comparison, and two (or more) trailing windows. A breach requires the
comparison to hold over **every** window — the classic multi-window
burn-rate rule: the short window proves the problem is happening now,
the long window proves it isn't a blip.

Sources (the part before ``:`` picks the resolver and the default
evaluation mode):

    counter:<name>              telemetry counter        -> rate/s
    ratio:<num>/<a>+<b>...      counter delta ratio      -> ratio
    gauge:<name>                telemetry gauge          -> level
    hist_p99:<name>             histogram p99 (reservoir)-> level
    ledger:goodput              fleet goodput roll-up    -> level
    ledger:<bucket>             ledger total bucket secs -> rate/s

``rate`` compares the per-second delta over the window; ``ratio``
compares delta(num)/delta(den); ``level`` requires the comparison to
hold for every sample in the window (sustained, not instantaneous). A
window with no sample old enough is *not evaluable* and cannot breach
— a fresh process never alarms on an empty history.

The engine samples on ``tick()``; hot paths (gateway predict, the
train loops, predictor queries, mesh supervision) call ``maybe_tick``
which is one clock read when the tick interval hasn't elapsed.
Breaches bump ``slo.breaches``, journal ``slo/breach`` and trip the
flight recorder, so every breach is reconstructible post-mortem;
recoveries journal ``slo/recover``. Current burn state rides in the
``slo`` telemetry collector and the periodic ``slo/state`` journal
record — ``python -m rafiki_tpu.obs slo`` renders either.

Specs come from ``RAFIKI_SLO``: unset -> :func:`default_specs`;
``off`` -> disabled; inline JSON (``[{...}]``) or a path to a JSON
file -> custom. See docs/perf.md for the grammar.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from rafiki_tpu import telemetry
from rafiki_tpu.obs.journal import journal

ENV_SPEC = "RAFIKI_SLO"
ENV_TICK = "RAFIKI_SLO_TICK_S"
DEFAULT_TICK_S = 5.0
DEFAULT_WINDOWS = (60.0, 300.0)
RING = 512


@dataclass
class SloSpec:
    name: str
    source: str
    threshold: float
    op: str = ">"
    windows: Tuple[float, ...] = DEFAULT_WINDOWS
    mode: str = ""            # derived from source when empty
    min_wall_s: float = 0.0   # engine age before the spec is live
    description: str = ""

    def __post_init__(self):
        if self.op not in (">", "<"):
            raise ValueError(f"slo {self.name}: op must be '>' or '<'")
        self.windows = tuple(float(w) for w in self.windows)
        if not self.windows:
            raise ValueError(f"slo {self.name}: needs at least one window")
        if not self.mode:
            head = self.source.split(":", 1)[0]
            if head == "counter" or (head == "ledger"
                                     and self.source != "ledger:goodput"):
                self.mode = "rate"
            elif head == "ratio":
                self.mode = "ratio"
            else:
                self.mode = "level"

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SloSpec":
        known = {"name", "source", "threshold", "op", "windows", "mode",
                 "min_wall_s", "description"}
        return cls(**{k: v for k, v in d.items() if k in known})


def default_specs() -> List[SloSpec]:
    return [
        SloSpec("gateway_p99_latency", "hist_p99:gateway.predict_s", 2.0,
                description="end-to-end gateway predict p99 under 2s"),
        SloSpec("gateway_shed_rate",
                "ratio:gateway.shed/gateway.shed+gateway.admitted", 0.05,
                description="shed fraction of admitted+shed under 5%"),
        SloSpec("trial_goodput_floor", "ledger:goodput", 0.30, op="<",
                windows=(120.0, 600.0), min_wall_s=120.0,
                description="fleet goodput (step_s/wall_s) above 0.30"),
        SloSpec("mesh_downtime_budget", "ledger:downtime_s", 0.10,
                description="downtime under 10% of wall"),
        SloSpec("step_anomaly_rate", "counter:perf.anomalies", 0.05,
                description="step-time anomalies under 3/min sustained"),
        SloSpec("divergence_rate", "counter:health.divergences", 0.02,
                description="numerics divergences under ~1/min sustained "
                            "(docs/health.md)"),
        SloSpec("serving_forward_p99", "hist_p99:serving.hop.forward_s",
                1.0, description="per-hop latency budget "
                                 "(docs/serving_anatomy.md): device "
                                 "forward p99 under 1s"),
    ]


def _resolve(source: str, snap: Dict[str, Any]) -> Optional[Any]:
    """Read one spec's raw (cumulative or instantaneous) value out of a
    telemetry snapshot; None means 'no data this tick'."""
    head, _, rest = source.partition(":")
    if head == "counter":
        return float(snap.get("counters", {}).get(rest, 0.0))
    if head == "gauge":
        return snap.get("gauges", {}).get(rest)
    if head == "hist_p99":
        h = snap.get("histograms", {}).get(rest)
        return None if not h else h.get("p99")
    if head == "ratio":
        num, _, den = rest.partition("/")
        counters = snap.get("counters", {})
        return (float(counters.get(num, 0.0)),
                sum(float(counters.get(d, 0.0)) for d in den.split("+")))
    if head == "ledger":
        led = snap.get("goodput")
        if not isinstance(led, dict):
            return None
        if rest == "goodput":
            return led.get("goodput")
        return float(led.get("total", {}).get(rest, 0.0))
    return None


def _compare(op: str, value: float, threshold: float) -> bool:
    return value > threshold if op == ">" else value < threshold


class SloEngine:
    """Samples spec sources into bounded rings and evaluates the
    multi-window burn rule on every tick (see module docstring)."""

    def __init__(self, specs: Optional[Sequence[SloSpec]] = None,
                 tick_s: Optional[float] = None, clock=time.monotonic):
        self._lock = threading.RLock()
        self._clock = clock
        self.configure(specs=specs, tick_s=tick_s)

    def configure(self, specs: Optional[Sequence[SloSpec]] = None,
                  tick_s: Optional[float] = None) -> None:
        with self._lock:
            self.specs = list(default_specs() if specs is None else specs)
            self.tick_s = (float(os.environ.get(ENV_TICK, DEFAULT_TICK_S))
                           if tick_s is None else float(tick_s))
            self._rings: Dict[str, deque] = {
                s.name: deque(maxlen=RING) for s in self.specs}
            self._breaching: Dict[str, bool] = {
                s.name: False for s in self.specs}
            self._last_eval: Dict[str, Dict[str, Any]] = {}
            self._t0 = self._clock()
            self._last_tick = 0.0

    # -- evaluation ----------------------------------------------------------

    def _window_value(self, spec: SloSpec, ring: deque, now: float,
                      w: float) -> Optional[float]:
        """The spec's value over the trailing window ``w`` ending now,
        or None when the ring doesn't reach back a full window."""
        base = None
        in_window: List[float] = []
        for ts, raw in ring:
            if ts <= now - w:
                base = (ts, raw)  # newest sample at least w old
            else:
                in_window.append(raw)
        if spec.mode == "level":
            if base is None:
                return None  # window not fully covered yet
            samples = [base[1]] + in_window
            samples = [s for s in samples if s is not None]
            if not samples:
                return None
            # The op must hold across the WHOLE window: evaluate the
            # least-breaching sample.
            return min(samples) if spec.op == ">" else max(samples)
        if base is None or not ring:
            return None
        ts0, raw0 = base
        ts1, raw1 = ring[-1]
        span = ts1 - ts0
        if span <= 0.0:
            return None
        if spec.mode == "ratio":
            dnum = raw1[0] - raw0[0]
            dden = raw1[1] - raw0[1]
            if dden <= 0.0:
                return None
            return dnum / dden
        return (raw1 - raw0) / span  # rate/s

    def tick(self, now: Optional[float] = None) -> Dict[str, Dict[str, Any]]:
        """Sample every spec and evaluate; returns the per-spec state
        dict (also kept for the collector)."""
        with self._lock:
            now = self._clock() if now is None else now
            self._last_tick = now
            if not self.specs:
                return {}
            snap = telemetry.snapshot()
            state: Dict[str, Dict[str, Any]] = {}
            for spec in self.specs:
                ring = self._rings[spec.name]
                raw = _resolve(spec.source, snap)
                if raw is not None:
                    ring.append((now, raw))
                windows: List[Dict[str, Any]] = []
                evaluable = raw is not None and (
                    now - self._t0 >= spec.min_wall_s)
                breaching = evaluable and bool(ring)
                for w in spec.windows:
                    wv = (self._window_value(spec, ring, now, w)
                          if evaluable else None)
                    windows.append({"w": w, "value": wv})
                    if wv is None or not _compare(spec.op, wv, spec.threshold):
                        breaching = False
                worst = max((d["value"] for d in windows
                             if d["value"] is not None),
                            default=None)
                state[spec.name] = {
                    "breaching": int(breaching),
                    "threshold": spec.threshold,
                    "value": worst,
                    "burn": (worst / spec.threshold
                             if worst is not None and spec.threshold > 0
                             else None),
                    "windows": windows,
                }
                was = self._breaching[spec.name]
                self._breaching[spec.name] = breaching
                if breaching and not was:
                    self._on_breach(spec, state[spec.name])
                elif was and not breaching:
                    telemetry.inc("slo.recoveries")
                    journal.record("slo", "recover", slo=spec.name)
            self._last_eval = state
            journal.record("slo", "state", state={
                name: {k: v for k, v in st.items() if k != "windows"}
                for name, st in state.items()})
            return state

    def _on_breach(self, spec: SloSpec, st: Dict[str, Any]) -> None:
        telemetry.inc("slo.breaches")
        journal.record("slo", "breach", slo=spec.name, source=spec.source,
                       op=spec.op, threshold=spec.threshold,
                       value=st["value"], windows=st["windows"],
                       description=spec.description)
        # Every breach leaves a full post-mortem bundle behind.
        from rafiki_tpu.obs import recorder

        recorder.dump(f"slo:{spec.name}",
                      extra={"slo": {"name": spec.name, **st}})

    def maybe_tick(self) -> Optional[Dict[str, Dict[str, Any]]]:
        """The hot-path entry: a clock read and a compare unless the
        tick interval has elapsed."""
        if not self.specs:
            return None
        now = self._clock()
        if now - self._last_tick < self.tick_s:
            return None
        return self.tick(now)

    def collector(self) -> Dict[str, Any]:
        """The ``slo`` telemetry collector payload."""
        with self._lock:
            return {
                "specs": len(self.specs),
                "breaching": sum(self._breaching.values()),
                "state": {
                    name: {k: v for k, v in st.items() if k != "windows"}
                    for name, st in self._last_eval.items()},
            }


def _specs_from_env() -> Optional[List[SloSpec]]:
    """None -> defaults; [] -> disabled; else parsed custom specs.
    A malformed spec disables nothing — defaults apply and the error
    is journaled rather than raised (SLOs must not break hosts)."""
    raw = os.environ.get(ENV_SPEC, "").strip()
    if not raw:
        return None
    if raw.lower() in ("off", "0", "false", "none"):
        return []
    try:
        if not raw.lstrip().startswith(("[", "{")):
            with open(raw) as f:
                raw = f.read()
        data = json.loads(raw)
        if isinstance(data, dict):
            data = data.get("specs", [])
        return [SloSpec.from_dict(d) for d in data]
    except Exception as e:
        journal.record("slo", "config_error", error=str(e))
        return None


#: Process-global engine, configured from RAFIKI_SLO at import.
engine = SloEngine(specs=_specs_from_env())


def configure(specs: Optional[Sequence[SloSpec]] = None,
              tick_s: Optional[float] = None) -> SloEngine:
    """(Re)configure the global engine — smoke scripts and tests."""
    engine.configure(specs=specs, tick_s=tick_s)
    return engine


def configure_from_env() -> SloEngine:
    engine.configure(specs=_specs_from_env())
    return engine


def maybe_tick() -> Optional[Dict[str, Dict[str, Any]]]:
    return engine.maybe_tick()


telemetry.register_collector("slo", engine.collector)
