"""Flight recorder: dump the last-N observability ring on the way down.

When a process dies — unhandled exception, SIGTERM from a drain or a
chaos ``term`` fault, Ctrl-C — its in-memory spans and metrics die
with it. The flight recorder writes one self-contained JSON file into
the journal directory at that moment:

    <log_dir>/flight-<role>-<pid>-<seq>.json

containing the dump reason, the active trace, the last-N finished span
records, the journal tail, and a full telemetry snapshot. The chaos
runner asserts recovery scenarios leave enough of these behind that
fault AND recovery are reconstructible from disk alone
(docs/observability.md).

``install()`` chains — it calls the previous ``sys.excepthook`` /
signal handler after dumping, so behavior (exit codes, tracebacks,
KeyboardInterrupt) is unchanged. SIGKILL cannot be caught by design;
for that case the *scheduler* writes the flight record on the dead
child's behalf (scheduler/process.py).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from pathlib import Path
from typing import Any, Dict, Optional

from rafiki_tpu import telemetry
from rafiki_tpu.obs import context
from rafiki_tpu.obs.journal import ENV_VAR, journal

#: Span records kept in a dump (the journal tail is bounded the same).
TAIL_N = 256

_seq_lock = threading.Lock()
_seq = 0
_installed = False
_dumping = False


def _next_seq() -> int:
    global _seq
    with _seq_lock:
        _seq += 1
        return _seq


def dump(reason: str, log_dir: Optional[str | os.PathLike] = None,
         extra: Optional[Dict[str, Any]] = None) -> Optional[Path]:
    """Write one flight record; returns its path, or None when no
    journal directory is known (nowhere durable to write). Never
    raises — this runs on the failure path."""
    global _dumping
    d = log_dir or journal.log_dir or os.environ.get(ENV_VAR)
    if not d:
        return None
    if _dumping:  # re-entrant fatal during a dump: give up quietly
        return None
    _dumping = True
    try:
        payload: Dict[str, Any] = {
            "reason": reason,
            "ts": time.time(),
            "pid": os.getpid(),
            "role": journal.role,
            "trace_id": context.current_trace_id(),
            "spans": telemetry.span_records()[-TAIL_N:],
            "journal_tail": journal.tail(TAIL_N),
            "telemetry": telemetry.snapshot(),
        }
        if extra:
            payload.update(extra)
        d = Path(d)
        d.mkdir(parents=True, exist_ok=True)
        path = d / f"flight-{journal.role}-{os.getpid()}-{_next_seq()}.json"
        tmp = path.with_name(path.name + ".tmp")
        with open(tmp, "w") as f:
            json.dump(payload, f, default=str)
        os.replace(tmp, path)
        # lint: disable=RF014 — flight records are breadcrumbs to the dump files; consumed by humans/grep, not code
        journal.record("flight", reason, path=str(path))
        return path
    except Exception:
        return None  # the failure path must not fail louder
    finally:
        _dumping = False


def install(log_dir: Optional[str | os.PathLike] = None) -> bool:
    """Chain the flight recorder into ``sys.excepthook`` and the
    SIGTERM/SIGINT handlers. Main-thread only (signal API constraint);
    returns False when called from elsewhere or already installed."""
    global _installed
    if _installed or threading.current_thread() is not threading.main_thread():
        return False

    prev_hook = sys.excepthook

    def _hook(exc_type, exc, tb):
        dump(f"fatal:{exc_type.__name__}", log_dir=log_dir)
        prev_hook(exc_type, exc, tb)

    sys.excepthook = _hook

    for signum, label in ((signal.SIGTERM, "sigterm"),
                          (signal.SIGINT, "sigint")):
        prev = signal.getsignal(signum)

        def _handler(sig, frame, _prev=prev, _label=label):
            dump(_label, log_dir=log_dir)
            if callable(_prev):
                _prev(sig, frame)
            else:  # SIG_DFL: restore and re-deliver so the exit
                   # status stays the conventional 128+sig
                signal.signal(sig, signal.SIG_DFL)
                os.kill(os.getpid(), sig)

        signal.signal(signum, _handler)

    _installed = True
    return True
