"""Divergence detection: the host half of the numerics health plane.

A :class:`HealthMonitor` lives on each TrainLoop / PackedTrainLoop and
consumes the epoch-boundary sentinel scalars (obs/health/sentinel.py).
Two trip conditions per trial:

* **nonfinite** — any non-finite gradient/loss element this epoch (or a
  non-finite global grad norm). Trips immediately: NaNs never heal.
* **explosion** — the epoch's max grad norm exceeds ``RAFIKI_HEALTH_K``
  times the trial's running median for ``RAFIKI_HEALTH_HYSTERESIS``
  consecutive epochs, after ``RAFIKI_HEALTH_WARMUP`` clean epochs of
  history. Exploded samples are NOT absorbed into the median, so a slow
  ramp cannot normalize itself out of detection.

On trip the monitor journals ``health/divergence``, bumps
``health.divergences``, charges the trial's banked wall-clock to the
``badput_s`` ledger bucket, dumps a flight record, and (when a
pre-epoch state snapshot is available) writes a replay capsule
(obs/health/capsule.py). Serial loops then raise
:class:`DivergenceError` so the worker fails the trial fast with a
diagnosis; packed loops return per-member verdicts and the pack driver
evicts only the sick member (docs/health.md).

This module is import-light on purpose (stdlib + telemetry + journal +
ledger): it must be importable before the jax backend is pinned.
"""

from __future__ import annotations

import math
import os
import statistics
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from rafiki_tpu import telemetry
from rafiki_tpu.obs.journal import journal
from rafiki_tpu.obs.ledger import ledger

#: Kill switch for the whole plane ("0"/"off" disables detection AND
#: capsules; the in-graph bundle still runs — it is part of the trace).
ENV_ENABLE = "RAFIKI_HEALTH"
#: Grad-norm explosion multiplier over the trial's running median.
ENV_K = "RAFIKI_HEALTH_K"
#: Clean epochs of history required before the explosion arm is live.
ENV_WARMUP = "RAFIKI_HEALTH_WARMUP"
#: Consecutive exploding epochs required to trip (nonfinite ignores this).
ENV_HYSTERESIS = "RAFIKI_HEALTH_HYSTERESIS"
#: "0"/"off" skips the pre-epoch state snapshot + capsule writes while
#: keeping detection/containment live.
ENV_CAPSULE = "RAFIKI_HEALTH_CAPSULE"

DEFAULT_K = 50.0
DEFAULT_WARMUP = 3
DEFAULT_HYSTERESIS = 2
_HISTORY = 32

_STATS: Dict[str, float] = {"divergences": 0, "capsules": 0, "evictions": 0,
                            "contained": 0, "badput_charged_s": 0.0}


def stats() -> Dict[str, float]:
    """The ``health`` telemetry collector payload (process-wide)."""
    out = dict(_STATS)
    out["badput_charged_s"] = round(float(out["badput_charged_s"]), 6)
    return out


def reset_stats() -> None:
    for k in _STATS:
        _STATS[k] = 0.0 if k == "badput_charged_s" else 0


def note_eviction() -> None:
    """A pack member was evicted for divergence (model/base.py)."""
    _STATS["evictions"] += 1
    telemetry.inc("health.evictions")


def note_contained() -> None:
    """A diverged trial was contained by the worker (fail-fast or
    packed skip-and-score-survivors) instead of burning its budget."""
    _STATS["contained"] += 1
    telemetry.inc("health.contained")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _on(name: str) -> bool:
    return os.environ.get(name, "1").strip().lower() not in (
        "0", "off", "false", "no")


class DivergenceError(RuntimeError):
    """A serial trial's numerics diverged; carries the verdict dict
    (kind/bad_step/diagnosis/capsule path) for the worker to surface."""

    def __init__(self, verdict: Dict[str, Any]):
        super().__init__(verdict.get("diagnosis", "numerics diverged"))
        self.verdict = verdict


class _MemberState:
    __slots__ = ("history", "streak", "bank", "tripped")

    def __init__(self) -> None:
        self.history: deque = deque(maxlen=_HISTORY)
        self.streak = 0
        self.bank = 0.0  # wall-clock this trial has consumed so far
        self.tripped = False


class HealthMonitor:
    """Per-loop divergence detector. ``k=0`` is a serial loop (one
    member); ``k>0`` mirrors a pack's live width through
    :meth:`evict_member` / :meth:`admit_member`."""

    def __init__(self, key: str, k: int = 0):
        self.key = str(key)
        self.k = int(k)
        self._members: List[_MemberState] = [
            _MemberState() for _ in range(max(1, self.k))]
        self._ctx: Optional[Dict[str, Any]] = None
        self._seq = 0
        self.enabled = _on(ENV_ENABLE)
        self.capsules_enabled = self.enabled and _on(ENV_CAPSULE)
        self.explosion_k = _env_float(ENV_K, DEFAULT_K)
        self.warmup = max(1, _env_int(ENV_WARMUP, DEFAULT_WARMUP))
        self.hysteresis = max(1, _env_int(ENV_HYSTERESIS, DEFAULT_HYSTERESIS))

    # -- wiring --------------------------------------------------------------

    def set_context(self, **ctx: Any) -> None:
        """Replay context from the model layer: ``model`` identity dict
        (module/qualname/source/knobs), ``train_uri``, ``batch_size``,
        ``seed``, ``planned_steps``; packed packs pass ``member_info``,
        a ``slot -> {knobs, seed}`` callable resolved at trip time."""
        self._ctx = dict(self._ctx or {}, **ctx)

    def _member_ctx(self, member: Optional[int]) -> Dict[str, Any]:
        ctx = dict(self._ctx or {})
        info = ctx.pop("member_info", None)
        if member is not None and callable(info):
            try:
                ctx.update(info(member) or {})
            except Exception:
                pass  # a stale slot must not break the trip path
        return ctx

    def evict_member(self, i: int) -> None:
        if self.k > 0 and 0 <= i < len(self._members):
            self._members.pop(i)
            self.k -= 1

    def admit_member(self) -> None:
        self._members.append(_MemberState())
        self.k += 1

    # -- pre-epoch snapshot --------------------------------------------------

    def snapshot_state(self, state: Any) -> Any:
        """Host copy of the train state BEFORE the epoch dispatches:
        the epoch programs donate their input buffers, so the capsule's
        'state at the start of the bad epoch' must be banked up front.
        Returns None when capsules are off (no copy, no sync)."""
        if not self.capsules_enabled:
            return None
        import jax

        return jax.device_get(state)

    # -- observation ---------------------------------------------------------

    def observe(self, health: Dict[str, float], *, t0: Optional[float] = None,
                epoch_seed: Optional[int] = None, idx: Any = None,
                poison: Any = None, snapshot: Any = None
                ) -> Optional[Dict[str, Any]]:
        """Serial epoch boundary: returns a verdict dict on trip, else
        None. The caller (TrainLoop) raises DivergenceError on it."""
        return self._observe(0, health, t0=t0, epoch_seed=epoch_seed,
                             idx=idx, poison=poison, member_state=snapshot,
                             member=None)

    def observe_pack(self, health_rows: List[Dict[str, float]], *,
                     t0: Optional[float] = None,
                     epoch_seeds: Any = None, idx: Any = None,
                     poison: Any = None, snapshot: Any = None
                     ) -> List[Optional[Dict[str, Any]]]:
        """Packed epoch boundary: one Optional[verdict] per live member.
        ``idx``/``poison`` are the (n_steps, k, ...) epoch arrays; the
        snapshot is the stacked pre-epoch host state (sliced per sick
        member only on trip)."""
        verdicts: List[Optional[Dict[str, Any]]] = []
        for j, health in enumerate(health_rows):
            member_state = None
            if snapshot is not None and self._would_trip(j, health):
                import jax

                member_state = jax.tree.map(
                    lambda a: a[j] if getattr(a, "ndim", 0) else a, snapshot)
            verdicts.append(self._observe(
                j, health, t0=t0,
                epoch_seed=(epoch_seeds[j] if epoch_seeds is not None else None),
                idx=(idx[:, j] if idx is not None else None),
                poison=(poison[:, j] if poison is not None else None),
                member_state=member_state, member=j))
        return verdicts

    def _classify(self, st: _MemberState,
                  health: Dict[str, float]) -> Optional[str]:
        """Pure trip decision against CURRENT detector state; does not
        mutate. 'explosion' means the streak including this epoch would
        reach the hysteresis bar."""
        gn = float(health.get("health_grad_norm", 0.0))
        nf = int(health.get("health_nonfinite", 0))
        if nf > 0 or not math.isfinite(gn):
            return "nonfinite"
        if len(st.history) >= self.warmup:
            median = statistics.median(st.history)
            if median > 0.0 and gn > self.explosion_k * median:
                if st.streak + 1 >= self.hysteresis:
                    return "explosion"
        return None

    def _would_trip(self, j: int, health: Dict[str, float]) -> bool:
        if not self.enabled or not health:
            return False
        st = self._members[j]
        return (not st.tripped) and self._classify(st, health) is not None

    def _observe(self, j: int, health: Dict[str, float], *, t0, epoch_seed,
                 idx, poison, member_state, member
                 ) -> Optional[Dict[str, Any]]:
        if not self.enabled or not health:
            return None
        st = self._members[j]
        if t0 is not None:
            # This module is telemetry-adjacent plumbing (obs/ is exempt
            # from the RF007 monotonic-delta rule): the bank is the
            # wall-clock a divergence retroactively turns into badput.
            st.bank += (time.monotonic() - t0) / max(1, self.k or 1)
        if st.tripped:
            return None
        kind = self._classify(st, health)
        gn = float(health.get("health_grad_norm", 0.0))
        if kind is None:
            if (len(st.history) >= self.warmup
                    and statistics.median(st.history) > 0.0
                    and gn > self.explosion_k * statistics.median(st.history)):
                st.streak += 1  # above the bar but under the hysteresis
            else:
                st.streak = 0
                st.history.append(gn)
            return None
        return self._trip(st, kind, health, epoch_seed=epoch_seed, idx=idx,
                          poison=poison, member_state=member_state,
                          member=member)

    # -- the trip path -------------------------------------------------------

    def _diagnosis(self, kind: str, st: _MemberState,
                   health: Dict[str, float]) -> str:
        gn = float(health.get("health_grad_norm", float("nan")))
        if kind == "nonfinite":
            return (f"non-finite numerics at step "
                    f"{int(health.get('health_bad_step', -1))}: "
                    f"{int(health.get('health_nonfinite', 0))} bad elements, "
                    f"grad_norm={gn:.4g}")
        median = statistics.median(st.history) if st.history else 0.0
        return (f"grad-norm explosion: {gn:.4g} > {self.explosion_k:g}x "
                f"running median {median:.4g} "
                f"({self.hysteresis} consecutive epochs)")

    def _trip(self, st: _MemberState, kind: str, health: Dict[str, float], *,
              epoch_seed, idx, poison, member_state, member
              ) -> Dict[str, Any]:
        st.tripped = True
        bad_step = int(health.get("health_bad_step", -1))
        capsule_path = None
        if self.capsules_enabled and member_state is not None and self._ctx:
            try:
                from rafiki_tpu.obs.health import capsule as capsule_mod

                capsule_path = capsule_mod.write(
                    self, member=member, kind=kind, health=health,
                    epoch_seed=epoch_seed, idx=idx, poison=poison,
                    state=member_state, seq=self._seq)
                self._seq += 1
            except Exception as e:  # capsules must never kill training
                journal.record("health", "capsule_error", key=self.key,
                               error=f"{type(e).__name__}: {e}")
        if capsule_path is not None:
            _STATS["capsules"] += 1
            telemetry.inc("health.capsules")
        wasted = st.bank
        if wasted > 0.0:
            # The trial's whole wall so far is retroactively badput: the
            # epochs "succeeded" but computed garbage. Overlaps the
            # step_s/compile_s charges by design — same convention as
            # chaos-injected downtime_s (docs/observability.md).
            ledger.add("badput_s", wasted)
            _STATS["badput_charged_s"] += wasted
        _STATS["divergences"] += 1
        telemetry.inc("health.divergences")
        verdict = {
            "divergence": kind,
            "key": self.key,
            "member": member,
            "bad_step": bad_step,
            "grad_norm": float(health.get("health_grad_norm", float("nan"))),
            "update_norm": float(health.get("health_update_norm",
                                            float("nan"))),
            "nonfinite": int(health.get("health_nonfinite", 0)),
            "badput_s": round(wasted, 6),
            "capsule": str(capsule_path) if capsule_path else None,
            "diagnosis": self._diagnosis(kind, st, health),
        }
        journal.record("health", "divergence", **verdict)
        from rafiki_tpu.obs import recorder

        recorder.dump("health:divergence", extra={"health": verdict})
        return verdict
