"""Numerics health plane (docs/health.md).

Three layers, one per module:

* ``sentinel``  — the in-graph half: a cheap per-step health bundle
  (loss finiteness, grad/update/param norms, non-finite counts) folded
  into the jitted donated epoch programs, reduced on-device, fetched
  once per epoch. Pure jax; imported lazily so this package stays
  importable before the backend is pinned.
* ``detector``  — the host half: per-trial divergence detection
  (NaN/Inf trips immediately, grad-norm explosion trips with
  hysteresis), journaling, badput charging, flight records, and the
  :class:`DivergenceError` contract serial workers fail fast on.
* ``capsule``   — replay capsules: atomic dumps of the pre-epoch state
  + offending batch ids, re-executed and bit-verified by
  ``python -m rafiki_tpu.obs replay <capsule>``.

The ``health`` telemetry collector (divergences / capsules / evictions
/ contained / badput charged) registers on import; ``ops.train``
imports this package, so the collector is live wherever training is.
"""

from __future__ import annotations

import importlib

from rafiki_tpu import telemetry
from rafiki_tpu.obs.health.detector import (  # noqa: F401
    DEFAULT_HYSTERESIS, DEFAULT_K, DEFAULT_WARMUP, ENV_CAPSULE, ENV_ENABLE,
    ENV_HYSTERESIS, ENV_K, ENV_WARMUP, DivergenceError, HealthMonitor,
    note_contained, note_eviction, reset_stats, stats)

telemetry.register_collector("health", stats)


def __getattr__(name: str):
    # sentinel/capsule import jax at module scope; loading them lazily
    # keeps `import rafiki_tpu.obs.health` safe before
    # honor_env_platform() has pinned the backend.
    if name in ("sentinel", "capsule"):
        return importlib.import_module(f"rafiki_tpu.obs.health.{name}")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
