"""Divergence replay capsules: freeze the bad step, re-execute it, and
verify bit-reproduction.

A capsule is an atomically-written pickle (``capsule-<pid>-<seq>.rcap``
in the journal directory) banked by the HealthMonitor at trip time. It
carries everything a fresh process needs to re-run the divergent epoch
prefix deterministically:

* the full train state ``(params, opt_state, step, rng, hyper)`` as it
  was BEFORE the bad epoch (packed members are sliced to serial shape —
  the pack invariant makes the serial re-execution bit-identical),
  serialized with ``utils.serial.dump_pytree`` at full precision;
* the offending batch-id rows (the epoch's shuffled index matrix,
  truncated at the first bad step) and the chaos poison column, if any
  (an injected fault must be re-applied for the replay to reproduce);
* the model's identity — import path, knobs, and the uploaded source
  bytes when it was loaded via ``load_model_class`` — plus the train
  dataset URI and batch size.

``python -m rafiki_tpu.obs replay <capsule>`` rebuilds the model,
restores the state, re-runs the truncated epoch through the SAME jitted
program and compares the at-bad-step sentinel values bit-for-bit
(f32 payloads compared as uint32 views; NaNs compare equal at the bit
level). Exit 0 means the divergence is deterministic and the capsule is
a faithful repro; anything else is itself a finding (docs/health.md).
"""

from __future__ import annotations

import os
import pickle
import time
from pathlib import Path
from typing import Any, Dict, Optional

import numpy as np

VERSION = 1
SUFFIX = ".rcap"

#: The sentinel keys replay must reproduce bit-exactly for every
#: capsule kind. ``bad_*`` values are taken AT the first bad step
#: (step 0 for a clean/explosion epoch), so they are well-defined for
#: truncated and full-epoch replays alike.
_ALWAYS_KEYS = ("health_bad_step", "health_bad_nonfinite",
                "health_bad_grad_norm", "health_bad_update_norm")
#: Extra keys compared when the replay covers the FULL epoch
#: (explosion capsules, bad_step < 0): whole-epoch reductions only
#: match when the replayed step count matches the observed one.
_FULL_EPOCH_KEYS = ("health_grad_norm", "health_update_norm",
                    "health_param_norm", "health_nonfinite")
_INT_KEYS = ("health_bad_step", "health_bad_nonfinite", "health_nonfinite")


def f32_bits(x: float) -> int:
    """The uint32 bit pattern of ``x`` as an f32 — the equality domain
    for replay verification (float() round-trips f32 exactly, and NaN
    bit patterns compare equal where NaN floats would not)."""
    return int(np.float32(x).view(np.uint32))


def _resolve_dir() -> Optional[Path]:
    from rafiki_tpu.obs.journal import ENV_VAR, journal

    d = journal.log_dir or os.environ.get(ENV_VAR)
    return Path(d) if d else None


def write(monitor: Any, *, member: Optional[int], kind: str,
          health: Dict[str, float], epoch_seed: Optional[int], idx: Any,
          poison: Any, state: Any, seq: int) -> Optional[Path]:
    """Bank one capsule; returns its path or None (no journal dir /
    no model context). Called from the HealthMonitor trip path, which
    guards with try/except — a capsule failure never kills training."""
    ctx = monitor._member_ctx(member)
    model = ctx.get("model")
    d = _resolve_dir()
    if d is None or not model:
        return None
    from rafiki_tpu.utils.serial import dump_pytree

    bad_step = int(health.get("health_bad_step", -1))
    if idx is not None:
        idx = np.asarray(idx, np.int32)
        if bad_step >= 0:
            idx = idx[: bad_step + 1]
    if poison is not None:
        poison = np.asarray(poison, np.float32)
        if bad_step >= 0:
            poison = poison[: bad_step + 1]
    import jax

    payload = {
        "version": VERSION,
        "created_ts": time.time(),
        # Capture-environment fingerprint: replay compares builds, not
        # just bits, when diagnosing a non-reproducing capsule
        # (docs/health.md#non-reproducing-capsules).
        "platform": jax.default_backend(),
        "jax_version": jax.__version__,
        "kind": kind,
        "perf_key": monitor.key,
        "member": member,
        "packed": member is not None,
        "bad_step": bad_step,
        "observed": {k: float(v) for k, v in health.items()},
        "epoch_seed": None if epoch_seed is None else int(epoch_seed),
        "idx": idx,
        "poison": poison,
        "state_packed": dump_pytree(state, cast_f32_to_bf16=False),
        "model": dict(model),
        "train_uri": ctx.get("train_uri"),
        "batch_size": ctx.get("batch_size"),
        "seed": ctx.get("seed", 0),
        "planned_steps": ctx.get("planned_steps"),
    }
    d.mkdir(parents=True, exist_ok=True)
    path = d / f"capsule-{os.getpid()}-{seq}{SUFFIX}"
    tmp = path.with_name(path.name + ".tmp")
    with open(tmp, "wb") as f:
        pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, path)
    from rafiki_tpu.obs.journal import journal

    journal.record("health", "capsule", path=str(path), divergence=kind,
                   member=member, bad_step=bad_step)
    return path


def load(path: str | os.PathLike) -> Dict[str, Any]:
    with open(path, "rb") as f:
        cap = pickle.load(f)
    if not isinstance(cap, dict) or cap.get("version") != VERSION:
        raise ValueError(f"{path}: not a v{VERSION} rafiki health capsule")
    return cap


def _rebuild_model(cap: Dict[str, Any]):
    """Re-instantiate the diverged trial's model template: from the
    embedded uploaded source when it was a ``load_model_class`` model,
    else by ordinary import of the recorded module path."""
    m = cap["model"]
    if m.get("source"):
        from rafiki_tpu.model.base import load_model_class

        cls = load_model_class(m["source"], m["qualname"].split(".")[0])
    else:
        import functools
        import importlib

        mod = importlib.import_module(m["module"])
        cls = functools.reduce(getattr, m["qualname"].split("."), mod)
    return cls(**(m.get("knobs") or {}))


def replay(path: str | os.PathLike) -> Dict[str, Any]:
    """Re-execute a capsule's divergent epoch prefix and bit-compare
    the sentinel surface. Returns a verdict document (JSON-able)."""
    import jax
    import jax.numpy as jnp
    from flax import serialization

    cap = load(path)
    model = _rebuild_model(cap)
    ds = model._prepared_dataset(cap["train_uri"])
    num_classes, input_shape = model._dataset_arch(ds)
    if cap.get("planned_steps"):
        model._planned_steps = cap["planned_steps"]
    model._build_loop(num_classes, input_shape)
    loop = model._loop

    from rafiki_tpu.ops.train import get_device_dataset
    from rafiki_tpu.utils.serial import load_pytree

    template = loop.state
    raw = load_pytree(cap["state_packed"])
    state = serialization.from_state_dict(template, raw)
    state = jax.tree.map(
        lambda t, v: jnp.asarray(v, jnp.asarray(t).dtype), template, state)

    X, Y = get_device_dataset(ds)
    idx = cap.get("idx")
    if idx is None:
        raise ValueError(f"{path}: capsule carries no batch indices "
                         "(trial ran outside the device-resident fast "
                         "path); replay is not supported")
    idx = jnp.asarray(np.asarray(idx, np.int32))
    poison = cap.get("poison")
    if poison is not None:
        poison = jnp.asarray(np.asarray(poison, np.float32))
    _, metrics = loop.program.train_epoch(jax.device_put(state), X, Y,
                                          idx, poison)
    got = {k: float(v) for k, v in metrics.items()
           if k.startswith("health_")}

    expected = cap["observed"]
    keys = list(_ALWAYS_KEYS)
    if cap["bad_step"] < 0:
        keys += list(_FULL_EPOCH_KEYS)
    mismatches = []
    comparisons = {}
    for k in keys:
        if k in _INT_KEYS:
            e, g = int(expected[k]), int(got[k])
            ok = e == g
            comparisons[k] = {"expected": e, "got": g, "match": ok}
        else:
            e, g = f32_bits(expected[k]), f32_bits(got[k])
            ok = e == g
            comparisons[k] = {"expected": float(np.float32(expected[k])),
                              "got": float(np.float32(got[k])),
                              "expected_bits": f"{e:08x}",
                              "got_bits": f"{g:08x}", "match": ok}
        if not ok:
            mismatches.append(k)
    return {
        "capsule": str(path),
        "kind": cap["kind"],
        "bad_step": cap["bad_step"],
        "member": cap.get("member"),
        "steps_replayed": int(idx.shape[0]),
        "poisoned": poison is not None,
        # Environment fingerprints: a NOT-reproduced verdict across
        # differing builds is expected, not alarming
        # (docs/health.md#non-reproducing-capsules).
        "captured_env": {"platform": cap.get("platform"),
                         "jax_version": cap.get("jax_version")},
        "replay_env": {"platform": jax.default_backend(),
                       "jax_version": jax.__version__},
        "comparisons": comparisons,
        "reproduced": not mismatches,
        "mismatches": mismatches,
    }
