"""In-graph numerics sentinels: the traced half of the health plane.

Everything here runs INSIDE the jitted, donated epoch programs
(``ops.train.Program.train_epoch`` and the vmapped packed variant):
:func:`bundle` folds a cheap health reduction into every train step's
metric dict, and :func:`reduce_epoch` collapses the per-step series to
one fixed set of epoch-boundary scalars — the only values that ever
cross to the host, and only once per epoch.

Design constraints (docs/health.md):

* **Bit-neutrality.** The bundle only *reads* loss/grads/updates/params;
  it never touches the rng chain or the update math, so params with the
  sentinel enabled are bit-identical to params without it — and a packed
  member stays bit-identical to its serial twin.
* **Always on.** The bundle is unconditionally part of the trace, so a
  program's cache key is unchanged and every cached program carries the
  same metric structure (no health-on/health-off retrace forks).
* **No per-step host sync.** All outputs are device scalars reduced by
  the same ``lax.scan`` that runs the epoch; the host fetches the
  reduced dict at the epoch boundary it already syncs on.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

#: Metric-dict key prefix for sentinel outputs. ``ops.train`` strips
#: these from caller-visible epoch metrics (the JaxModel/logger contract
#: predates the health plane) and routes them to the HealthMonitor.
PREFIX = "health_"


def _sq_sum(tree: Any) -> jax.Array:
    total = jnp.zeros((), jnp.float32)
    for leaf in jax.tree.leaves(tree):
        f = leaf.astype(jnp.float32)
        total = total + jnp.sum(f * f)
    return total


def _nonfinite(tree: Any) -> jax.Array:
    total = jnp.zeros((), jnp.int32)
    for leaf in jax.tree.leaves(tree):
        total = total + jnp.sum(~jnp.isfinite(leaf)).astype(jnp.int32)
    return total


def bundle(loss: jax.Array, grads: Any, updates: Any,
           params: Any) -> Dict[str, jax.Array]:
    """Per-step health stats as one fused reduction over the step's
    already-materialized intermediates: global grad/update/param
    L2 norms (f32 accumulation regardless of leaf dtype) and the count
    of non-finite elements across the gradients and the loss."""
    return {
        "health_grad_norm": jnp.sqrt(_sq_sum(grads)),
        "health_update_norm": jnp.sqrt(_sq_sum(updates)),
        "health_param_norm": jnp.sqrt(_sq_sum(params)),
        "health_nonfinite": (_nonfinite(grads)
                             + jnp.sum(~jnp.isfinite(loss)).astype(jnp.int32)),
    }


def split(metrics: Dict[str, Any]) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Partition a metric dict into (caller-visible, health) halves."""
    rest = {k: v for k, v in metrics.items() if not k.startswith(PREFIX)}
    health = {k: v for k, v in metrics.items() if k.startswith(PREFIX)}
    return rest, health


def reduce_epoch(series: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    """Epoch-boundary reduction of the per-step sentinel series.

    Handles both the serial shape ``(n_steps,)`` and the packed shape
    ``(n_steps, k)`` — the dispatch is on static ndim, never a traced
    branch. Outputs, per trial:

    * ``health_nonfinite``   — total non-finite elements this epoch
    * ``health_grad_norm``   — max step grad norm (NaN-propagating)
    * ``health_update_norm`` — max step update norm
    * ``health_param_norm``  — post-update param norm at the last step
    * ``health_bad_step``    — first step with non-finite numerics, -1
      if the epoch was clean
    * ``health_bad_*``       — grad/update norm and non-finite count AT
      the first bad step (step 0 when clean; ignore when bad_step < 0).
      These are the bit-reproduction surface ``obs replay`` verifies.
    """
    nf = series["health_nonfinite"]
    bad = nf > 0
    any_bad = bad.any(axis=0)
    at = jnp.argmax(bad, axis=0).astype(jnp.int32)  # 0 when clean
    first_bad = jnp.where(any_bad, at, jnp.int32(-1))

    def _at_bad(v: jax.Array) -> jax.Array:
        if v.ndim == 1:
            return v[at]
        return jnp.take_along_axis(v, at[None, :], axis=0)[0]

    gn = series["health_grad_norm"]
    un = series["health_update_norm"]
    return {
        "health_nonfinite": nf.sum(axis=0),
        "health_grad_norm": gn.max(axis=0),
        "health_update_norm": un.max(axis=0),
        "health_param_norm": series["health_param_norm"][-1],
        "health_bad_step": first_bad,
        "health_bad_grad_norm": _at_bad(gn),
        "health_bad_update_norm": _at_bad(un),
        "health_bad_nonfinite": _at_bad(nf),
    }
