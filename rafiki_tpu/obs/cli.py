"""``python -m rafiki_tpu.obs`` — read the merged cross-process journals.

Subcommands (all read ``journal-*.jsonl*`` under ``--dir``, default
``$RAFIKI_LOG_DIR`` then the configured ``logs_dir``):

    trace <id>     every record carrying the trace id (prefix match),
                   time-ordered across processes, one line per hop —
                   the stitched end-to-end view of one query or trial
    tail [-n N]    the last N records fleet-wide
    slowest [-n N] the N slowest finished spans
    profile [key]  per-program roofline join: XLA cost model
                   (``perf/cost``) x observed step times (``perf/step``)
                   -> achieved FLOP/s, MFU, arithmetic intensity
                   (docs/perf.md); ``key`` prefix-matches the program
                   key hash or substring-matches the key repr
    slo            current SLO burn state (latest ``slo/state``) plus
                   the breach/recovery history
    health         numerics health: every ``health/divergence`` verdict
                   with its diagnosis and capsule, plus totals
                   (docs/health.md)
    curves [id]    per-trial learning curves from the durable
                   ``trial/epoch_eval`` records; ``id`` prefix-matches
                   trial ids (omit for every trial)
    replay <cap>   re-execute a divergence capsule and bit-verify the
                   reproduction; exit 0 iff the bad step reproduced
                   bit-exactly

Output is one human line per record by default, ``--json`` for JSONL
(pipe into jq). Exit code 1 when a requested trace has no records.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

from rafiki_tpu.obs import journal as journal_mod


def _default_dir() -> str:
    d = os.environ.get(journal_mod.ENV_VAR)
    if d:
        return d
    from rafiki_tpu.config import get_config
    return str(get_config().logs_dir)


def _fmt_record(rec: Dict[str, Any], t0: float) -> str:
    dt = rec.get("ts", 0.0) - t0
    who = f"{rec.get('role', '?')}/{rec.get('pid', '?')}"
    head = f"+{dt:9.3f}s  {who:<18} {rec.get('kind', '?'):<7} {rec.get('name', '?')}"
    parts = []
    if rec.get("dur_s") is not None:
        parts.append(f"dur={rec['dur_s']:.4f}s")
    for k in ("trial_id", "worker_id", "query_id", "site", "mode", "event",
              "reason", "path", "error"):
        if rec.get(k) is not None:
            parts.append(f"{k}={rec[k]}")
    tags = rec.get("tags")
    if isinstance(tags, dict):
        parts.extend(f"{k}={v}" for k, v in tags.items())
    return head + ("  [" + " ".join(parts) + "]" if parts else "")


def _emit(records: List[Dict[str, Any]], as_json: bool) -> None:
    if as_json:
        for rec in records:
            print(json.dumps(rec, default=str))
        return
    t0 = records[0].get("ts", 0.0) if records else 0.0
    for rec in records:
        print(_fmt_record(rec, t0))


def cmd_trace(log_dir: str, trace_id: str, as_json: bool) -> int:
    records = [r for r in journal_mod.read_dir(log_dir)
               if str(r.get("trace_id", "")).startswith(trace_id)]
    if not records:
        print(f"no journal records for trace {trace_id!r} under {log_dir}",
              file=sys.stderr)
        return 1
    _emit(records, as_json)
    if not as_json:
        pids = {(r.get("role"), r.get("pid")) for r in records}
        wall = records[-1].get("ts", 0.0) - records[0].get("ts", 0.0)
        print(f"-- trace {records[0].get('trace_id')}: {len(records)} records "
              f"across {len(pids)} processes, {wall:.3f}s")
    return 0


def cmd_tail(log_dir: str, n: int, as_json: bool) -> int:
    _emit(journal_mod.read_dir(log_dir)[-n:], as_json)
    return 0


def cmd_slowest(log_dir: str, n: int, as_json: bool) -> int:
    spans = [r for r in journal_mod.read_dir(log_dir)
             if r.get("kind") == "span" and r.get("dur_s") is not None]
    spans.sort(key=lambda r: r["dur_s"], reverse=True)
    _emit(spans[:n], as_json)
    return 0


def cmd_profile(log_dir: str, key: Optional[str], as_json: bool,
                peak_flops: Optional[float]) -> int:
    """Join perf/cost x perf/step journal records into per-program
    MFU/roofline rows (the cross-process sibling of the live ``perf``
    telemetry collector)."""
    records = journal_mod.read_dir(log_dir)
    costs: Dict[str, Dict[str, Any]] = {}
    steps: Dict[str, List[float]] = {}
    colds: Dict[str, List[float]] = {}
    for r in records:
        if r.get("kind") != "perf":
            continue
        h = r.get("key_hash")
        if not h:
            continue
        if r.get("name") == "cost":
            costs[h] = r  # latest wins: re-captures supersede
        elif r.get("name") == "step":
            dt = r.get("dt")
            if dt is None:
                continue
            (colds if r.get("cold") else steps).setdefault(h, []).append(
                float(dt) - float(r.get("feed_s") or 0.0))
    hashes = sorted(set(costs) | set(steps) | set(colds))
    if key:
        hashes = [h for h in hashes
                  if h.startswith(key) or key in str(costs.get(h, {}).get("key", ""))]
    if not hashes:
        print(f"no perf records{f' matching {key!r}' if key else ''} "
              f"under {log_dir}", file=sys.stderr)
        return 1
    if peak_flops is None:
        from rafiki_tpu.obs.perf import profiler
        peak_flops = profiler.PEAK_FLOPS_V5E_BF16
    rows = []
    for h in hashes:
        c = costs.get(h, {})
        warm = sorted(steps.get(h, []))
        row: Dict[str, Any] = {
            "key_hash": h,
            "key": c.get("key"),
            "kind": c.get("program_kind"),
            "k": c.get("k"),
            "flops": c.get("flops"),
            "bytes_accessed": c.get("bytes_accessed"),
            "peak_hbm_bytes": c.get("peak_hbm_bytes"),
            "epochs": len(warm),
            "cold_epochs": len(colds.get(h, [])),
        }
        if warm:
            row["step_p50_s"] = warm[len(warm) // 2]
            row["step_min_s"] = warm[0]
        if c.get("flops") and c.get("bytes_accessed"):
            row["arith_intensity"] = c["flops"] / c["bytes_accessed"]
        if c.get("flops") and warm:
            row["achieved_flops_s"] = c["flops"] / row["step_p50_s"]
            # MFU claims a hardware peak: only meaningful when the
            # steps ran on an accelerator. The journal can't know, so
            # the report states its basis instead of guessing.
            row["mfu_vs_peak"] = row["achieved_flops_s"] / peak_flops
            row["peak_flops_basis"] = peak_flops
        rows.append(row)
    if as_json:
        print(json.dumps({"programs": rows}, default=str))
        return 0
    for row in rows:
        print(f"program {row['key_hash']}  kind={row['kind'] or '?'} "
              f"k={row['k'] or '?'} epochs={row['epochs']}"
              f" (+{row['cold_epochs']} cold)")
        if row.get("key"):
            print(f"  key: {row['key']}")
        if row.get("flops"):
            print(f"  cost model: {row['flops']:.3e} flops, "
                  f"{row.get('bytes_accessed') or 0:.3e} bytes"
                  + (f", AI={row['arith_intensity']:.2f} flops/byte"
                     if row.get("arith_intensity") else ""))
        if row.get("step_p50_s") is not None:
            print(f"  observed: p50 step {row['step_p50_s'] * 1e3:.3f}ms "
                  f"(min {row['step_min_s'] * 1e3:.3f}ms)")
        if row.get("achieved_flops_s"):
            print(f"  achieved: {row['achieved_flops_s']:.3e} FLOP/s "
                  f"-> MFU {row['mfu_vs_peak'] * 100:.4f}% of "
                  f"{row['peak_flops_basis']:.3g} peak")
    return 0


def cmd_slo(log_dir: str, as_json: bool) -> int:
    """Latest slo/state snapshot + full breach/recovery history."""
    records = journal_mod.read_dir(log_dir)
    state = None
    breaches: List[Dict[str, Any]] = []
    recoveries: List[Dict[str, Any]] = []
    for r in records:
        if r.get("kind") != "slo":
            continue
        if r.get("name") == "state":
            state = r
        elif r.get("name") == "breach":
            breaches.append(r)
        elif r.get("name") == "recover":
            recoveries.append(r)
    if state is None and not breaches:
        print(f"no slo records under {log_dir} (is the engine ticking? "
              f"see docs/perf.md)", file=sys.stderr)
        return 1
    if as_json:
        print(json.dumps({"state": state, "breaches": breaches,
                          "recoveries": recoveries}, default=str))
        return 0
    if state is not None:
        print(f"slo state @ ts={state.get('ts')}:")
        for name, st in sorted((state.get("state") or {}).items()):
            mark = "BREACH" if st.get("breaching") else "ok"
            val = st.get("value")
            burn = st.get("burn")
            print(f"  {name:<24} {mark:<7} value="
                  f"{'n/a' if val is None else format(val, '.4g')} "
                  f"threshold={st.get('threshold')}"
                  + (f" burn={burn:.2f}x" if burn is not None else ""))
    print(f"breaches: {len(breaches)}, recoveries: {len(recoveries)}")
    for b in breaches[-8:]:
        print(f"  ts={b.get('ts')} {b.get('slo')} value={b.get('value')} "
              f"threshold={b.get('threshold')} ({b.get('source')})")
    return 0


def cmd_health(log_dir: str, as_json: bool) -> int:
    """Numerics health report: divergence verdicts + capsule inventory
    from the ``health/*`` journal records (docs/health.md). An empty
    report is a PASS — exit 0 with a clean bill, unlike trace/curves
    where absence means the query missed."""
    records = journal_mod.read_dir(log_dir)
    divergences: List[Dict[str, Any]] = []
    capsules: List[Dict[str, Any]] = []
    errors: List[Dict[str, Any]] = []
    for r in records:
        if r.get("kind") != "health":
            continue
        if r.get("name") == "divergence":
            divergences.append(r)
        elif r.get("name") == "capsule":
            capsules.append(r)
        elif r.get("name") == "capsule_error":
            errors.append(r)
    if as_json:
        print(json.dumps({"divergences": divergences, "capsules": capsules,
                          "capsule_errors": errors}, default=str))
        return 0
    if not divergences and not errors:
        print(f"no divergences under {log_dir} — numerically clean")
        return 0
    print(f"divergences: {len(divergences)}, capsules: {len(capsules)}, "
          f"capsule write errors: {len(errors)}")
    for d in divergences:
        member = d.get("member")
        where = f" member={member}" if member is not None else ""
        cap = d.get("capsule")
        print(f"  ts={d.get('ts')} {d.get('divergence', '?'):<10}"
              f"{where} bad_step={d.get('bad_step')} "
              f"badput={d.get('badput_s')}s")
        print(f"    {d.get('diagnosis', '?')}")
        if cap:
            print(f"    capsule: {cap}")
    for e in errors:
        print(f"  capsule write FAILED: {e.get('error')}")
    return 0


def cmd_curves(log_dir: str, trial: Optional[str], as_json: bool) -> int:
    """Learning-curve surfacing: replay the durable ``trial/epoch_eval``
    records into per-trial curves (the journal half of what the sqlite
    trial log holds per process)."""
    curves: Dict[str, List[Dict[str, Any]]] = {}
    for r in journal_mod.read_dir(log_dir):
        if r.get("kind") != "trial" or r.get("name") != "epoch_eval":
            continue
        tid = str(r.get("trial_id", "?"))
        if trial and not tid.startswith(trial):
            continue
        curves.setdefault(tid, []).append(r)
    if not curves:
        print(f"no epoch_eval records"
              f"{f' for trial {trial!r}' if trial else ''} under {log_dir}",
              file=sys.stderr)
        return 1
    for tid in curves:
        curves[tid].sort(key=lambda r: (r.get("epoch", 0), r.get("ts", 0.0)))
    if as_json:
        print(json.dumps({"trials": curves}, default=str))
        return 0
    for tid, rows in sorted(curves.items()):
        last = rows[-1]
        packed = " [packed]" if last.get("packed") else ""
        print(f"trial {tid}{packed}: {len(rows)} epochs, "
              f"final score={last.get('score')}")
        for r in rows:
            vals = []
            for k in ("loss", "acc"):
                if r.get(k) is not None:
                    vals.append(f"{k}={r[k]:.6g}")
            if r.get("wall_s") is not None:
                vals.append(f"wall={r['wall_s']:.3f}s")
            print(f"  epoch {r.get('epoch'):>3}  " + " ".join(vals))
    return 0


def cmd_replay(path: str, as_json: bool) -> int:
    """Re-execute a divergence capsule and report the bit-comparison.
    Exit 0 only when every compared sentinel value reproduced exactly —
    the determinism contract scripts/health_smoke.py enforces."""
    from rafiki_tpu.obs.health import capsule

    try:
        result = capsule.replay(path)
    except (FileNotFoundError, ValueError) as e:
        print(f"replay failed: {e}", file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps(result, default=str))
        return 0 if result["reproduced"] else 1
    member = result.get("member")
    where = f" member={member}" if member is not None else ""
    print(f"capsule {result['capsule']}: {result['kind']}{where} "
          f"bad_step={result['bad_step']} "
          f"steps_replayed={result['steps_replayed']}"
          + (" (poisoned)" if result["poisoned"] else ""))
    for k, c in result["comparisons"].items():
        mark = "ok " if c["match"] else "DIFF"
        bits = (f" [{c['expected_bits']} vs {c['got_bits']}]"
                if "expected_bits" in c else "")
        print(f"  {mark} {k:<26} expected={c['expected']} "
              f"got={c['got']}{bits}")
    if result["reproduced"]:
        print("reproduced: the divergent step re-executed bit-exactly")
        return 0
    print(f"NOT reproduced: {', '.join(result['mismatches'])} diverged "
          f"from the observed run — the failure is not deterministic "
          f"under replay (docs/health.md#non-reproducing-capsules)")
    cap_env = result.get("captured_env") or {}
    rep_env = result.get("replay_env") or {}
    if cap_env != rep_env:
        print(f"  note: captured on {cap_env}, replayed on {rep_env} — "
              f"a build/backend mismatch changes XLA fusion and rounding")
    return 1


def main(argv: Optional[List[str]] = None) -> int:
    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()  # profile's peak-flops default imports the
    # profiler package; pin the platform before anything can touch jax.
    p = argparse.ArgumentParser(
        prog="python -m rafiki_tpu.obs",
        description="merge and query the per-process observability journals")
    p.add_argument("--dir", default=None,
                   help="journal directory (default: $RAFIKI_LOG_DIR, "
                        "then the configured logs_dir)")
    p.add_argument("--json", action="store_true",
                   help="emit raw JSONL instead of formatted lines")
    sub = p.add_subparsers(dest="cmd", required=True)
    sp = sub.add_parser("trace", help="stitch one trace across processes")
    sp.add_argument("trace_id")
    sp = sub.add_parser("tail", help="last N records fleet-wide")
    sp.add_argument("-n", type=int, default=32)
    sp = sub.add_parser("slowest", help="N slowest spans")
    sp.add_argument("-n", type=int, default=16)
    sp = sub.add_parser("profile",
                        help="per-program cost model x step-time join")
    sp.add_argument("key", nargs="?", default=None,
                    help="program key-hash prefix or key substring")
    sp.add_argument("--peak-flops", type=float, default=None,
                    help="MFU denominator (default: v5e bf16 peak)")
    sub.add_parser("slo", help="current SLO burn state + breach history")
    sub.add_parser("health",
                   help="numerics divergences + replay capsule inventory")
    sp = sub.add_parser("curves",
                        help="per-trial learning curves from the journals")
    sp.add_argument("trial", nargs="?", default=None,
                    help="trial id prefix (omit for all trials)")
    sp = sub.add_parser("replay",
                        help="re-execute a divergence capsule, bit-verify")
    sp.add_argument("capsule", help="path to a capsule-*.rcap file")
    args = p.parse_args(argv)

    if args.cmd == "replay":
        # No journal dir needed: the capsule is self-contained.
        return cmd_replay(args.capsule, args.json)
    log_dir = args.dir or _default_dir()
    if args.cmd == "trace":
        return cmd_trace(log_dir, args.trace_id, args.json)
    if args.cmd == "tail":
        return cmd_tail(log_dir, args.n, args.json)
    if args.cmd == "profile":
        return cmd_profile(log_dir, args.key, args.json, args.peak_flops)
    if args.cmd == "slo":
        return cmd_slo(log_dir, args.json)
    if args.cmd == "health":
        return cmd_health(log_dir, args.json)
    if args.cmd == "curves":
        return cmd_curves(log_dir, args.trial, args.json)
    return cmd_slowest(log_dir, args.n, args.json)
