"""``python -m rafiki_tpu.obs`` — read the merged cross-process journals.

Subcommands (all read ``journal-*.jsonl*`` under ``--dir``, default
``$RAFIKI_LOG_DIR`` then the configured ``logs_dir``):

    trace <id>     every record carrying the trace id (prefix match),
                   time-ordered across processes, one line per hop —
                   the stitched end-to-end view of one query or trial
    tail [-n N]    the last N records fleet-wide
    slowest [-n N] the N slowest finished spans
    profile [key]  per-program roofline join: XLA cost model
                   (``perf/cost``) x observed step times (``perf/step``)
                   -> achieved FLOP/s, MFU, arithmetic intensity
                   (docs/perf.md); ``key`` prefix-matches the program
                   key hash or substring-matches the key repr
    slo            current SLO burn state (latest ``slo/state``) plus
                   the breach/recovery history
    health         numerics health: every ``health/divergence`` verdict
                   with its diagnosis and capsule, plus totals
                   (docs/health.md)
    curves [id]    per-trial learning curves from the durable
                   ``trial/epoch_eval`` records; ``id`` prefix-matches
                   trial ids (omit for every trial); ``--predicted``
                   overlays the curve extrapolator's fit and credible
                   band (docs/early_kill.md)
    replay <cap>   re-execute a divergence capsule and bit-verify the
                   reproduction; exit 0 iff the bad step reproduced
                   bit-exactly
    waterfall <id> per-hop serving waterfall for one trace (prefix
                   match): every gathered hop chain rendered with
                   offsets, segments, pids, and the hop-sum
                   reconciliation error (docs/serving_anatomy.md)
    tails          tail attribution: decompose the p99-over-p50 excess
                   of the serving path into per-hop contributions from
                   the ``serving/hops`` + ``serving/exemplar``
                   records; ``--check`` also gates hop-sum
                   reconciliation within ``--tolerance``
    serving [-n N] the continuous serving time-series: last N
                   ``serving/ts`` rollup rows (qps, p50/p99, shed
                   rate, queue depth, inflight, breaker state)
    sweep [job]    reconstruct a whole sweep from the ``advisor/*``
                   audit records: ordered proposals with acquisition
                   breakdowns, scores, regret curve, advisor lift vs
                   random with a bootstrap CI; exits 1 when a
                   feedback/batch member has no propose record
                   (docs/search_anatomy.md)
    lineage [id]   walk one trial across incarnations/chips/packs
                   (evict, backfill, resume, repack); ``--check``
                   exits 1 on orphaned incarnations fleet-wide
    resume [job]   reconstruct a sweep's crash→adopt→resume timeline
                   from the ``recovery/*`` + supervisor lifecycle
                   records; exits 1 when no recovery story exists
                   (docs/recovery.md)
    autoscale      replay the elasticity controller's decision stream
                   (``autoscale/decision`` + spawn/drain/prewarm):
                   per-tick lane, direction, pressure, reason and the
                   sensor snapshot that justified it; ``--check``
                   exits 1 when actuations flap (direction flips
                   within ``--window`` exceed ``--flips``) —
                   docs/autoscale.md
    tenants        per-tenant serving forensics from the ``tenant/*``
                   accounting records (admit/request/shed/summary) and
                   the ``tenancy/*`` fabric records (residency swaps,
                   co-host rollouts, arbiter verdicts): one row per
                   tenant with tier, qps, p50/p99, shed breakdown and
                   SLO burn; ``--check`` exits 1 when the flushed
                   tenant/summary disagrees with the raw per-record
                   counts — docs/multitenancy.md

Output is one human line per record by default, ``--json`` for JSONL
(pipe into jq). Exit code 1 when a requested trace has no records.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

from rafiki_tpu.obs import journal as journal_mod


def _default_dir() -> str:
    d = os.environ.get(journal_mod.ENV_VAR)
    if d:
        return d
    from rafiki_tpu.config import get_config
    return str(get_config().logs_dir)


def _fmt_record(rec: Dict[str, Any], t0: float) -> str:
    dt = rec.get("ts", 0.0) - t0
    who = f"{rec.get('role', '?')}/{rec.get('pid', '?')}"
    head = f"+{dt:9.3f}s  {who:<18} {rec.get('kind', '?'):<7} {rec.get('name', '?')}"
    parts = []
    if rec.get("dur_s") is not None:
        parts.append(f"dur={rec['dur_s']:.4f}s")
    for k in ("trial_id", "worker_id", "query_id", "site", "mode", "event",
              "reason", "path", "error"):
        if rec.get(k) is not None:
            parts.append(f"{k}={rec[k]}")
    tags = rec.get("tags")
    if isinstance(tags, dict):
        parts.extend(f"{k}={v}" for k, v in tags.items())
    return head + ("  [" + " ".join(parts) + "]" if parts else "")


def _emit(records: List[Dict[str, Any]], as_json: bool) -> None:
    if as_json:
        for rec in records:
            print(json.dumps(rec, default=str))
        return
    t0 = records[0].get("ts", 0.0) if records else 0.0
    for rec in records:
        print(_fmt_record(rec, t0))


def cmd_trace(log_dir: str, trace_id: str, as_json: bool) -> int:
    records = [r for r in journal_mod.read_dir(log_dir)
               if str(r.get("trace_id", "")).startswith(trace_id)]
    if not records:
        print(f"no journal records for trace {trace_id!r} under {log_dir}",
              file=sys.stderr)
        return 1
    _emit(records, as_json)
    if not as_json:
        pids = {(r.get("role"), r.get("pid")) for r in records}
        wall = records[-1].get("ts", 0.0) - records[0].get("ts", 0.0)
        print(f"-- trace {records[0].get('trace_id')}: {len(records)} records "
              f"across {len(pids)} processes, {wall:.3f}s")
    return 0


def cmd_tail(log_dir: str, n: int, as_json: bool) -> int:
    _emit(journal_mod.read_dir(log_dir)[-n:], as_json)
    return 0


def cmd_slowest(log_dir: str, n: int, as_json: bool) -> int:
    spans = [r for r in journal_mod.read_dir(log_dir)
             if r.get("kind") == "span" and r.get("dur_s") is not None]
    spans.sort(key=lambda r: r["dur_s"], reverse=True)
    _emit(spans[:n], as_json)
    return 0


def cmd_profile(log_dir: str, key: Optional[str], as_json: bool,
                peak_flops: Optional[float]) -> int:
    """Join perf/cost x perf/step journal records into per-program
    MFU/roofline rows (the cross-process sibling of the live ``perf``
    telemetry collector)."""
    records = journal_mod.read_dir(log_dir)
    costs: Dict[str, Dict[str, Any]] = {}
    steps: Dict[str, List[float]] = {}
    colds: Dict[str, List[float]] = {}
    for r in records:
        if r.get("kind") != "perf":
            continue
        h = r.get("key_hash")
        if not h:
            continue
        if r.get("name") == "cost":
            costs[h] = r  # latest wins: re-captures supersede
        elif r.get("name") == "step":
            dt = r.get("dt")
            if dt is None:
                continue
            (colds if r.get("cold") else steps).setdefault(h, []).append(
                float(dt) - float(r.get("feed_s") or 0.0))
    hashes = sorted(set(costs) | set(steps) | set(colds))
    if key:
        hashes = [h for h in hashes
                  if h.startswith(key) or key in str(costs.get(h, {}).get("key", ""))]
    if not hashes:
        print(f"no perf records{f' matching {key!r}' if key else ''} "
              f"under {log_dir}", file=sys.stderr)
        return 1
    if peak_flops is None:
        from rafiki_tpu.obs.perf import profiler
        peak_flops = profiler.PEAK_FLOPS_V5E_BF16
    rows = []
    for h in hashes:
        c = costs.get(h, {})
        warm = sorted(steps.get(h, []))
        row: Dict[str, Any] = {
            "key_hash": h,
            "key": c.get("key"),
            "kind": c.get("program_kind"),
            "k": c.get("k"),
            "flops": c.get("flops"),
            "bytes_accessed": c.get("bytes_accessed"),
            "peak_hbm_bytes": c.get("peak_hbm_bytes"),
            "epochs": len(warm),
            "cold_epochs": len(colds.get(h, [])),
        }
        if warm:
            row["step_p50_s"] = warm[len(warm) // 2]
            row["step_min_s"] = warm[0]
        if c.get("flops") and c.get("bytes_accessed"):
            row["arith_intensity"] = c["flops"] / c["bytes_accessed"]
        if c.get("flops") and warm:
            row["achieved_flops_s"] = c["flops"] / row["step_p50_s"]
            # MFU claims a hardware peak: only meaningful when the
            # steps ran on an accelerator. The journal can't know, so
            # the report states its basis instead of guessing.
            row["mfu_vs_peak"] = row["achieved_flops_s"] / peak_flops
            row["peak_flops_basis"] = peak_flops
        rows.append(row)
    if as_json:
        print(json.dumps({"programs": rows}, default=str))
        return 0
    for row in rows:
        print(f"program {row['key_hash']}  kind={row['kind'] or '?'} "
              f"k={row['k'] or '?'} epochs={row['epochs']}"
              f" (+{row['cold_epochs']} cold)")
        if row.get("key"):
            print(f"  key: {row['key']}")
        if row.get("flops"):
            print(f"  cost model: {row['flops']:.3e} flops, "
                  f"{row.get('bytes_accessed') or 0:.3e} bytes"
                  + (f", AI={row['arith_intensity']:.2f} flops/byte"
                     if row.get("arith_intensity") else ""))
        if row.get("step_p50_s") is not None:
            print(f"  observed: p50 step {row['step_p50_s'] * 1e3:.3f}ms "
                  f"(min {row['step_min_s'] * 1e3:.3f}ms)")
        if row.get("achieved_flops_s"):
            print(f"  achieved: {row['achieved_flops_s']:.3e} FLOP/s "
                  f"-> MFU {row['mfu_vs_peak'] * 100:.4f}% of "
                  f"{row['peak_flops_basis']:.3g} peak")
    return 0


def cmd_slo(log_dir: str, as_json: bool) -> int:
    """Latest slo/state snapshot + full breach/recovery history."""
    records = journal_mod.read_dir(log_dir)
    state = None
    breaches: List[Dict[str, Any]] = []
    recoveries: List[Dict[str, Any]] = []
    for r in records:
        if r.get("kind") != "slo":
            continue
        if r.get("name") == "state":
            state = r
        elif r.get("name") == "breach":
            breaches.append(r)
        elif r.get("name") == "recover":
            recoveries.append(r)
    if state is None and not breaches:
        print(f"no slo records under {log_dir} (is the engine ticking? "
              f"see docs/perf.md)", file=sys.stderr)
        return 1
    if as_json:
        print(json.dumps({"state": state, "breaches": breaches,
                          "recoveries": recoveries}, default=str))
        return 0
    if state is not None:
        print(f"slo state @ ts={state.get('ts')}:")
        for name, st in sorted((state.get("state") or {}).items()):
            mark = "BREACH" if st.get("breaching") else "ok"
            val = st.get("value")
            burn = st.get("burn")
            print(f"  {name:<24} {mark:<7} value="
                  f"{'n/a' if val is None else format(val, '.4g')} "
                  f"threshold={st.get('threshold')}"
                  + (f" burn={burn:.2f}x" if burn is not None else ""))
    print(f"breaches: {len(breaches)}, recoveries: {len(recoveries)}")
    for b in breaches[-8:]:
        print(f"  ts={b.get('ts')} {b.get('slo')} value={b.get('value')} "
              f"threshold={b.get('threshold')} ({b.get('source')})")
    return 0


def cmd_health(log_dir: str, as_json: bool) -> int:
    """Numerics health report: divergence verdicts + capsule inventory
    from the ``health/*`` journal records (docs/health.md). An empty
    report is a PASS — exit 0 with a clean bill, unlike trace/curves
    where absence means the query missed."""
    records = journal_mod.read_dir(log_dir)
    divergences: List[Dict[str, Any]] = []
    capsules: List[Dict[str, Any]] = []
    errors: List[Dict[str, Any]] = []
    for r in records:
        if r.get("kind") != "health":
            continue
        if r.get("name") == "divergence":
            divergences.append(r)
        elif r.get("name") == "capsule":
            capsules.append(r)
        elif r.get("name") == "capsule_error":
            errors.append(r)
    if as_json:
        print(json.dumps({"divergences": divergences, "capsules": capsules,
                          "capsule_errors": errors}, default=str))
        return 0
    if not divergences and not errors:
        print(f"no divergences under {log_dir} — numerically clean")
        return 0
    print(f"divergences: {len(divergences)}, capsules: {len(capsules)}, "
          f"capsule write errors: {len(errors)}")
    for d in divergences:
        member = d.get("member")
        where = f" member={member}" if member is not None else ""
        cap = d.get("capsule")
        print(f"  ts={d.get('ts')} {d.get('divergence', '?'):<10}"
              f"{where} bad_step={d.get('bad_step')} "
              f"badput={d.get('badput_s')}s")
        print(f"    {d.get('diagnosis', '?')}")
        if cap:
            print(f"    capsule: {cap}")
    for e in errors:
        print(f"  capsule write FAILED: {e.get('error')}")
    return 0


def _curve_overlay(rows: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Fit the same extrapolator the early-kill path uses (higher-is-
    better ``acc`` points only) and return {fit, points} or None when
    the trial has fewer than two accuracy observations."""
    from rafiki_tpu.advisor import curve as curve_mod

    from rafiki_tpu.advisor.speculative import DEFAULT_HORIZON

    pts = [(int(r["epoch"]), float(r["acc"])) for r in rows
           if r.get("epoch") is not None and r.get("acc") is not None]
    if len(pts) < 2:
        return None
    fit = curve_mod.fit_curve(pts, max(DEFAULT_HORIZON,
                                       max(e for e, _ in pts) + 1))
    if fit is None:
        return None
    return {"fit": fit.to_record(),
            "points": [{"epoch": e, "predicted": v}
                       for e, v in curve_mod.predict_points(fit, pts)]}


def cmd_curves(log_dir: str, trial: Optional[str], as_json: bool,
               predicted: bool = False) -> int:
    """Learning-curve surfacing: replay the durable ``trial/epoch_eval``
    records into per-trial curves (the journal half of what the sqlite
    trial log holds per process). With ``--predicted``, overlay the
    curve extrapolator's fit — the same prediction the early-kill path
    audits a kill decision against (docs/early_kill.md)."""
    curves: Dict[str, List[Dict[str, Any]]] = {}
    for r in journal_mod.read_dir(log_dir):
        if r.get("kind") != "trial" or r.get("name") != "epoch_eval":
            continue
        tid = str(r.get("trial_id", "?"))
        if trial and not tid.startswith(trial):
            continue
        curves.setdefault(tid, []).append(r)
    if not curves:
        print(f"no epoch_eval records"
              f"{f' for trial {trial!r}' if trial else ''} under {log_dir}",
              file=sys.stderr)
        return 1
    for tid in curves:
        curves[tid].sort(key=lambda r: (r.get("epoch", 0), r.get("ts", 0.0)))
    overlays: Dict[str, Optional[Dict[str, Any]]] = {}
    if predicted:
        overlays = {tid: _curve_overlay(rows)
                    for tid, rows in curves.items()}
    if as_json:
        doc: Dict[str, Any] = {"trials": curves}
        if predicted:
            doc["predicted"] = overlays
        print(json.dumps(doc, default=str))
        return 0
    for tid, rows in sorted(curves.items()):
        last = rows[-1]
        packed = " [packed]" if last.get("packed") else ""
        print(f"trial {tid}{packed}: {len(rows)} epochs, "
              f"final score={last.get('score')}")
        ov = overlays.get(tid)
        fitted = ({p["epoch"]: p["predicted"] for p in ov["points"]}
                  if ov else {})
        for r in rows:
            vals = []
            for k in ("loss", "acc"):
                if r.get(k) is not None:
                    vals.append(f"{k}={r[k]:.6g}")
            if r.get("wall_s") is not None:
                vals.append(f"wall={r['wall_s']:.3f}s")
            if r.get("epoch") in fitted:
                vals.append(f"fit={fitted[r['epoch']]:.6g}")
            print(f"  epoch {r.get('epoch'):>3}  " + " ".join(vals))
        if predicted:
            if ov is None:
                print("  predicted: (needs >=2 acc observations)")
            else:
                f = ov["fit"]
                print(f"  predicted final={f['predicted']:.6g} "
                      f"band=±{f['band']:.6g} "
                      f"[{f['lo']:.6g}, {f['hi']:.6g}] "
                      f"family={f['family']} n_obs={f['n_obs']} "
                      f"rmse={f['rmse']:.6g} horizon={f['horizon']}")
    return 0


def cmd_replay(path: str, as_json: bool) -> int:
    """Re-execute a divergence capsule and report the bit-comparison.
    Exit 0 only when every compared sentinel value reproduced exactly —
    the determinism contract scripts/health_smoke.py enforces."""
    from rafiki_tpu.obs.health import capsule

    try:
        result = capsule.replay(path)
    except (FileNotFoundError, ValueError) as e:
        print(f"replay failed: {e}", file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps(result, default=str))
        return 0 if result["reproduced"] else 1
    member = result.get("member")
    where = f" member={member}" if member is not None else ""
    print(f"capsule {result['capsule']}: {result['kind']}{where} "
          f"bad_step={result['bad_step']} "
          f"steps_replayed={result['steps_replayed']}"
          + (" (poisoned)" if result["poisoned"] else ""))
    for k, c in result["comparisons"].items():
        mark = "ok " if c["match"] else "DIFF"
        bits = (f" [{c['expected_bits']} vs {c['got_bits']}]"
                if "expected_bits" in c else "")
        print(f"  {mark} {k:<26} expected={c['expected']} "
              f"got={c['got']}{bits}")
    if result["reproduced"]:
        print("reproduced: the divergent step re-executed bit-exactly")
        return 0
    print(f"NOT reproduced: {', '.join(result['mismatches'])} diverged "
          f"from the observed run — the failure is not deterministic "
          f"under replay (docs/health.md#non-reproducing-capsules)")
    cap_env = result.get("captured_env") or {}
    rep_env = result.get("replay_env") or {}
    if cap_env != rep_env:
        print(f"  note: captured on {cap_env}, replayed on {rep_env} — "
              f"a build/backend mismatch changes XLA fusion and rounding")
    return 1


def _hop_records(log_dir: str,
                 trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """The unique ``serving`` hop-chain records (waterfalls), deduped
    by query id — an exemplar is the same chains journaled twice."""
    out: List[Dict[str, Any]] = []
    seen = set()
    for r in journal_mod.read_dir(log_dir):
        if r.get("kind") != "serving" or r.get("name") not in ("hops",
                                                               "exemplar"):
            continue
        if trace_id and not str(r.get("trace_id", "")).startswith(trace_id):
            continue
        if not r.get("chains"):
            continue
        qid = r.get("query_id")
        if qid in seen:
            continue
        seen.add(qid)
        out.append(r)
    return out


def _chain_view(marks: List[List[Any]]) -> Dict[str, Any]:
    """Segments + reconciliation for one chain. The reconciliation
    compares the sum of NAMED segments against the chain's end-to-end
    span — exact when every mark is known and ordered, loud when a hop
    went missing or a foreign mark absorbed time."""
    from rafiki_tpu.obs.anatomy import hops as hops_mod

    total = hops_mod.chain_total_s(marks)
    segs = hops_mod.segments(marks)
    seg_sum = sum(d for _, d in segs)
    err = abs(seg_sum - total) / total if total > 0 else 0.0
    return {"marks": marks,
            "segments": [{"segment": s, "ms": round(d * 1000.0, 3)}
                         for s, d in segs],
            "total_ms": round(total * 1000.0, 3),
            "seg_sum_ms": round(seg_sum * 1000.0, 3),
            "reconcile_err": round(err, 6)}


def cmd_waterfall(log_dir: str, trace_id: str, as_json: bool) -> int:
    """Stitch one trace's hop chains into a waterfall."""
    records = _hop_records(log_dir, trace_id)
    if not records:
        print(f"no serving hop records for trace {trace_id!r} under "
              f"{log_dir}", file=sys.stderr)
        return 1
    e2e = [r for r in journal_mod.read_dir(log_dir)
           if r.get("kind") == "serving" and r.get("name") == "request"
           and str(r.get("trace_id", "")).startswith(trace_id)]
    queries = []
    for r in records:
        chains = {w: _chain_view(m) for w, m in r["chains"].items()}
        all_marks = [m for v in chains.values() for m in v["marks"]]
        queries.append({
            "query_id": r.get("query_id"),
            "trace_id": r.get("trace_id"),
            "n_hops": max((len(v["marks"]) for v in chains.values()),
                          default=0),
            "pids": sorted({int(m[2]) for m in all_marks}),
            "total_s": r.get("total_s"),
            "max_reconcile_err": max((v["reconcile_err"]
                                      for v in chains.values()), default=0.0),
            "chains": chains,
        })
    doc = {"trace_id": records[0].get("trace_id"), "queries": queries,
           "e2e_s": e2e[-1].get("e2e_s") if e2e else None}
    if as_json:
        print(json.dumps(doc, default=str))
        return 0
    for q in queries:
        print(f"query {q['query_id']}  trace={q['trace_id']} "
              f"hops={q['n_hops']} pids={q['pids']} "
              f"total={q['total_s']}s "
              f"reconcile_err={q['max_reconcile_err']:.4f}")
        for w, v in sorted(q["chains"].items()):
            print(f"  chain {w}: total {v['total_ms']}ms "
                  f"(segments sum {v['seg_sum_ms']}ms)")
            t_first = float(v["marks"][0][1]) if v["marks"] else 0.0
            for m in v["marks"]:
                off_ms = (float(m[1]) - t_first) * 1000.0
                print(f"    +{off_ms:10.3f}ms  {str(m[0]):<6} pid={m[2]}")
            for s in v["segments"]:
                print(f"      {s['segment']:<16} {s['ms']:10.3f}ms")
    if doc["e2e_s"] is not None:
        print(f"-- gateway e2e (post-admission): {doc['e2e_s']}s")
    return 0


def _pctile(xs: List[float], q: float) -> float:
    xs = sorted(xs)
    idx = min(len(xs) - 1, max(0, int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[idx]


def cmd_tails(log_dir: str, as_json: bool, check: bool,
              tolerance: float) -> int:
    """Decompose the p99-over-p50 latency excess into hop
    contributions, and (with ``--check``) gate hop-sum
    reconciliation."""
    from rafiki_tpu.obs.anatomy import hops as hops_mod

    records = _hop_records(log_dir)
    if not records:
        print(f"no serving hop records under {log_dir}", file=sys.stderr)
        return 1
    per_seg: Dict[str, List[float]] = {}
    totals: List[float] = []
    worst_err = 0.0
    for r in records:
        rec_total = 0.0
        for marks in r["chains"].values():
            total = hops_mod.chain_total_s(marks)
            segs = hops_mod.segments(marks)
            for s, d in segs:
                per_seg.setdefault(s, []).append(d)
            seg_sum = sum(d for _, d in segs)
            if total > 0:
                worst_err = max(worst_err, abs(seg_sum - total) / total)
            rec_total = max(rec_total, total)
        totals.append(rec_total)
    p50_tot, p99_tot = _pctile(totals, 50.0), _pctile(totals, 99.0)
    excess = max(0.0, p99_tot - p50_tot)
    contribs = {s: max(0.0, _pctile(d, 99.0) - _pctile(d, 50.0))
                for s, d in per_seg.items()}
    contrib_sum = sum(contribs.values()) or 1.0
    segments = [{"segment": s,
                 "count": len(per_seg[s]),
                 "p50_ms": round(_pctile(per_seg[s], 50.0) * 1000.0, 3),
                 "p99_ms": round(_pctile(per_seg[s], 99.0) * 1000.0, 3),
                 "excess_ms": round(c * 1000.0, 3),
                 "share": round(c / contrib_sum, 4)}
                for s, c in sorted(contribs.items(),
                                   key=lambda kv: kv[1], reverse=True)]
    reconciled = worst_err <= tolerance
    doc = {"requests": len(records),
           "p50_ms": round(p50_tot * 1000.0, 3),
           "p99_ms": round(p99_tot * 1000.0, 3),
           "excess_ms": round(excess * 1000.0, 3),
           "dominant": segments[0]["segment"] if segments else None,
           "segments": segments,
           "reconcile": {"worst_err": round(worst_err, 6),
                         "tolerance": tolerance, "ok": reconciled}}
    if as_json:
        print(json.dumps(doc, default=str))
    else:
        print(f"{doc['requests']} requests: p50 {doc['p50_ms']}ms, "
              f"p99 {doc['p99_ms']}ms, excess {doc['excess_ms']}ms")
        for s in segments:
            print(f"  {s['segment']:<16} n={s['count']:<5} "
                  f"p50={s['p50_ms']:>9.3f}ms p99={s['p99_ms']:>9.3f}ms "
                  f"excess={s['excess_ms']:>9.3f}ms share={s['share']:.0%}")
        print(f"hop-sum reconciliation: worst_err="
              f"{doc['reconcile']['worst_err']:.4f} "
              f"({'ok' if reconciled else 'FAIL'} at tol {tolerance})")
    if check and not reconciled:
        print(f"hop sums do not reconcile with end-to-end latency "
              f"(worst_err {worst_err:.4f} > {tolerance})", file=sys.stderr)
        return 1
    return 0


def cmd_serving(log_dir: str, n: int, as_json: bool) -> int:
    """Render the last N serving/ts rollup rows."""
    rows = [r for r in journal_mod.read_dir(log_dir)
            if r.get("kind") == "serving" and r.get("name") == "ts"]
    if not rows:
        print(f"no serving/ts records under {log_dir} (is a gateway "
              f"journaling? see docs/serving_anatomy.md)", file=sys.stderr)
        return 1
    rows = rows[-n:]
    if as_json:
        for r in rows:
            print(json.dumps(r, default=str))
        return 0
    for r in rows:
        breakers = r.get("breakers") or {}
        open_n = r.get("breakers_open", 0)
        print(f"bucket {r.get('bucket')}  qps={r.get('qps')} "
              f"p50={r.get('p50_ms')}ms p99={r.get('p99_ms')}ms "
              f"shed_rate={r.get('shed_rate')} ok={r.get('ok')} "
              f"shed={r.get('shed')} err={r.get('errors')} "
              f"queue={r.get('queue_depth')} inflight={r.get('inflight')} "
              f"breakers={len(breakers)} ({open_n} open)")
    return 0


def cmd_decisions(log_dir: str, n: int, as_json: bool) -> int:
    """Control-plane decision forensics: route switches, admission
    sheds, breaker flips, and twin placement advisories merged into
    one time-ordered stream — the "why did serving degrade at 14:03"
    view. Each of these kinds is write-once forensic state; this is
    their reader (RF014)."""
    rows = []
    for r in journal_mod.read_dir(log_dir):
        kind, name = r.get("kind"), r.get("name")
        if kind == "serving" and name == "route":
            rows.append(("route", r))
        elif kind == "gateway" and name == "shed":
            rows.append(("shed", r))
        elif kind == "gateway" and name == "breaker_transition":
            rows.append(("breaker", r))
        elif kind == "twin" and name == "placement":
            rows.append(("placement", r))
    if not rows:
        print(f"no decision records under {log_dir} (routes, sheds, "
              f"breaker transitions, placement advisories)",
              file=sys.stderr)
        return 1
    rows.sort(key=lambda kr: kr[1].get("ts", 0.0))
    shown = rows[-n:] if n else rows
    if as_json:
        for _, r in shown:
            print(json.dumps(r, default=str))
        return 0
    for tag, r in shown:
        ts = r.get("ts")
        if tag == "route":
            line = (f"route={r.get('route')} job={r.get('job_id')} "
                    f"k={r.get('k')} reason={r.get('reason')} "
                    f"workers={r.get('workers')}")
        elif tag == "shed":
            line = f"reason={r.get('reason')}"
        elif tag == "breaker":
            line = (f"worker={r.get('worker_id')} "
                    f"{r.get('from_state')}→{r.get('to_state')}")
        else:
            line = (f"job={r.get('job_id')} k={r.get('k')} "
                    f"chips={r.get('chips')} "
                    f"rec={r.get('recommendation')} "
                    f"advisory={r.get('advisory')}")
        print(f"{ts:>14.3f}  {tag:<9} {line}" if isinstance(ts, float)
              else f"{str(ts):>14}  {tag:<9} {line}")
    sheds: Dict[str, int] = {}
    flips: Dict[str, int] = {}
    for tag, r in rows:
        if tag == "shed":
            k = str(r.get("reason"))
            sheds[k] = sheds.get(k, 0) + 1
        elif tag == "breaker":
            k = str(r.get("worker_id"))
            flips[k] = flips.get(k, 0) + 1
    print(f"{len(rows)} decisions"
          + (f"; sheds by reason: {sheds}" if sheds else "")
          + (f"; breaker transitions by worker: {flips}" if flips else ""))
    return 0


def cmd_shard(log_dir: str, as_json: bool) -> int:
    """The sharded-lane story (docs/sharding.md), reconstructed from
    the journals alone: per trial, the plan, every group (re-)formation
    with its width and members, member losses, and reshard-on-restore
    events — the width history a post-mortem needs. These kinds are
    write-once forensic state; this is their reader (RF014)."""
    plans = []
    by_trial: Dict[str, List[dict]] = {}
    group_walls = 0
    for r in journal_mod.read_dir(log_dir):
        kind, name = r.get("kind"), r.get("name")
        if kind == "shard" and name == "plan":
            plans.append(r)
        elif kind == "shard" and name in ("group_formed", "member_lost",
                                          "reshard"):
            by_trial.setdefault(str(r.get("trial_id")), []).append(r)
        elif (kind == "perf" and name == "step"
              and int(r.get("group_width") or 0) > 1):
            group_walls += 1
    if not plans and not by_trial:
        print(f"no shard/* records under {log_dir} (did a sharded "
              f"group run? see docs/sharding.md)", file=sys.stderr)
        return 1
    if as_json:
        for r in plans:
            print(json.dumps(r, default=str))
        for rows in by_trial.values():
            for r in sorted(rows, key=lambda x: x.get("ts", 0.0)):
                print(json.dumps(r, default=str))
        return 0
    for r in plans:
        frac = r.get("hbm_frac")
        print(f"plan    family={r.get('family')} width={r.get('width')} "
              f"hbm_bytes={r.get('hbm_bytes')} "
              f"hbm_frac={round(frac, 4) if isinstance(frac, float) else frac}")
    reshards = 0
    for tid in sorted(by_trial):
        rows = sorted(by_trial[tid], key=lambda x: x.get("ts", 0.0))
        widths = [r.get("width") for r in rows
                  if r.get("name") == "group_formed"]
        print(f"trial {tid[:13]}  width history: "
              + (" -> ".join(str(w) for w in widths) or "(none)"))
        for r in rows:
            name = r.get("name")
            if name == "group_formed":
                line = (f"width={r.get('width')} members={r.get('members')} "
                        f"attempt={r.get('attempt')}")
            elif name == "member_lost":
                line = (f"lost={r.get('lost')} "
                        f"survivors={r.get('survivors')}")
            else:
                reshards += 1
                line = (f"{r.get('from_width')} -> {r.get('to_width')} "
                        f"@epoch {r.get('epoch')}")
            print(f"  {name:<13} {line}")
    print(f"{len(by_trial)} sharded trial(s), {reshards} reshard "
          f"restore(s), {group_walls} group epoch wall(s) journaled")
    return 0


def cmd_autoscale(log_dir: str, n: int, as_json: bool, check: bool,
                  window_s: float, max_flips: int) -> int:
    """Replay the controller's decision stream; with ``--check``, gate
    on flap: actuated direction flips per lane inside ``window_s``
    must stay under ``max_flips`` (the smoke's vacuous-pass polarity
    runs an undamped controller through here and MUST fail)."""
    records = [r for r in journal_mod.read_dir(log_dir)
               if r.get("kind") == "autoscale"]
    if not records:
        print(f"no autoscale records under {log_dir} (is a controller "
              f"running? see docs/autoscale.md)", file=sys.stderr)
        return 1
    decisions = [r for r in records if r.get("name") == "decision"]
    shown = decisions[-n:] if n else decisions
    if as_json:
        for r in shown:
            print(json.dumps(r, default=str))
    else:
        for r in shown:
            flags = "".join((" DAMPED" if r.get("damped") else "",
                             " VETOED" if r.get("vetoed") else "",
                             " actuated" if r.get("actuated") else ""))
            s = r.get("sensors") or {}
            press = r.get("pressure")
            print(f"{r.get('lane', '?'):<10} {r.get('direction', '?'):<5}"
                  f" {r.get('current')}→{r.get('target')}"
                  f"  p={press if press is None else round(press, 3)}"
                  f" reason={r.get('reason')}{flags}"
                  f"  [burn={s.get('slo_burn')} queue={s.get('queue_depth')}"
                  f" shed={s.get('shed_rate')}"
                  f" eph={s.get('effective_trials_per_hour')}]")
    if not check:
        return 0
    worst = 0
    for lane in {r.get("lane") for r in decisions}:
        acts = [(r.get("tick_ts") or r.get("ts", 0.0), r.get("direction"))
                for r in decisions
                if r.get("lane") == lane and r.get("actuated")]
        flips = [b_ts for (a_ts, a), (b_ts, b) in zip(acts, acts[1:])
                 if a != b]
        for i, ts in enumerate(flips):
            inside = sum(1 for t in flips[:i + 1] if ts - t <= window_s)
            worst = max(worst, inside)
    if worst > max_flips:
        print(f"FLAPPING: {worst} direction flips inside {window_s}s "
              f"(limit {max_flips}) — an undamped actuator is thrashing "
              f"capacity (docs/autoscale.md)", file=sys.stderr)
        return 1
    print(f"damping ok: worst flip count {worst} within {window_s}s "
          f"(limit {max_flips})")
    return 0


def cmd_tenants(log_dir: str, as_json: bool, check: bool) -> int:
    """Per-tenant serving forensics: who was admitted, who was shed
    and why, and whose SLO burned — the "which tenant is the noisy
    neighbor" view, read from journals alone. This is the reader for
    the ``tenant`` and ``tenancy`` journal kinds (RF014): the
    admission/accounting plane writes them per request, the residency
    manager per swap, the arbiter per job verdict."""
    recs = journal_mod.read_dir(log_dir)
    # Kind-wholesale filters on purpose: every name under these two
    # kinds is forensic state this verb must surface, including names
    # added later.
    tenant_recs = [r for r in recs if r.get("kind") == "tenant"]
    tenancy_recs = [r for r in recs if r.get("kind") == "tenancy"]
    if not tenant_recs and not tenancy_recs:
        print(f"no tenant/tenancy records under {log_dir} (is a "
              f"tenant-aware gateway running? see docs/multitenancy.md)",
              file=sys.stderr)
        return 1

    def _p(xs: List[float], frac: float) -> Optional[float]:
        if not xs:
            return None
        return xs[min(len(xs) - 1, int(frac * len(xs)))]

    per: Dict[str, Dict[str, Any]] = {}
    for r in tenant_recs:
        t = r.get("tenant")
        if t is None:
            continue
        row = per.setdefault(t, {"tier": None, "admitted": 0, "requests": 0,
                                 "ok": 0, "shed": 0, "shed_reasons": {},
                                 "lat_s": [], "burn": None})
        name = r.get("name")
        if name == "admit":
            row["admitted"] += 1
            row["tier"] = r.get("tier") or row["tier"]
        elif name == "request":
            row["requests"] += 1
            row["ok"] += 1 if r.get("ok") else 0
            if isinstance(r.get("e2e_s"), (int, float)):
                row["lat_s"].append(float(r["e2e_s"]))
        elif name == "shed":
            row["shed"] += 1
            row["tier"] = r.get("tier") or row["tier"]
            reason = str(r.get("reason"))
            row["shed_reasons"][reason] = (
                row["shed_reasons"].get(reason, 0) + 1)
    summaries = [r for r in tenant_recs if r.get("name") == "summary"]
    latest = summaries[-1].get("tenants", {}) if summaries else {}
    ts = [r.get("ts") for r in tenant_recs
          if isinstance(r.get("ts"), (int, float))]
    span_s = (max(ts) - min(ts)) if len(ts) > 1 else 0.0
    table = []
    for t in sorted(per):
        row = per[t]
        xs = sorted(row["lat_s"])
        table.append({
            "tenant": t,
            "tier": row["tier"],
            "admitted": row["admitted"],
            "requests": row["requests"],
            "ok": row["ok"],
            "shed": row["shed"],
            "shed_reasons": row["shed_reasons"],
            "qps": (round(row["requests"] / span_s, 2) if span_s else None),
            "p50_ms": (None if _p(xs, 0.50) is None
                       else round(_p(xs, 0.50) * 1000, 3)),
            "p99_ms": (None if _p(xs, 0.99) is None
                       else round(_p(xs, 0.99) * 1000, 3)),
            "burn": (latest.get(t, {}) or {}).get("burn"),
        })
    residency = [r for r in tenancy_recs if r.get("name") == "residency"]
    cohosts = [r for r in tenancy_recs if r.get("name") == "cohost"]
    verdicts = [r for r in tenancy_recs if r.get("name") == "arbiter"]
    swap_events: Dict[str, int] = {}
    for r in residency:
        ev = str(r.get("event"))
        swap_events[ev] = swap_events.get(ev, 0) + 1
    if as_json:
        print(json.dumps({
            "tenants": table,
            "summary": latest or None,
            "residency_events": swap_events,
            "cohosted_workers": [
                {"worker_id": r.get("worker_id"), "jobs": r.get("jobs"),
                 "budget_bytes": r.get("budget_bytes")} for r in cohosts],
            "arbiter_verdicts": [
                {"job_id": r.get("job_id"), "tenant": r.get("tenant"),
                 "verdict": r.get("verdict")} for r in verdicts],
        }, default=str))
    else:
        for row in table:
            sheds = (f" shed={row['shed']}{row['shed_reasons']}"
                     if row["shed"] else "")
            print(f"{row['tenant']:<16} {str(row['tier']):<6} "
                  f"adm={row['admitted']:<5} req={row['requests']:<5} "
                  f"qps={row['qps']} p50={row['p50_ms']}ms "
                  f"p99={row['p99_ms']}ms burn={row['burn']}{sheds}")
        if swap_events:
            print(f"residency: {swap_events}")
        for r in cohosts:
            print(f"cohost: worker={r.get('worker_id')} "
                  f"jobs={r.get('jobs')} budget={r.get('budget_bytes')}B")
        for r in verdicts:
            print(f"arbiter: job={r.get('job_id')} "
                  f"tenant={r.get('tenant')} verdict={r.get('verdict')}")
    if not check:
        return 0
    if not summaries:
        print("no tenant/summary record — the gateway never drained, so "
              "the accounting flush is missing (docs/multitenancy.md)",
              file=sys.stderr)
        return 1
    bad = []
    for t, row in per.items():
        s = latest.get(t, {}) or {}
        if s.get("admitted") != row["admitted"]:
            bad.append(f"{t}: summary admitted={s.get('admitted')} vs "
                       f"{row['admitted']} tenant/admit records")
        if s.get("shed") != row["shed"]:
            bad.append(f"{t}: summary shed={s.get('shed')} vs "
                       f"{row['shed']} tenant/shed records")
    if bad:
        print("RECONCILIATION FAILED: " + "; ".join(bad), file=sys.stderr)
        return 1
    print(f"reconciled: {len(per)} tenant(s) against the flushed summary")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()  # profile's peak-flops default imports the
    # profiler package; pin the platform before anything can touch jax.
    p = argparse.ArgumentParser(
        prog="python -m rafiki_tpu.obs",
        description="merge and query the per-process observability journals")
    p.add_argument("--dir", default=None,
                   help="journal directory (default: $RAFIKI_LOG_DIR, "
                        "then the configured logs_dir)")
    p.add_argument("--json", action="store_true",
                   help="emit raw JSONL instead of formatted lines")
    sub = p.add_subparsers(dest="cmd", required=True)
    sp = sub.add_parser("trace", help="stitch one trace across processes")
    sp.add_argument("trace_id")
    sp = sub.add_parser("tail", help="last N records fleet-wide")
    sp.add_argument("-n", type=int, default=32)
    sp = sub.add_parser("slowest", help="N slowest spans")
    sp.add_argument("-n", type=int, default=16)
    sp = sub.add_parser("profile",
                        help="per-program cost model x step-time join")
    sp.add_argument("key", nargs="?", default=None,
                    help="program key-hash prefix or key substring")
    sp.add_argument("--peak-flops", type=float, default=None,
                    help="MFU denominator (default: v5e bf16 peak)")
    sub.add_parser("slo", help="current SLO burn state + breach history")
    sub.add_parser("health",
                   help="numerics divergences + replay capsule inventory")
    sp = sub.add_parser("curves",
                        help="per-trial learning curves from the journals")
    sp.add_argument("trial", nargs="?", default=None,
                    help="trial id prefix (omit for all trials)")
    sp.add_argument("--predicted", action="store_true",
                    help="overlay the learning-curve extrapolator's fit "
                         "(predicted final + credible band) on each curve")
    sp = sub.add_parser("replay",
                        help="re-execute a divergence capsule, bit-verify")
    sp.add_argument("capsule", help="path to a capsule-*.rcap file")
    sp = sub.add_parser("waterfall",
                        help="per-hop serving waterfall for one trace")
    sp.add_argument("trace_id")
    sp = sub.add_parser("tails",
                        help="p99-over-p50 excess by serving hop")
    sp.add_argument("--check", action="store_true",
                    help="exit 1 unless hop sums reconcile with "
                         "end-to-end latency")
    sp.add_argument("--tolerance", type=float, default=0.10,
                    help="reconciliation tolerance (default 0.10)")
    sp = sub.add_parser("serving",
                        help="continuous serving time-series rows")
    sp.add_argument("-n", type=int, default=32)
    sp = sub.add_parser("decisions",
                        help="control-plane decision stream: routes, "
                             "sheds, breaker flips, placement advisories")
    sp.add_argument("-n", type=int, default=32,
                    help="show the last N decisions (0 = all)")
    sub.add_parser("shard",
                   help="sharded-group width history: plans, "
                        "formations, member losses, reshard restores")
    sp = sub.add_parser("autoscale",
                        help="elasticity controller decision replay")
    sp.add_argument("-n", type=int, default=32,
                    help="show the last N decisions (0 = all)")
    sp.add_argument("--check", action="store_true",
                    help="exit 1 when actuations flap (direction flips "
                         "within --window exceed --flips)")
    sp.add_argument("--window", type=float, default=60.0,
                    help="flap detection window seconds (default 60)")
    sp.add_argument("--flips", type=int, default=4,
                    help="max direction flips tolerated in the window")
    sp = sub.add_parser("tenants",
                        help="per-tenant serving forensics: admission, "
                             "shed breakdown, SLO burn, residency swaps")
    sp.add_argument("--check", action="store_true",
                    help="exit 1 when the flushed tenant/summary "
                         "disagrees with raw per-record counts")
    from rafiki_tpu.obs.twin import cli as twin_cli

    # Stdlib-only at import time; the engine loads inside the verbs.
    twin_cli.attach(sub)
    from rafiki_tpu.obs.search import cli as search_cli

    # Same discipline: attach is argparse-only, readers load lazily.
    search_cli.attach(sub)
    args = p.parse_args(argv)

    if args.cmd == "replay":
        # No journal dir needed: the capsule is self-contained.
        return cmd_replay(args.capsule, args.json)
    log_dir = args.dir or _default_dir()
    if args.cmd == "trace":
        return cmd_trace(log_dir, args.trace_id, args.json)
    if args.cmd == "tail":
        return cmd_tail(log_dir, args.n, args.json)
    if args.cmd == "profile":
        return cmd_profile(log_dir, args.key, args.json, args.peak_flops)
    if args.cmd == "slo":
        return cmd_slo(log_dir, args.json)
    if args.cmd == "health":
        return cmd_health(log_dir, args.json)
    if args.cmd == "curves":
        return cmd_curves(log_dir, args.trial, args.json, args.predicted)
    if args.cmd == "waterfall":
        return cmd_waterfall(log_dir, args.trace_id, args.json)
    if args.cmd == "tails":
        return cmd_tails(log_dir, args.json, args.check, args.tolerance)
    if args.cmd == "serving":
        return cmd_serving(log_dir, args.n, args.json)
    if args.cmd == "decisions":
        return cmd_decisions(log_dir, args.n, args.json)
    if args.cmd == "shard":
        return cmd_shard(log_dir, args.json)
    if args.cmd == "autoscale":
        return cmd_autoscale(log_dir, args.n, args.json, args.check,
                             args.window, args.flips)
    if args.cmd == "tenants":
        return cmd_tenants(log_dir, args.json, args.check)
    if args.cmd == "twin":
        return twin_cli.dispatch(args, log_dir, args.json)
    if args.cmd in ("sweep", "lineage", "resume"):
        return search_cli.dispatch(args, log_dir, args.json)
    return cmd_slowest(log_dir, args.n, args.json)
