"""``python -m rafiki_tpu.obs`` — read the merged cross-process journals.

Subcommands (all read ``journal-*.jsonl*`` under ``--dir``, default
``$RAFIKI_LOG_DIR`` then the configured ``logs_dir``):

    trace <id>     every record carrying the trace id (prefix match),
                   time-ordered across processes, one line per hop —
                   the stitched end-to-end view of one query or trial
    tail [-n N]    the last N records fleet-wide
    slowest [-n N] the N slowest finished spans

Output is one human line per record by default, ``--json`` for JSONL
(pipe into jq). Exit code 1 when a requested trace has no records.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

from rafiki_tpu.obs import journal as journal_mod


def _default_dir() -> str:
    d = os.environ.get(journal_mod.ENV_VAR)
    if d:
        return d
    from rafiki_tpu.config import get_config
    return str(get_config().logs_dir)


def _fmt_record(rec: Dict[str, Any], t0: float) -> str:
    dt = rec.get("ts", 0.0) - t0
    who = f"{rec.get('role', '?')}/{rec.get('pid', '?')}"
    head = f"+{dt:9.3f}s  {who:<18} {rec.get('kind', '?'):<7} {rec.get('name', '?')}"
    parts = []
    if rec.get("dur_s") is not None:
        parts.append(f"dur={rec['dur_s']:.4f}s")
    for k in ("trial_id", "worker_id", "query_id", "site", "mode", "event",
              "reason", "path", "error"):
        if rec.get(k) is not None:
            parts.append(f"{k}={rec[k]}")
    tags = rec.get("tags")
    if isinstance(tags, dict):
        parts.extend(f"{k}={v}" for k, v in tags.items())
    return head + ("  [" + " ".join(parts) + "]" if parts else "")


def _emit(records: List[Dict[str, Any]], as_json: bool) -> None:
    if as_json:
        for rec in records:
            print(json.dumps(rec, default=str))
        return
    t0 = records[0].get("ts", 0.0) if records else 0.0
    for rec in records:
        print(_fmt_record(rec, t0))


def cmd_trace(log_dir: str, trace_id: str, as_json: bool) -> int:
    records = [r for r in journal_mod.read_dir(log_dir)
               if str(r.get("trace_id", "")).startswith(trace_id)]
    if not records:
        print(f"no journal records for trace {trace_id!r} under {log_dir}",
              file=sys.stderr)
        return 1
    _emit(records, as_json)
    if not as_json:
        pids = {(r.get("role"), r.get("pid")) for r in records}
        wall = records[-1].get("ts", 0.0) - records[0].get("ts", 0.0)
        print(f"-- trace {records[0].get('trace_id')}: {len(records)} records "
              f"across {len(pids)} processes, {wall:.3f}s")
    return 0


def cmd_tail(log_dir: str, n: int, as_json: bool) -> int:
    _emit(journal_mod.read_dir(log_dir)[-n:], as_json)
    return 0


def cmd_slowest(log_dir: str, n: int, as_json: bool) -> int:
    spans = [r for r in journal_mod.read_dir(log_dir)
             if r.get("kind") == "span" and r.get("dur_s") is not None]
    spans.sort(key=lambda r: r["dur_s"], reverse=True)
    _emit(spans[:n], as_json)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m rafiki_tpu.obs",
        description="merge and query the per-process observability journals")
    p.add_argument("--dir", default=None,
                   help="journal directory (default: $RAFIKI_LOG_DIR, "
                        "then the configured logs_dir)")
    p.add_argument("--json", action="store_true",
                   help="emit raw JSONL instead of formatted lines")
    sub = p.add_subparsers(dest="cmd", required=True)
    sp = sub.add_parser("trace", help="stitch one trace across processes")
    sp.add_argument("trace_id")
    sp = sub.add_parser("tail", help="last N records fleet-wide")
    sp.add_argument("-n", type=int, default=32)
    sp = sub.add_parser("slowest", help="N slowest spans")
    sp.add_argument("-n", type=int, default=16)
    args = p.parse_args(argv)

    log_dir = args.dir or _default_dir()
    if args.cmd == "trace":
        return cmd_trace(log_dir, args.trace_id, args.json)
    if args.cmd == "tail":
        return cmd_tail(log_dir, args.n, args.json)
    return cmd_slowest(log_dir, args.n, args.json)
