"""Goodput/cost ledger: where did the fleet's wall-clock go?

The telemetry histograms say how long an epoch took; they don't say
who *paid* for it. The ledger does the accounting the ROADMAP's
compile-cache and straggler-eviction items are blocked on: per-entity
(``trial:<id>``, ``pack:<key>``, ``job:<id>``, or the whole ``bench``
section) buckets of

    compile_s     program build + cold-epoch overhead (first epoch
                  wall minus its feed, beyond a warm epoch's cost)
    step_s        warm-epoch device step/dispatch time — the only
                  bucket that counts as *productive*
    feed_s        host→device feed stalls
    checkpoint_s  checkpoint/persist writes
    downtime_s    chaos-injected delays and death→respawn gaps
    badput_s      anomaly excess: wall an epoch spent over its EWMA
                  baseline (the perf sentinel's regression charge)

rolled up to ``goodput = productive_step_s / wall_s`` per entity and
fleet-wide. The roll-up is exposed as the ``goodput`` telemetry
collector, so it rides along in every ``GET /metrics`` snapshot and in
``bench.py`` detail on both TPU and degraded-CPU runs.

Charging is ambient: ``with ledger.entity("trial:t1"): ...`` binds the
entity to the thread (nestable — inner entities win), and the training
loop / chaos plane / checkpoint paths call ``ledger.add(bucket, s)``
without knowing who is currently paying. Unbound charges land on the
``process`` entity so nothing is silently dropped.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, Iterator, Optional

from rafiki_tpu import telemetry
from rafiki_tpu.obs.journal import journal as _journal

BUCKETS = ("compile_s", "step_s", "feed_s", "checkpoint_s", "downtime_s",
           "badput_s")

#: Fallback entity for charges made outside any ``entity()`` block.
DEFAULT_ENTITY = "process"


class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self._tls = threading.local()
        # entity -> {bucket: seconds, "wall_s": seconds}
        self._entities: Dict[str, Dict[str, float]] = {}

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def current_entity(self) -> str:
        stack = self._stack()
        return stack[-1] if stack else DEFAULT_ENTITY

    @contextlib.contextmanager
    def entity(self, name: str) -> Iterator[str]:
        """Bind ``name`` as this thread's paying entity; its wall-clock
        accumulates into ``wall_s`` (the goodput denominator)."""
        stack = self._stack()
        stack.append(name)
        t0 = time.monotonic()
        try:
            yield name
        finally:
            dt = time.monotonic() - t0
            stack.pop()
            with self._lock:
                row = self._entities.setdefault(name, {})
                row["wall_s"] = row.get("wall_s", 0.0) + dt
                split = dict(row)
            # lint: disable=RF014 — per-entity cost audit stream read offline (notebooks/goodput post-mortems), not by code
            _journal.record("ledger", name, **{
                k: round(v, 6) for k, v in split.items()})

    def add(self, bucket: str, seconds: float,
            entity: Optional[str] = None) -> None:
        """Charge ``seconds`` to ``bucket`` for ``entity`` (default:
        the thread's bound entity, else ``process``)."""
        if seconds <= 0.0:
            return
        name = entity or self.current_entity()
        with self._lock:
            row = self._entities.setdefault(name, {})
            row[bucket] = row.get(bucket, 0.0) + seconds

    # -- reads ---------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Per-entity splits plus the fleet roll-up. JSON-able; this is
        the ``goodput`` telemetry collector."""
        with self._lock:
            entities = {name: {k: round(v, 6) for k, v in row.items()}
                        for name, row in self._entities.items()}
        total: Dict[str, float] = {}
        for row in entities.values():
            for k, v in row.items():
                total[k] = total.get(k, 0.0) + v
        for name, row in entities.items():
            wall = row.get("wall_s", 0.0)
            if wall > 0.0:
                row["goodput"] = round(row.get("step_s", 0.0) / wall, 4)
        out: Dict[str, Any] = {
            "entities": entities,
            "total": {k: round(v, 6) for k, v in total.items()},
        }
        wall = total.get("wall_s", 0.0)
        out["goodput"] = (round(total.get("step_s", 0.0) / wall, 4)
                          if wall > 0.0 else None)
        return out

    def reset(self) -> None:
        with self._lock:
            self._entities.clear()


#: Process-global ledger (telemetry scope rules apply: per process).
ledger = Ledger()

telemetry.register_collector("goodput", ledger.snapshot)
