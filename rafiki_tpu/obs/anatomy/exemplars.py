"""Slowest-N exemplar ring: full waterfalls for the requests that
percentile summaries erase.

A p99 histogram says *that* the tail is slow, never *why*. The ring
keeps the complete hop chains of the slowest N requests per time
window; when a window rolls (or :func:`ExemplarRing.flush` forces it)
the retained exemplars are journaled as ``serving/exemplar`` records —
so ``obs tails`` can show the actual anatomy of the worst requests,
not just their rank.

Bounded by construction: at most ``cap`` exemplars retained at any
moment, sorted slowest-first, windows sized in seconds. Both knobs are
env-tunable (``RAFIKI_EXEMPLAR_N``, ``RAFIKI_EXEMPLAR_WINDOW_S``).

The trace id is captured at *offer* time and journaled explicitly: a
window rolls during some LATER request's offer, and letting the
journal stamp that request's ambient trace onto these records would
mis-attribute every exemplar in the window.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from rafiki_tpu import telemetry
from rafiki_tpu.obs.journal import journal as _journal

ENV_CAP = "RAFIKI_EXEMPLAR_N"
ENV_WINDOW = "RAFIKI_EXEMPLAR_WINDOW_S"
DEFAULT_CAP = 8
DEFAULT_WINDOW_S = 30.0


class ExemplarRing:
    """Slowest-``cap`` full-waterfall retention per ``window_s`` window.

    ``clock`` is injectable (monotonic by default) so window-roll tests
    are deterministic.
    """

    def __init__(self, cap: Optional[int] = None,
                 window_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        if cap is None:
            cap = int(os.environ.get(ENV_CAP, DEFAULT_CAP))
        if window_s is None:
            window_s = float(os.environ.get(ENV_WINDOW, DEFAULT_WINDOW_S))
        self.cap = max(1, int(cap))
        self.window_s = float(window_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._window_start: Optional[float] = None
        self._items: List[Tuple[float, Dict[str, Any]]] = []
        self._offered = 0
        self._windows_flushed = 0

    def offer(self, total_s: float, record: Dict[str, Any]) -> None:
        """Consider one finished request for retention. ``record`` must
        carry ``query_id`` / ``chains`` (and ideally ``trace_id``)."""
        rolled: List[Tuple[float, Dict[str, Any]]] = []
        with self._lock:
            now = self._clock()
            if self._window_start is None:
                self._window_start = now
            elif now - self._window_start >= self.window_s:
                rolled, self._items = self._items, []
                self._window_start = now
                self._windows_flushed += 1
            self._offered += 1
            self._items.append((float(total_s), record))
            self._items.sort(key=lambda it: it[0], reverse=True)
            del self._items[self.cap:]
        if rolled:
            self._journal_items(rolled)

    def flush(self) -> int:
        """Force the current window closed (bench/smoke teardown —
        otherwise a run shorter than ``window_s`` journals nothing).
        Returns how many exemplars were journaled."""
        with self._lock:
            items, self._items = self._items, []
            self._window_start = None
            if items:
                self._windows_flushed += 1
        self._journal_items(items)
        return len(items)

    def _journal_items(self,
                       items: List[Tuple[float, Dict[str, Any]]]) -> None:
        for rank, (total_s, rec) in enumerate(items):
            _journal.record("serving", "exemplar", rank=rank,
                            total_s=round(total_s, 6),
                            query_id=rec.get("query_id"),
                            chains=rec.get("chains"),
                            trace_id=rec.get("trace_id"))

    def collector(self) -> Dict[str, Any]:
        """Telemetry collector payload — numeric-only so the prom
        flattener keeps every leaf."""
        with self._lock:
            out: Dict[str, Any] = {
                "retained": len(self._items),
                "offered": self._offered,
                "windows_flushed": self._windows_flushed,
                "cap": self.cap,
                "window_s": self.window_s,
            }
            if self._items:
                out["slowest_s"] = round(self._items[0][0], 6)
            return out


#: Process-global ring, mirroring the journal/telemetry singletons:
#: the predictor's absorb step and bench teardown must agree on one.
ring = ExemplarRing()
telemetry.register_collector("serving_exemplars", ring.collector)
