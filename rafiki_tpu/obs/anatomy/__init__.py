"""Request anatomy plane (docs/serving_anatomy.md).

Rafiki's thesis is that the *system*, not the model, dominates served
query latency — so the serving path must be decomposable per hop or
every perf claim about it is folklore. This package is that
decomposition, built on the PR 6 trace/journal substrate:

* :mod:`hops` — compact per-hop timestamp marks carried inside the bus
  envelope, segment math, and the absorb step that turns a gathered
  chain into histograms + journal records.
* :mod:`exemplars` — a slowest-N-per-window ring retaining FULL
  waterfalls for exactly the requests percentile summaries erase.
* :mod:`timeseries` — the per-second serving rollup journaled as
  ``serving/ts`` records (qps, p50/p99, shed rate, queue depth,
  inflight, breaker state).

Stitching and rendering live in the obs CLI (``obs waterfall``,
``obs tails``, ``obs serving``).
"""

from rafiki_tpu.obs.anatomy import exemplars, hops, timeseries  # noqa: F401

__all__ = ["exemplars", "hops", "timeseries"]
