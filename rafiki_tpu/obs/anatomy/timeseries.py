"""Continuous serving time-series: the per-second rollup.

Bench numbers are point-in-time; serving regressions are processes.
``ServingRollup`` buckets every gateway outcome into fixed wall
intervals (1s by default) and, each time a bucket rolls, journals one
``serving/ts`` record: qps, p50/p99 latency, shed rate, outcome
counts, plus whatever live context the owner injects (admission-queue
depth, inflight, per-worker breaker state). ``obs serving`` renders
the rows; the gauges it refreshes (``serving.qps`` etc.) feed the
PR 8 SLO engine so a hop regression burns an alert, not just a bench
number.

Deterministic by construction: the clock is injectable, latencies per
bucket are bounded (``CAP``), and a bucket's row depends only on what
was observed in it — tests drive a fake clock and get byte-stable
rows.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from rafiki_tpu import telemetry
from rafiki_tpu.obs.journal import journal as _journal

#: Latency samples kept per bucket. At one-second buckets this only
#: truncates past 4k qps, where the percentile is stable anyway.
CAP = 4096


def _pct_ms(xs: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile of a sorted seconds list, in ms."""
    if not xs:
        return None
    idx = min(len(xs) - 1, max(0, int(round(q / 100.0 * (len(xs) - 1)))))
    return round(xs[idx] * 1000.0, 3)


class ServingRollup:
    """Per-bucket outcome/latency aggregation -> ``serving/ts`` rows.

    ``context_fn`` (optional) returns a dict merged into each flushed
    row — the gateway wires admission/breaker state through it. It is
    called OUTSIDE the rollup lock.
    """

    def __init__(self, bucket_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic,
                 context_fn: Optional[Callable[[], Dict[str, Any]]] = None):
        self.bucket_s = float(bucket_s)
        self._clock = clock
        self._context_fn = context_fn
        self._lock = threading.Lock()
        self._bucket: Optional[int] = None
        self._lat: List[float] = []
        self._ok = 0
        self._shed = 0
        self._err = 0
        self._flushed = 0
        self._last_row: Dict[str, Any] = {}

    def observe(self, latency_s: Optional[float] = None,
                outcome: str = "ok") -> None:
        """Record one finished request. ``outcome`` is ``ok`` /
        ``shed`` / ``error``; latency only accumulates for ok."""
        row = None
        with self._lock:
            b = int(self._clock() / self.bucket_s)
            if self._bucket is None:
                self._bucket = b
            elif b != self._bucket:
                row = self._close_locked()
                self._bucket = b
            if outcome == "ok":
                self._ok += 1
                if latency_s is not None and len(self._lat) < CAP:
                    self._lat.append(float(latency_s))
            elif outcome == "shed":
                self._shed += 1
            else:
                self._err += 1
        if row is not None:
            self._emit(row)

    def flush(self) -> Optional[Dict[str, Any]]:
        """Force-close the current bucket (teardown — a run shorter
        than ``bucket_s`` would otherwise journal nothing)."""
        with self._lock:
            row = self._close_locked() if self._bucket is not None else None
            self._bucket = None
        if row is not None:
            self._emit(row)
        return row

    def _close_locked(self) -> Optional[Dict[str, Any]]:
        n = self._ok + self._shed + self._err
        if n == 0:
            self._lat = []
            return None
        xs = sorted(self._lat)
        row: Dict[str, Any] = {
            "bucket": self._bucket,
            "span_s": self.bucket_s,
            "requests": n,
            "ok": self._ok,
            "shed": self._shed,
            "errors": self._err,
            "qps": round(n / self.bucket_s, 3),
            "p50_ms": _pct_ms(xs, 50.0),
            "p99_ms": _pct_ms(xs, 99.0),
            "shed_rate": round(self._shed / n, 4),
        }
        self._lat = []
        self._ok = self._shed = self._err = 0
        self._flushed += 1
        self._last_row = row
        return row

    def _emit(self, row: Dict[str, Any]) -> None:
        if self._context_fn is not None:
            try:
                row.update(self._context_fn() or {})
            except Exception:
                pass  # context is garnish; the rollup row must land
        _journal.record("serving", "ts", **row)
        telemetry.set_gauge("serving.qps", row["qps"])
        telemetry.set_gauge("serving.shed_rate", row["shed_rate"])
        if row["p50_ms"] is not None:
            telemetry.set_gauge("serving.p50_ms", row["p50_ms"])
        if row["p99_ms"] is not None:
            telemetry.set_gauge("serving.p99_ms", row["p99_ms"])
        self._last_row = row

    def collector(self) -> Dict[str, Any]:
        """Telemetry collector payload: the last flushed row plus flush
        count — the live ``serving`` block in ``/metrics``."""
        with self._lock:
            return {"buckets_flushed": self._flushed,
                    "last": dict(self._last_row)}
