"""Per-hop timestamp marks: the wire format of the request waterfall.

A *mark* is ``[code, ts, pid]`` — a stage code, a ``time.monotonic()``
timestamp and the stamping process id. Monotonic, not wall:
``CLOCK_MONOTONIC`` is system-wide on Linux, so marks stamped by the
gateway, a spawned inference worker and the predictor subtract cleanly
on one host, and NTP steps cannot corrupt a segment (RF009). The pid
is the cross-process evidence: a stitched waterfall proves it crossed
process boundaries because its marks carry distinct pids.

Marks ride inside the existing trace envelope (``trace["hops"]``) on
the query leg and as an optional third element of the prediction tuple
on the reply leg — both back-compat the same way the PR 6 trace
3-tuple was: untraced messages keep their old shapes, old readers
ignore the extra element.

Chain order (full gateway path)::

    admit -> queue -> enq -> deq -> fwds -> fwd|fwdc -> reply -> dec

Each NON-FIRST mark names the segment that *ends* at it; the segment's
duration is its ts minus the previous mark's ts. A standalone
predictor call (no gateway) starts at ``enq`` — still a >=4-hop chain.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from rafiki_tpu import telemetry
from rafiki_tpu.obs import context as _context
from rafiki_tpu.obs.journal import journal as _journal

#: Mark code -> the segment it terminates. A chain's first mark opens
#: the waterfall; every later mark closes one named segment.
SEGMENT_OF = {
    "queue": "admission_wait",   # gateway admit -> admission grant
    "bat": "gateway_batch_wait",  # admission grant -> microbatch flush
    "enq": "route",              # admission grant -> bus enqueue
    "deq": "bus_queue",          # bus enqueue -> worker dequeue
    "fwds": "batch_wait",        # dequeue -> device forward start
    "fwd": "forward",            # warm device forward
    "fwdc": "forward_cold",      # first forward on this worker (compile)
    "reply": "reply_publish",    # forward end -> put_prediction
    "dec": "gather_decide",      # reply -> predictor quorum/hedge decision
}

SEGMENTS: Tuple[str, ...] = tuple(dict.fromkeys(SEGMENT_OF.values()))

#: Histogram name per segment, precomputed so hot-path observes never
#: build strings (and the name set stays a closed, greppable table —
#: these are the docs/telemetry.md ``serving.hop.*`` rows).
METRIC_OF = {seg: "serving.hop." + seg + "_s" for seg in SEGMENTS}

#: The ensemble fan-out overhead: chain total minus the slowest device
#: forward — everything the k-replica round-trip adds on top of the
#: model itself. Rafiki's headline decomposition.
FANOUT_METRIC = "serving.fanout_cost_s"


def mark(code: str) -> List[Any]:
    """A fresh ``[code, ts, pid]`` mark stamped now."""
    return [code, time.monotonic(), os.getpid()]


# ---------------------------------------------------------------------------
# Gateway-side prefix: marks stamped BEFORE the bus envelope exists.
# ---------------------------------------------------------------------------

_local = threading.local()


def begin() -> None:
    """Open a per-thread mark prefix (gateway request entry). Until
    :func:`clear`, :func:`add` appends to it and the bus envelope
    copies it into ``trace["hops"]``."""
    _local.prefix = []


def add(code: str) -> Optional[List[Any]]:
    """Stamp ``code`` onto the open prefix; no-op (returns None) when
    no prefix is open, so bus users outside the gateway pay nothing."""
    pfx = getattr(_local, "prefix", None)
    if pfx is None:
        return None
    m = mark(code)
    pfx.append(m)
    return m


def prefix_marks() -> List[List[Any]]:
    """A copy of the open prefix (empty when none is open)."""
    pfx = getattr(_local, "prefix", None)
    return list(pfx) if pfx else []


def clear() -> None:
    """Close the prefix. MUST run in the gateway's finally: a stale
    prefix would leak one request's marks into the next chain stitched
    on this thread."""
    _local.prefix = None


# ---------------------------------------------------------------------------
# Segment math + the absorb step (predictor side, post-gather).
# ---------------------------------------------------------------------------

def segments(marks: Iterable[List[Any]]) -> List[Tuple[str, float]]:
    """``[(segment, duration_s), ...]`` for one chain. Unknown codes
    contribute no segment but still advance the clock — so a chain
    with a foreign mark fails hop-sum reconciliation loudly instead of
    silently absorbing the gap into a neighbor."""
    out: List[Tuple[str, float]] = []
    prev_ts: Optional[float] = None
    for m in marks:
        ts = float(m[1])
        seg = SEGMENT_OF.get(m[0])
        if seg is not None and prev_ts is not None:
            out.append((seg, ts - prev_ts))
        prev_ts = ts
    return out


def chain_total_s(marks: List[List[Any]]) -> float:
    """End-to-end span of one chain: last mark ts minus first."""
    if len(marks) < 2:
        return 0.0
    return float(marks[-1][1]) - float(marks[0][1])


def absorb(query_id: str, chains: Dict[str, List[List[Any]]]) -> float:
    """Fold one query's gathered chains (worker id -> full mark list,
    each ending in ``dec``) into the anatomy plane: per-segment
    histograms, the fan-out cost, a ``serving/hops`` journal record,
    and an exemplar-ring offer. Returns the query's total span (the
    slowest chain)."""
    totals: List[float] = []
    fwd_durs: List[float] = []
    bat_durs: List[float] = []
    for marks in chains.values():
        for seg, dur in segments(marks):
            # Dynamic name but drawn from the closed METRIC_OF table
            # above — rafiki_tpu.obs is RF008-exempt for this reason.
            telemetry.observe(METRIC_OF[seg], max(0.0, dur))
            if seg in ("forward", "forward_cold"):
                fwd_durs.append(dur)
            elif seg == "gateway_batch_wait":
                bat_durs.append(dur)
        totals.append(chain_total_s(marks))
    total_s = max(totals) if totals else 0.0
    if fwd_durs:
        # The microbatch coalescing wait is a deliberate latency trade
        # the gateway chose, not fan-out overhead — exclude it so the
        # stacked route's fanout cost measures only what the wire adds.
        waited = max(bat_durs) if bat_durs else 0.0
        telemetry.observe(FANOUT_METRIC,
                          max(0.0, total_s - max(fwd_durs) - waited))
    trace_id = _context.current_trace_id()
    _journal.record("serving", "hops", query_id=query_id,
                    chains=chains, total_s=round(total_s, 6))
    from rafiki_tpu.obs.anatomy import exemplars

    exemplars.ring.offer(total_s, {"query_id": query_id, "chains": chains,
                                   "trace_id": trace_id})
    return total_s
