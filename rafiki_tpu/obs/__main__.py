import sys

from rafiki_tpu.obs.cli import main

if __name__ == "__main__":
    sys.exit(main())
