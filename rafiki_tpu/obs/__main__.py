import sys

from rafiki_tpu.obs.cli import main
from rafiki_tpu.utils.backend import honor_env_platform

if __name__ == "__main__":
    honor_env_platform()
    sys.exit(main())
