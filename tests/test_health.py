"""Numerics health plane (docs/health.md): in-graph sentinels,
divergence detection/containment, replay capsules, and the CLI surface.

The contract under test:
  * sentinels — the per-step health bundle is always in the trace, its
    epoch reduction names the first bad step, and stripping it keeps
    the caller-visible metric dict identical to the pre-health-plane
    shape;
  * detector — NaN/Inf trips immediately, grad-norm explosion trips
    only after warmup + hysteresis, and every knob has an env override
    including the kill switch;
  * containment — a serial trial fails fast with DivergenceError and a
    diagnosis; a packed trial evicts ONLY the sick member, and the
    survivors' final params stay bit-identical to an unfaulted run;
  * capsules — a divergence banks an atomic replay capsule whose
    re-execution reproduces the bad step bit-for-bit, through the
    in-proc API and the real ``obs replay`` CLI alike.
"""

import math
import time

import numpy as np
import pytest

from rafiki_tpu import telemetry
from rafiki_tpu.chaos import FaultPlane, install, uninstall
from rafiki_tpu.models.ff import FeedForward
from rafiki_tpu.obs import health
from rafiki_tpu.obs.health import DivergenceError, HealthMonitor
from rafiki_tpu.obs.journal import journal

TRAIN = "synthetic://images?classes=4&n=128&w=8&h=8&c=1&seed=0"
VAL = "synthetic://images?classes=4&n=64&w=8&h=8&c=1&seed=1"


@pytest.fixture(autouse=True)
def _clean_plane():
    """Chaos-free and stat-isolated on both sides of every test."""
    uninstall()
    health.reset_stats()
    yield
    uninstall()
    health.reset_stats()


@pytest.fixture
def journaled(tmp_path):
    journal.configure(tmp_path, role="test")
    try:
        yield tmp_path
    finally:
        journal.close()


def _ff(seed=0, epochs=2):
    m = FeedForward(hidden_layers=1, hidden_units=32, learning_rate=1e-3,
                    batch_size=32, epochs=epochs, seed=0)
    m._seed = seed
    return m


def _healthy(gn=1.0):
    return {"health_grad_norm": gn, "health_update_norm": gn * 0.01,
            "health_param_norm": 10.0, "health_nonfinite": 0,
            "health_bad_step": 0, "health_bad_grad_norm": gn,
            "health_bad_update_norm": gn * 0.01, "health_bad_nonfinite": 0}


def _nan_epoch(bad_step=2):
    h = _healthy(gn=float("nan"))
    h.update(health_nonfinite=7, health_bad_step=bad_step,
             health_bad_nonfinite=7, health_bad_grad_norm=float("nan"))
    return h


def _observe(mon, h):
    return mon.observe(h, t0=time.monotonic(), epoch_seed=0,
                       idx=None, poison=None, snapshot=None)


def _bits_equal(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return a.shape == b.shape and bool(np.array_equal(a, b))


def _tree_bits_equal(ta, tb):
    import jax

    return bool(jax.tree.all(jax.tree.map(_bits_equal, ta, tb)))


# -- detector unit behavior ---------------------------------------------------


def test_clean_epochs_never_trip():
    mon = HealthMonitor("t")
    for _ in range(20):
        assert _observe(mon, _healthy()) is None
    assert health.stats()["divergences"] == 0


def test_nonfinite_trips_immediately_with_diagnosis():
    mon = HealthMonitor("t")
    v = _observe(mon, _nan_epoch())
    assert v is not None
    assert v["divergence"] == "nonfinite"
    assert v["bad_step"] == 2
    assert "non-finite" in v["diagnosis"]
    assert health.stats()["divergences"] == 1


def test_nonfinite_grad_norm_without_count_still_trips():
    """An Inf grad norm with a zero non-finite count (overflow in the
    norm reduction itself) is still a nonfinite verdict."""
    mon = HealthMonitor("t")
    h = _healthy(gn=float("inf"))
    assert _observe(mon, h)["divergence"] == "nonfinite"


def test_explosion_needs_warmup_and_hysteresis():
    mon = HealthMonitor("t")
    assert mon.warmup == 3 and mon.hysteresis == 2
    # Too little history: even a wild norm cannot trip (no baseline).
    fresh = HealthMonitor("t2")
    assert _observe(fresh, _healthy(gn=1e9)) is None
    # Warmed up: first exploding epoch arms the streak, second trips.
    for _ in range(3):
        assert _observe(mon, _healthy(gn=1.0)) is None
    assert _observe(mon, _healthy(gn=1000.0)) is None
    v = _observe(mon, _healthy(gn=1000.0))
    assert v is not None and v["divergence"] == "explosion"
    assert "explosion" in v["diagnosis"]


def test_explosion_streak_resets_on_clean_epoch():
    mon = HealthMonitor("t")
    for _ in range(3):
        _observe(mon, _healthy(gn=1.0))
    assert _observe(mon, _healthy(gn=1000.0)) is None
    assert _observe(mon, _healthy(gn=1.0)) is None  # streak broken
    assert _observe(mon, _healthy(gn=1000.0)) is None
    assert health.stats()["divergences"] == 0


def test_exploding_epochs_not_absorbed_into_median():
    """A slow ramp must not normalize itself out of detection: epochs
    above the bar never enter the history the median is taken from."""
    mon = HealthMonitor("t")
    for _ in range(3):
        _observe(mon, _healthy(gn=1.0))
    _observe(mon, _healthy(gn=1000.0))
    assert all(g <= 1.0 for g in mon._members[0].history)


def test_env_knobs(monkeypatch):
    monkeypatch.setenv(health.ENV_K, "5")
    monkeypatch.setenv(health.ENV_WARMUP, "1")
    monkeypatch.setenv(health.ENV_HYSTERESIS, "1")
    mon = HealthMonitor("t")
    assert mon.explosion_k == 5.0
    _observe(mon, _healthy(gn=1.0))
    v = _observe(mon, _healthy(gn=6.0))  # 6 > 5x median 1, 1-epoch fuse
    assert v is not None and v["divergence"] == "explosion"


def test_kill_switch(monkeypatch):
    monkeypatch.setenv(health.ENV_ENABLE, "0")
    mon = HealthMonitor("t")
    assert _observe(mon, _nan_epoch()) is None
    assert health.stats()["divergences"] == 0


def test_capsule_switch_disables_snapshots(monkeypatch):
    monkeypatch.setenv(health.ENV_CAPSULE, "off")
    mon = HealthMonitor("t")
    assert mon.snapshot_state({"w": np.ones(2)}) is None
    v = _observe(mon, _nan_epoch())  # detection stays live
    assert v is not None and v["capsule"] is None


def test_divergence_charges_badput(journaled):
    from rafiki_tpu.obs.ledger import ledger

    before = ledger.snapshot()["total"].get("badput_s", 0.0)
    mon = HealthMonitor("t")
    _observe(mon, _healthy())  # banks some wall first
    v = mon.observe(_nan_epoch(), t0=time.monotonic() - 2.0, epoch_seed=0,
                    idx=None, poison=None, snapshot=None)
    assert v is not None and v["badput_s"] > 0.0
    after = ledger.snapshot()["total"].get("badput_s", 0.0)
    assert after - before == pytest.approx(v["badput_s"], abs=1e-3)
    assert health.stats()["badput_charged_s"] > 0.0


def test_tripped_member_not_reobserved():
    mon = HealthMonitor("t")
    assert _observe(mon, _nan_epoch()) is not None
    assert _observe(mon, _nan_epoch()) is None  # already contained
    assert health.stats()["divergences"] == 1


# -- in-graph sentinels -------------------------------------------------------


def test_sentinel_bundle_counts_nonfinite():
    import jax.numpy as jnp

    from rafiki_tpu.obs.health import sentinel

    grads = {"w": jnp.array([1.0, jnp.nan, jnp.inf]), "b": jnp.ones(2)}
    ups = {"w": jnp.ones(3), "b": jnp.ones(2)}
    b = sentinel.bundle(jnp.float32(0.5), grads, ups, ups)
    assert int(b["health_nonfinite"]) == 2
    b2 = sentinel.bundle(jnp.float32(jnp.nan), ups, ups, ups)
    assert int(b2["health_nonfinite"]) == 1  # the loss itself
    assert math.isfinite(float(b2["health_grad_norm"]))


def test_sentinel_reduce_epoch_locates_first_bad_step():
    import jax.numpy as jnp

    from rafiki_tpu.obs.health import sentinel

    nan = float("nan")
    series = {
        "health_grad_norm": jnp.array([1.0, 2.0, nan, 4.0]),
        "health_update_norm": jnp.array([0.1, 0.2, nan, 0.4]),
        "health_param_norm": jnp.array([9.0, 9.0, nan, nan]),
        "health_nonfinite": jnp.array([0, 0, 5, 3], dtype=jnp.int32),
    }
    out = {k: np.asarray(v) for k, v in sentinel.reduce_epoch(series).items()}
    assert int(out["health_bad_step"]) == 2
    assert int(out["health_bad_nonfinite"]) == 5
    assert int(out["health_nonfinite"]) == 8  # epoch total
    assert math.isnan(float(out["health_bad_grad_norm"]))
    # Clean series: bad_step sentinel is -1 and maxes are finite.
    clean = {k: jnp.nan_to_num(v) for k, v in series.items()}
    clean["health_nonfinite"] = jnp.zeros(4, jnp.int32)
    out = sentinel.reduce_epoch(clean)
    assert int(out["health_bad_step"]) == -1
    assert float(out["health_grad_norm"]) == 4.0  # max, not last


def test_sentinel_keys_stripped_from_metrics():
    """The JaxModel metrics contract predates the health plane: no
    ``health_*`` key may leak into the caller-visible epoch dict."""
    m = _ff()
    m.train(TRAIN)
    out = m._loop.run_epoch(m._prepared_dataset(TRAIN), m.batch_size,
                            epoch_seed=99)
    assert not any(k.startswith("health_") for k in out)
    assert "loss" in out
    m.destroy()


# -- serial containment + capsule replay --------------------------------------


def test_serial_divergence_fails_fast_with_capsule(journaled):
    install(FaultPlane.from_spec("seed=3;train.nan:nan:times=1"))
    m = _ff()
    with pytest.raises(DivergenceError) as ei:
        m.train(TRAIN)
    v = ei.value.verdict
    assert v["divergence"] == "nonfinite"
    assert v["bad_step"] == 2  # n_steps//2 of a 4-step epoch
    assert v["capsule"] is not None
    assert "non-finite" in str(ei.value)
    uninstall()

    from rafiki_tpu.obs.health import capsule

    cap = capsule.load(v["capsule"])
    assert cap["kind"] == "nonfinite" and cap["bad_step"] == 2
    assert cap["idx"].shape[0] == 3  # truncated at the bad step
    result = capsule.replay(v["capsule"])
    assert result["reproduced"], result["mismatches"]
    assert result["steps_replayed"] == 3
    assert result["poisoned"]
    m.destroy()


def test_divergence_journaled_and_flight_recorded(journaled):
    install(FaultPlane.from_spec("seed=3;train.nan:nan:times=1"))
    m = _ff()
    with pytest.raises(DivergenceError):
        m.train(TRAIN)
    uninstall()
    journal.close()
    from rafiki_tpu.obs import journal as journal_mod

    recs = journal_mod.read_dir(journaled)
    assert any(r.get("kind") == "health" and r.get("name") == "divergence"
               for r in recs)
    assert any(r.get("kind") == "health" and r.get("name") == "capsule"
               for r in recs)
    assert list(journaled.glob("flight-*.json"))
    m.destroy()


def test_clean_run_writes_no_capsules(journaled):
    m = _ff()
    m.train(TRAIN)
    assert not list(journaled.glob("capsule-*.rcap"))
    assert health.stats()["divergences"] == 0
    m.destroy()


# -- packed isolation ---------------------------------------------------------


def test_packed_member_divergence_isolated(journaled):
    """Member 2 of a k=4 pack diverges: it alone carries a verdict, and
    members 0/1/3 finish bit-identical to an unfaulted packed run."""
    from rafiki_tpu.model.base import JaxModel

    install(FaultPlane.from_spec("seed=3;train.nan:nan:times=1:match=@m2"))
    faulted = [_ff(seed=s) for s in range(4)]
    JaxModel.train_packed(faulted, TRAIN)
    uninstall()
    verdicts = [getattr(m, "_health_verdict", None) for m in faulted]
    assert [v is None for v in verdicts] == [True, True, False, True]
    assert verdicts[2]["divergence"] == "nonfinite"
    assert verdicts[2]["member"] == 2
    assert health.stats()["evictions"] == 1

    clean = [_ff(seed=s) for s in range(4)]
    JaxModel.train_packed(clean, TRAIN)
    for i in (0, 1, 3):
        assert _tree_bits_equal(faulted[i]._loop.params,
                                clean[i]._loop.params), f"member {i}"
    for m in faulted + clean:
        m.destroy()


def test_packed_capsule_replays_serially(journaled):
    """A packed member's capsule holds the member-sliced (serial-shape)
    state; its replay re-executes through a SERIAL program and must
    still reproduce bit-exactly — the pack/serial parity invariant is
    what makes cross-shape replay sound."""
    from rafiki_tpu.model.base import JaxModel

    install(FaultPlane.from_spec("seed=3;train.nan:nan:times=1:match=@m1"))
    models = [_ff(seed=s) for s in range(2)]
    JaxModel.train_packed(models, TRAIN)
    uninstall()
    caps = sorted(journaled.glob("capsule-*.rcap"))
    assert caps
    from rafiki_tpu.obs.health import capsule

    cap = capsule.load(caps[-1])
    assert cap["packed"] is True and cap["member"] == 1
    result = capsule.replay(caps[-1])
    assert result["reproduced"], result["mismatches"]
    for m in models:
        m.destroy()


# -- worker containment (serial + packed) -------------------------------------


class _ScriptedAdvisor:
    def __init__(self):
        self.fed = []

    def propose(self):
        return dict(hidden_layers=1, hidden_units=32, learning_rate=1e-3,
                    batch_size=32, epochs=2, seed=0)

    def propose_batch(self, n):
        return [self.propose() for _ in range(n)]

    def feedback(self, score, knobs):
        self.fed.append(round(float(score), 6))


def _mk_worker(tmp_path, n_trials, trial_pack=1):
    from rafiki_tpu.store import MetaStore, ParamsStore
    from rafiki_tpu.worker.train import TrainWorker

    store = MetaStore(tmp_path / "meta.sqlite3")
    params = ParamsStore(tmp_path / "params")
    model = store.create_model("hff", "IMAGE_CLASSIFICATION", None,
                               b"", "FeedForward")
    job = store.create_train_job("app", "IMAGE_CLASSIFICATION", None,
                                 TRAIN, VAL,
                                 {"MODEL_TRIAL_COUNT": n_trials})
    sub = store.create_sub_train_job(job["id"], model["id"])
    adv = _ScriptedAdvisor()
    worker = TrainWorker(store, params, sub["id"], FeedForward, adv,
                         TRAIN, VAL, {"MODEL_TRIAL_COUNT": n_trials},
                         async_persist=False, trial_pack=trial_pack)
    return store, worker, adv, sub


def test_worker_serial_contains_divergence(tmp_path, journaled):
    install(FaultPlane.from_spec("seed=3;train.nan:nan:times=1"))
    store, worker, adv, sub = _mk_worker(tmp_path, n_trials=2)
    n = worker.run()
    uninstall()
    assert n == 2
    trials = store.get_trials_of_sub_train_job(sub["id"])
    statuses = sorted(t["status"] for t in trials)
    assert statuses == ["COMPLETED", "ERRORED"]
    bad = next(t for t in trials if t["status"] == "ERRORED")
    assert "diverged" in (bad["error"] or "")
    assert 0.0 in adv.fed  # floor score steered the advisor away
    assert health.stats()["contained"] == 1
    # The worker loop SURVIVED the divergence: trial 2 completed.
    good = next(t for t in trials if t["status"] == "COMPLETED")
    assert good["score"] is not None


def test_worker_packed_contains_divergence(tmp_path, journaled):
    from rafiki_tpu.model.knobs import knob_config_signature
    from rafiki_tpu.worker.train import PackedTrialRunner

    install(FaultPlane.from_spec("seed=3;train.nan:nan:times=1:match=@m1"))
    store, worker, adv, sub = _mk_worker(tmp_path, n_trials=2, trial_pack=2)
    knob_config = FeedForward.get_knob_config()
    rows = []
    for _ in range(2):
        kn = adv.propose()
        t = store.create_trial(sub["id"], "FeedForward", kn,
                               shape_sig=knob_config_signature(
                                   knob_config, kn),
                               budget_max=2)
        rows.append((t["id"], kn))
    n = PackedTrialRunner(worker, 2).run_assigned(rows, budget_max=2)
    uninstall()
    assert n == 2
    trials = {t["id"]: t for t in store.get_trials_of_sub_train_job(sub["id"])}
    t0, t1 = trials[rows[0][0]], trials[rows[1][0]]
    assert t0["status"] == "COMPLETED" and t0["score"] is not None
    assert t1["status"] == "ERRORED" and "diverged" in (t1["error"] or "")
    assert 0.0 in adv.fed
    assert health.stats()["contained"] == 1
    assert health.stats()["evictions"] == 1


# -- CLI surface --------------------------------------------------------------


def test_cli_health_curves_replay(tmp_path, journaled, capsys):
    from rafiki_tpu.obs import cli

    install(FaultPlane.from_spec("seed=3;train.nan:nan:times=1"))
    store, worker, adv, sub = _mk_worker(tmp_path, n_trials=2)
    worker.run()
    uninstall()
    journal.close()

    assert cli.main(["--dir", str(journaled), "health"]) == 0
    out = capsys.readouterr().out
    assert "divergences: 1" in out and "capsule" in out

    assert cli.main(["--dir", str(journaled), "curves"]) == 0
    out = capsys.readouterr().out
    assert "trial " in out and "epoch" in out

    caps = sorted(journaled.glob("capsule-*.rcap"))
    assert cli.main(["replay", str(caps[-1])]) == 0
    out = capsys.readouterr().out
    assert "reproduced" in out

    # Empty dir: health reports a clean bill (exit 0), curves miss (1).
    empty = tmp_path / "empty"
    empty.mkdir()
    assert cli.main(["--dir", str(empty), "health"]) == 0
    assert "clean" in capsys.readouterr().out
    assert cli.main(["--dir", str(empty), "curves"]) == 1


def test_cli_replay_rejects_garbage(tmp_path, capsys):
    from rafiki_tpu.obs import cli

    bad = tmp_path / "not-a-capsule.rcap"
    bad.write_bytes(b"\x80\x04N.")  # pickled None
    assert cli.main(["replay", str(bad)]) == 2
