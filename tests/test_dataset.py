import numpy as np

from rafiki_tpu.model.dataset import Dataset, dataset_utils, synthetic_corpus, synthetic_images


def test_synthetic_images_learnable_and_deterministic():
    a = dataset_utils.load("synthetic://images?classes=5&n=256&seed=3")
    b = dataset_utils.load("synthetic://images?classes=5&n=256&seed=3")
    assert a.size == 256 and a.classes == 5
    np.testing.assert_array_equal(a.x, b.x)
    assert a.x.min() >= 0.0 and a.x.max() <= 1.0


def test_train_batches_static_shape():
    ds = synthetic_images(n=150, seed=0)
    batches = list(ds.batches(64, shuffle=True, seed=1, drop_remainder=True))
    assert len(batches) == 2
    assert all(b["x"].shape[0] == 64 for b in batches)


def test_eval_batches_padded_and_masked():
    ds = synthetic_images(n=150, seed=0)
    batches = list(ds.batches(64, drop_remainder=False))
    assert len(batches) == 3
    assert batches[-1]["x"].shape[0] == 64
    assert batches[-1]["valid"].sum() == 150 - 128
    total_valid = sum(b["valid"].sum() for b in batches)
    assert total_valid == 150


def test_split_is_disjoint_and_total():
    ds = synthetic_images(n=100, seed=0)
    a, b = ds.split(0.8, seed=1)
    assert a.size == 80 and b.size == 20


def test_corpus_masks_and_labels():
    ds = synthetic_corpus(vocab=50, tags=5, n=32, length=12, seed=0)
    assert ds.x.shape == (32, 12)
    assert ds.mask is not None
    assert (ds.y[~ds.mask] == -1).all()
    assert (ds.y[ds.mask] >= 0).all()


def test_image_zip_format_round_trip(tmp_path):
    import zipfile
    from PIL import Image

    zpath = tmp_path / "ds.zip"
    rng = np.random.default_rng(0)
    with zipfile.ZipFile(zpath, "w") as zf:
        rows = ["path,class"]
        for i in range(6):
            arr = (rng.uniform(0, 255, size=(8, 8)).astype(np.uint8))
            import io

            buf = io.BytesIO()
            Image.fromarray(arr, mode="L").save(buf, format="PNG")
            zf.writestr(f"img_{i}.png", buf.getvalue())
            rows.append(f"img_{i}.png,{i % 3}")
        zf.writestr("images.csv", "\n".join(rows))
    ds = dataset_utils.load(str(zpath))
    assert ds.size == 6 and ds.classes == 3
    assert ds.x.shape == (6, 8, 8, 1)


def test_npz_round_trip(tmp_path):
    ds = synthetic_images(n=32, seed=0)
    path = dataset_utils.save_npz(ds, str(tmp_path / "d.npz"))
    ds2 = dataset_utils.load(path)
    assert ds2.size == 32
    np.testing.assert_allclose(ds.x, ds2.x, atol=1e-6)


def test_dataset_load_is_cached(tmp_path):
    """Same URI loads once per process (trials reload every trial; a
    CIFAR-scale regeneration costs as much as a warm trial's compute)."""
    from rafiki_tpu.model.dataset import dataset_utils

    uri = "synthetic://images?classes=3&n=64&w=8&h=8&seed=0"
    a = dataset_utils.load(uri)
    b = dataset_utils.load(uri)
    assert a is b  # cache hit: identical object
    assert dataset_utils.load(
        "synthetic://images?classes=3&n=64&w=8&h=8&seed=1") is not a


def test_dataset_cache_invalidated_by_file_mtime(tmp_path):
    import os
    import time

    import numpy as np

    from rafiki_tpu.model.dataset import dataset_utils

    p = tmp_path / "d.npz"
    np.savez(p, x=np.zeros((4, 4, 4, 1), np.float32),
             y=np.arange(4, dtype=np.int32))
    a = dataset_utils.load(str(p))
    assert dataset_utils.load(str(p)) is a
    # rewrite the file with a newer mtime -> fresh load
    np.savez(p, x=np.ones((4, 4, 4, 1), np.float32),
             y=np.arange(4, dtype=np.int32))
    os.utime(p, (time.time() + 5, time.time() + 5))
    b = dataset_utils.load(str(p))
    assert b is not a
    assert float(b.x.max()) == 1.0
