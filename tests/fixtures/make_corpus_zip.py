"""Generate the committed POS-corpus zip fixtures (reference format).

The reference's POS datasets are zips holding a ``corpus.tsv`` of
token<TAB>tag rows with blank lines between sentences (SURVEY.md §2
dataset-utils row). The fixtures below are REAL text: hand-tagged
English sentences (universal-style tags), split into train/val zips so
the end-to-end corpus path — canonical hashing/tag encoding across
independently loaded zips, masking, training, prediction — is proven
on actual language data rather than the synthetic token generator.

Run from the repo root to (re)generate:
  python tests/fixtures/make_corpus_zip.py
Writes tests/fixtures/pos_train.zip and tests/fixtures/pos_val.zip.
"""

from __future__ import annotations

import os
import zipfile

# (token, tag) sentences; universal-style tagset:
# DET NOUN VERB ADJ ADV PRON ADP CONJ NUM PRT PUNCT
_SENTENCES = [
    "the/DET cat/NOUN sat/VERB on/ADP the/DET mat/NOUN ./PUNCT",
    "a/DET dog/NOUN barked/VERB loudly/ADV ./PUNCT",
    "she/PRON reads/VERB old/ADJ books/NOUN ./PUNCT",
    "the/DET quick/ADJ fox/NOUN jumps/VERB over/ADP the/DET lazy/ADJ dog/NOUN ./PUNCT",
    "he/PRON ate/VERB two/NUM green/ADJ apples/NOUN ./PUNCT",
    "birds/NOUN fly/VERB south/ADV in/ADP winter/NOUN ./PUNCT",
    "we/PRON walked/VERB to/ADP the/DET market/NOUN and/CONJ bought/VERB bread/NOUN ./PUNCT",
    "the/DET old/ADJ man/NOUN smiled/VERB warmly/ADV ./PUNCT",
    "children/NOUN play/VERB in/ADP the/DET park/NOUN ./PUNCT",
    "it/PRON rained/VERB heavily/ADV all/DET night/NOUN ./PUNCT",
    "three/NUM ships/NOUN sailed/VERB across/ADP the/DET sea/NOUN ./PUNCT",
    "they/PRON sang/VERB a/DET happy/ADJ song/NOUN ./PUNCT",
    "the/DET teacher/NOUN wrote/VERB on/ADP the/DET board/NOUN ./PUNCT",
    "my/DET sister/NOUN likes/VERB red/ADJ flowers/NOUN ./PUNCT",
    "he/PRON quickly/ADV closed/VERB the/DET heavy/ADJ door/NOUN ./PUNCT",
    "the/DET river/NOUN flows/VERB through/ADP the/DET valley/NOUN ./PUNCT",
    "we/PRON saw/VERB five/NUM small/ADJ boats/NOUN ./PUNCT",
    "the/DET sun/NOUN rises/VERB in/ADP the/DET east/NOUN ./PUNCT",
    "she/PRON gave/VERB him/PRON a/DET new/ADJ pen/NOUN ./PUNCT",
    "farmers/NOUN grow/VERB wheat/NOUN and/CONJ corn/NOUN ./PUNCT",
    "the/DET baby/NOUN slept/VERB quietly/ADV upstairs/ADV ./PUNCT",
    "i/PRON drank/VERB cold/ADJ water/NOUN after/ADP the/DET race/NOUN ./PUNCT",
    "dark/ADJ clouds/NOUN covered/VERB the/DET sky/NOUN ./PUNCT",
    "the/DET train/NOUN arrived/VERB late/ADV again/ADV ./PUNCT",
    "you/PRON should/VERB try/VERB the/DET soup/NOUN ./PUNCT",
    "a/DET tall/ADJ tree/NOUN fell/VERB during/ADP the/DET storm/NOUN ./PUNCT",
    "the/DET chef/NOUN cooked/VERB fresh/ADJ fish/NOUN ./PUNCT",
    "wolves/NOUN hunt/VERB in/ADP packs/NOUN ./PUNCT",
    "her/DET voice/NOUN sounded/VERB very/ADV calm/ADJ ./PUNCT",
    "the/DET clock/NOUN struck/VERB nine/NUM ./PUNCT",
    "students/NOUN study/VERB hard/ADV before/ADP exams/NOUN ./PUNCT",
    "he/PRON painted/VERB the/DET fence/NOUN white/ADJ ./PUNCT",
    "the/DET wind/NOUN blew/VERB the/DET leaves/NOUN away/ADV ./PUNCT",
    "they/PRON built/VERB a/DET stone/NOUN bridge/NOUN ./PUNCT",
    "snow/NOUN fell/VERB softly/ADV on/ADP the/DET hills/NOUN ./PUNCT",
    "the/DET girl/NOUN found/VERB a/DET shiny/ADJ coin/NOUN ./PUNCT",
    "bees/NOUN make/VERB sweet/ADJ honey/NOUN ./PUNCT",
    "we/PRON waited/VERB for/ADP the/DET bus/NOUN ./PUNCT",
    "the/DET moon/NOUN glowed/VERB brightly/ADV above/ADP the/DET lake/NOUN ./PUNCT",
    "old/ADJ houses/NOUN need/VERB constant/ADJ care/NOUN ./PUNCT",
    # -- validation split (same tag set, overlapping vocabulary) --
    "the/DET dog/NOUN sat/VERB near/ADP the/DET door/NOUN ./PUNCT",
    "she/PRON likes/VERB the/DET old/ADJ park/NOUN ./PUNCT",
    "two/NUM birds/NOUN sang/VERB in/ADP the/DET tree/NOUN ./PUNCT",
    "he/PRON reads/VERB books/NOUN quietly/ADV ./PUNCT",
    "the/DET children/NOUN play/VERB near/ADP the/DET river/NOUN ./PUNCT",
    "cold/ADJ wind/NOUN blew/VERB through/ADP the/DET valley/NOUN ./PUNCT",
    "they/PRON bought/VERB fresh/ADJ bread/NOUN and/CONJ honey/NOUN ./PUNCT",
    "the/DET man/NOUN walked/VERB to/ADP the/DET lake/NOUN ./PUNCT",
]
N_VAL = 8


def _tsv(sentences) -> str:
    blocks = []
    for s in sentences:
        rows = [pair.rsplit("/", 1) for pair in s.split()]
        blocks.append("\n".join(f"{tok}\t{tag}" for tok, tag in rows))
    return "\n\n".join(blocks) + "\n"


def make_zips(out_dir: str) -> None:
    train, val = _SENTENCES[:-N_VAL], _SENTENCES[-N_VAL:]
    for name, sents in (("pos_train.zip", train), ("pos_val.zip", val)):
        with zipfile.ZipFile(os.path.join(out_dir, name), "w",
                             zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("corpus.tsv", _tsv(sents))


if __name__ == "__main__":
    here = os.path.dirname(os.path.abspath(__file__))
    make_zips(here)
    print(f"wrote pos_train.zip ({len(_SENTENCES) - N_VAL} sentences) and "
          f"pos_val.zip ({N_VAL})")
