"""Generate the committed digit-zip fixtures (reference dataset format).

The reference's image datasets are zips of real image files plus an
``images.csv`` of ``path,class`` rows (SURVEY.md §2 dataset-utils row).
These fixtures are REAL raster images — 16x16 grayscale PNGs of digit
glyphs rendered from a 5x7 bitmap font at jittered offsets with light
pixel noise — so the end-to-end zip path (decode, normalize, batch,
train, predict) is proven on actual image files rather than on the
synthetic:// generator.

Run from the repo root to (re)generate:
  python tests/fixtures/make_digits_zip.py
Writes tests/fixtures/digits_train.zip (200 images) and
tests/fixtures/digits_val.zip (60 images), both committed.
"""

from __future__ import annotations

import io
import os
import zipfile

import numpy as np

# A classic 5x7 bitmap font for the digits 0-9.
_FONT = {
    0: ["01110", "10001", "10011", "10101", "11001", "10001", "01110"],
    1: ["00100", "01100", "00100", "00100", "00100", "00100", "01110"],
    2: ["01110", "10001", "00001", "00010", "00100", "01000", "11111"],
    3: ["11111", "00010", "00100", "00010", "00001", "10001", "01110"],
    4: ["00010", "00110", "01010", "10010", "11111", "00010", "00010"],
    5: ["11111", "10000", "11110", "00001", "00001", "10001", "01110"],
    6: ["00110", "01000", "10000", "11110", "10001", "10001", "01110"],
    7: ["11111", "00001", "00010", "00100", "01000", "01000", "01000"],
    8: ["01110", "10001", "10001", "01110", "10001", "10001", "01110"],
    9: ["01110", "10001", "10001", "01111", "00001", "00010", "01100"],
}
SIZE = 16


def _render(digit: int, rng: np.random.Generator) -> np.ndarray:
    glyph = np.array([[int(c) for c in row] for row in _FONT[digit]],
                     dtype=np.float32)
    # 2x upscale to 10x14, jittered placement on a 16x16 canvas.
    glyph = np.repeat(np.repeat(glyph, 2, axis=0), 2, axis=1)
    canvas = np.zeros((SIZE, SIZE), dtype=np.float32)
    oy = rng.integers(0, SIZE - glyph.shape[0] + 1)
    ox = rng.integers(0, SIZE - glyph.shape[1] + 1)
    canvas[oy:oy + glyph.shape[0], ox:ox + glyph.shape[1]] = glyph
    canvas += rng.normal(0, 0.08, canvas.shape).astype(np.float32)
    return (np.clip(canvas, 0, 1) * 255).astype(np.uint8)


def make_zip(path: str, n: int, seed: int) -> None:
    from PIL import Image

    rng = np.random.default_rng(seed)
    rows = ["path,class"]
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        for i in range(n):
            digit = int(rng.integers(0, 10))
            img = Image.fromarray(_render(digit, rng), mode="L")
            buf = io.BytesIO()
            img.save(buf, format="PNG")
            name = f"images/{i:04d}.png"
            zf.writestr(name, buf.getvalue())
            rows.append(f"{name},{digit}")
        zf.writestr("images.csv", "\n".join(rows) + "\n")


if __name__ == "__main__":
    here = os.path.dirname(os.path.abspath(__file__))
    make_zip(os.path.join(here, "digits_train.zip"), n=200, seed=0)
    make_zip(os.path.join(here, "digits_val.zip"), n=60, seed=1)
    print("wrote digits_train.zip (200) and digits_val.zip (60)")
