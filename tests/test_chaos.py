"""Chaos plane: spec parsing, deterministic replay, inertness, hook
effects at real call sites, and the scenario runner — including the
ISSUE-5 acceptance scenario (kill-mid-pack-resume) end to end.
"""

import threading
import time

import pytest

from rafiki_tpu import chaos, telemetry
from rafiki_tpu.chaos import (
    ChaosError, ChaosSpecError, FaultPlane, install, uninstall)
from rafiki_tpu.chaos.runner import run_scenario
from rafiki_tpu.chaos.scenarios import SCENARIOS


@pytest.fixture(autouse=True)
def _clean_plane():
    """Every test starts and ends chaos-free; telemetry isolated."""
    telemetry.reset()
    uninstall()
    yield
    uninstall()
    telemetry.reset()


# ---------------------------------------------------------------------------
# Spec parsing
# ---------------------------------------------------------------------------


def test_spec_parses_sites_modes_and_options():
    plane = FaultPlane.from_spec(
        "seed=9;worker.epoch:kill:after=1:times=2:unless=-r;"
        "bus.add_query:drop:p=0.25;store.params_write:delay:delay=0.5:match=_ckpt_")
    assert plane.seed == 9
    assert len(plane.faults) == 3
    kill, drop, delay = plane.faults
    assert (kill.site, kill.mode, kill.after, kill.times, kill.unless) == \
        ("worker.epoch", "kill", 1, 2, "-r")
    assert (drop.site, drop.mode, drop.prob) == ("bus.add_query", "drop", 0.25)
    assert (delay.site, delay.delay_s, delay.match) == \
        ("store.params_write", 0.5, "_ckpt_")


@pytest.mark.parametrize("bad", [
    "",                            # nothing to inject
    "worker.epoch",                # no mode
    "worker.epoch:explode",        # unknown mode
    "worker.epoch:kill:after",     # option not k=v
    "worker.epoch:kill:nope=1",    # unknown option
    "worker.epoch:kill:p=lots",    # bad value
    "seed=seven;a.b:drop",         # bad seed
])
def test_bad_specs_fail_loudly(bad):
    with pytest.raises(ChaosSpecError):
        FaultPlane.from_spec(bad)


# ---------------------------------------------------------------------------
# Determinism + inertness (acceptance criteria)
# ---------------------------------------------------------------------------


def _drive(plane, hits=200):
    install(plane)
    for i in range(hits):
        chaos.decide("bus.add_query", key=f"w{i % 3}")
        chaos.decide("bus.heartbeat", key=f"w{i % 2}")
    uninstall()
    return plane.schedule()


def test_fixed_seed_replays_identical_schedule():
    spec = "seed=42;bus.add_query:drop:p=0.3;bus.heartbeat:skip:p=0.2:match=w1"
    first = _drive(FaultPlane.from_spec(spec))
    second = _drive(FaultPlane.from_spec(spec))
    assert first, "schedule empty — p gates never fired"
    assert first == second


def test_different_seed_changes_schedule():
    a = _drive(FaultPlane.from_spec("seed=1;bus.add_query:drop:p=0.3"))
    b = _drive(FaultPlane.from_spec("seed=2;bus.add_query:drop:p=0.3"))
    assert a != b


def test_per_site_streams_are_independent():
    """Interleaving extra traffic on one site must not shift another
    site's firing pattern (per-spec rng streams, one draw per hit)."""
    spec = "seed=7;bus.add_query:drop:p=0.5"

    plane_a = FaultPlane.from_spec(spec)
    install(plane_a)
    for i in range(50):
        chaos.decide("bus.add_query", key=f"w{i}")
    uninstall()

    plane_b = FaultPlane.from_spec(spec)
    install(plane_b)
    for i in range(50):
        chaos.decide("bus.heartbeat", key="noise")  # no spec on this site
        chaos.decide("bus.add_query", key=f"w{i}")
    uninstall()

    assert [s for s in plane_a.schedule()] == \
        [s for s in plane_b.schedule() if s[0] == "bus.add_query"]


def test_inert_when_unset(monkeypatch):
    monkeypatch.delenv(chaos.ENV_VAR, raising=False)
    assert chaos.reset_from_env() is None
    assert chaos.active() is None
    assert chaos.hook("bus.add_query", "w0") is None
    assert chaos.decide("worker.epoch", "w0") is None
    # No telemetry churn on the inert path either.
    assert telemetry.get_counter("chaos.injected") == 0.0


def test_env_spec_installs_on_reset(monkeypatch):
    monkeypatch.setenv(chaos.ENV_VAR, "seed=3;bus.heartbeat:skip")
    plane = chaos.reset_from_env()
    assert plane is not None and plane.seed == 3
    assert chaos.hook("bus.heartbeat", "w0") == "skip"
    monkeypatch.delenv(chaos.ENV_VAR)
    assert chaos.reset_from_env() is None


# ---------------------------------------------------------------------------
# Gating options
# ---------------------------------------------------------------------------


def test_after_times_match_unless_gates():
    plane = FaultPlane.from_spec(
        "worker.epoch:kill:after=2:times=1:match=w0:unless=-r")
    install(plane)
    # unless filters the restarted incarnation entirely (no hit counted)
    assert chaos.decide("worker.epoch", "w0-r1") is None
    # match filters other workers
    assert chaos.decide("worker.epoch", "w1") is None
    # after=2: first two matching hits pass through
    assert chaos.decide("worker.epoch", "w0") is None
    assert chaos.decide("worker.epoch", "w0") is None
    fault = chaos.decide("worker.epoch", "w0")
    assert fault is not None and fault.mode == "kill"
    # times=1: exhausted
    assert chaos.decide("worker.epoch", "w0") is None
    assert plane.schedule() == [("worker.epoch", "kill", 3, "w0")]
    assert telemetry.get_counter("chaos.injected") == 1.0
    assert telemetry.get_counter("chaos.injected.worker.epoch.kill") == 1.0


def test_delay_and_error_modes_enact():
    install(FaultPlane.from_spec(
        "store.params_write:delay:delay=0.12:times=1;inference.forward:error"))
    t0 = time.monotonic()
    assert chaos.hook("store.params_write", "p1") == "delay"
    assert time.monotonic() - t0 >= 0.1
    with pytest.raises(ChaosError):
        chaos.hook("inference.forward", "w0")


# ---------------------------------------------------------------------------
# Hook effects at real call sites
# ---------------------------------------------------------------------------


def test_bus_drop_and_heartbeat_skip():
    from rafiki_tpu.bus import InProcBus

    bus = InProcBus()
    bus.add_worker("j", "w0")
    lease_before = bus.get_workers("j", max_age_s=10.0)
    assert lease_before == ["w0"]

    install(FaultPlane.from_spec("bus.add_query:drop;bus.heartbeat:skip"))
    bus.add_query("w0", "q1", [1.0])
    assert bus.pop_queries("w0", max_n=10, timeout=0.05) == []
    assert telemetry.get_counter("bus.queries_dropped_chaos") == 1.0
    # skipped heartbeat: the lease does NOT refresh
    time.sleep(0.15)
    bus.heartbeat("j", "w0")
    assert bus.get_workers("j", max_age_s=0.1) == []
    uninstall()
    bus.heartbeat("j", "w0")
    assert bus.get_workers("j", max_age_s=0.1) == ["w0"]


def test_store_write_fault_targets_checkpoints_only(tmp_path):
    from rafiki_tpu.store import ParamsStore

    params = ParamsStore(tmp_path / "p")
    install(FaultPlane.from_spec("store.params_write:error:match=_ckpt_"))
    pid = params.save(b"final-params")  # non-checkpoint write unaffected
    assert params.load(pid) == b"final-params"
    with pytest.raises(ChaosError):
        params.save_checkpoint("trial1", 0, b"snap")
    assert params.latest_checkpoint("trial1") is None  # nothing torn


def test_checkpoint_write_failure_does_not_error_trial(tmp_path):
    """The recovery gap this PR fixed: an injected checkpoint-write
    failure must cost resumability, not the trial."""
    from rafiki_tpu.model.base import BaseModel
    from rafiki_tpu.store import MetaStore, ParamsStore
    from rafiki_tpu.worker.train import TrainWorker

    class _Model(BaseModel):
        _sink = None

        @staticmethod
        def get_knob_config():
            return {}

        def set_checkpoint_sink(self, sink):
            self._sink = sink

        def train(self, uri):
            for epoch in range(2):
                self._sink(epoch, lambda: b"snap")

        def evaluate(self, uri):
            return 0.5

        def predict(self, queries):
            return []

        def dump_parameters(self):
            return b"params"

    store = MetaStore(tmp_path / "m.sqlite3")
    params = ParamsStore(tmp_path / "p")
    mrow = store.create_model("m", "T", None, b"x = 1", "X")
    job = store.create_train_job("app", "T", None, "t", "v", {})
    sub = store.create_sub_train_job(job["id"], mrow["id"])

    class _Advisor:
        def propose(self):
            return {}

        def feedback(self, score, knobs):
            pass

    install(FaultPlane.from_spec("store.params_write:error:match=_ckpt_"))
    worker = TrainWorker(store, params, sub["id"], _Model, _Advisor(),
                         "t", "v", {}, async_persist=False,
                         checkpoint_every=1)

    trial = worker.run_trial({})
    assert trial["status"] == "COMPLETED"
    assert telemetry.get_counter("worker.checkpoint_write_failed") == 2.0


def test_scheduler_preempt_decision():
    """scheduler.preempt is caller-enacted: decide() returns the fault,
    the supervise loop signals the subprocess."""
    install(FaultPlane.from_spec("scheduler.preempt:preempt:delay=1.5:times=1"))
    fault = chaos.decide("scheduler.preempt", "w0")
    assert fault is not None
    assert fault.mode == "preempt" and fault.delay_s == 1.5
    assert chaos.decide("scheduler.preempt", "w0") is None  # times=1


# ---------------------------------------------------------------------------
# Runner + scenarios
# ---------------------------------------------------------------------------


def test_catalog_has_the_required_scenarios():
    assert {"kill-mid-trial-resume", "kill-mid-pack-resume",
            "straggler-quorum", "drain-under-load",
            "predictor-outage-surfaces",
            "checkpoint-write-failure"} <= set(SCENARIOS)


def test_runner_rejects_unknown_scenario():
    with pytest.raises(KeyError):
        run_scenario("no-such-scenario")


def test_invariant_failures_actually_fail(monkeypatch):
    """A scenario whose invariant is violated must report FAIL — the
    runner can't be vacuously green."""
    from rafiki_tpu.chaos import runner as runner_mod
    from rafiki_tpu.chaos.scenarios import Scenario

    def always_wrong(tmp, check):
        check("impossible", False, "violated by construction")

    monkeypatch.setitem(
        SCENARIOS, "always-wrong",
        Scenario(name="always-wrong", description="x",
                 spec="bus.heartbeat:skip", fn=always_wrong))
    report = runner_mod.run_scenario("always-wrong")
    assert not report.passed
    assert [c.name for c in report.checks if not c.ok] == ["impossible"]

    def raises(tmp, check):
        raise RuntimeError("scenario body exploded")

    monkeypatch.setitem(
        SCENARIOS, "raises",
        Scenario(name="raises", description="x",
                 spec="bus.heartbeat:skip", fn=raises))
    report = runner_mod.run_scenario("raises")
    assert not report.passed and "exploded" in report.error

    def checks_nothing(tmp, check):
        pass

    monkeypatch.setitem(
        SCENARIOS, "vacuous",
        Scenario(name="vacuous", description="x",
                 spec="bus.heartbeat:skip", fn=checks_nothing))
    assert not runner_mod.run_scenario("vacuous").passed


def test_runner_restores_env_and_plane(monkeypatch):
    import os

    from rafiki_tpu.chaos import runner as runner_mod
    from rafiki_tpu.chaos.scenarios import Scenario

    monkeypatch.setenv(chaos.ENV_VAR, "bus.add_query:drop")
    seen = {}

    def body(tmp, check):
        seen["env"] = os.environ.get(chaos.ENV_VAR)
        seen["extra"] = os.environ.get("RAFIKI_CHAOS_TEST_EXTRA")
        check("ran", True)

    monkeypatch.setitem(
        SCENARIOS, "env-probe",
        Scenario(name="env-probe", description="x",
                 spec="seed=5;bus.heartbeat:skip", fn=body,
                 env={"RAFIKI_CHAOS_TEST_EXTRA": "1"}))
    report = runner_mod.run_scenario("env-probe")
    assert report.passed
    assert seen == {"env": "seed=5;bus.heartbeat:skip", "extra": "1"}
    assert os.environ[chaos.ENV_VAR] == "bus.add_query:drop"
    assert "RAFIKI_CHAOS_TEST_EXTRA" not in os.environ
    assert chaos.active() is None  # uninstalled on the way out


def test_straggler_quorum_scenario_passes():
    report = run_scenario("straggler-quorum")
    assert report.passed, "\n".join(
        f"{c.name}: {c.detail}" for c in report.checks if not c.ok)
    assert any(s[0] == "inference.forward" for s in report.schedule)


def test_predictor_outage_scenario_passes():
    report = run_scenario("predictor-outage-surfaces")
    assert report.passed, "\n".join(
        f"{c.name}: {c.detail}" for c in report.checks if not c.ok)


def test_kill_mid_pack_resume_acceptance():
    """ISSUE 5 acceptance: k=4 packed run SIGKILLed mid-trial resumes
    every member from its per-epoch slice checkpoint; no lost or
    duplicated rows; resumed final params bit-match an unfaulted
    serial run. Real subprocess workers on the CPU platform."""
    report = run_scenario("kill-mid-pack-resume")
    assert report.passed, "\n".join(
        f"{c.name}: {c.detail}" for c in report.checks if not c.ok) \
        + (f"\n{report.error}" if report.error else "")
    names = {c.name for c in report.checks}
    assert any(n.startswith("params_match_serial") for n in names)
    assert "all_trials_resumed_by_respawned_worker" in names
