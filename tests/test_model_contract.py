"""The minimum end-to-end slice (SURVEY.md §7): FeedForward on synthetic
MNIST-class data through the full trial loop, on the CPU backend."""

import numpy as np
import pytest

from rafiki_tpu.model.dataset import synthetic_images
from rafiki_tpu.model.dev import test_model_class, tune_model
from rafiki_tpu.models.ff import FeedForward

TRAIN = "synthetic://images?classes=10&n=1024&seed=0"
TEST = "synthetic://images?classes=10&n=256&seed=1"

FAST_KNOBS = dict(hidden_layers=1, hidden_units=64, learning_rate=3e-3,
                  batch_size=64, epochs=2, seed=0)


def test_full_trial_loop_learns():
    queries = [synthetic_images(n=4, seed=2).x[i] for i in range(4)]
    score, preds = test_model_class(
        FeedForward, "IMAGE_CLASSIFICATION", TRAIN, TEST,
        queries=queries, knobs=FAST_KNOBS)
    assert score > 0.5  # learnable synthetic data; random = 0.1
    assert len(preds) == 4
    assert len(preds[0]) == 10
    np.testing.assert_allclose(np.sum(preds, axis=1), 1.0, atol=1e-3)


def test_params_round_trip_bytes():
    m = FeedForward(**FAST_KNOBS)
    m.train(TRAIN)
    blob = m.dump_parameters()
    assert isinstance(blob, bytes) and len(blob) > 1000
    m2 = FeedForward(**FAST_KNOBS)
    m2.load_parameters(blob)
    q = synthetic_images(n=8, seed=3).x
    np.testing.assert_allclose(m.predict_proba(q), m2.predict_proba(q), atol=1e-5)


def test_load_model_class_from_source():
    from rafiki_tpu.model.base import load_model_class

    src = b"""
from rafiki_tpu.model.base import JaxModel
from rafiki_tpu.model.knobs import FloatKnob, FixedKnob
from rafiki_tpu.models.ff import _Mlp

class MyModel(JaxModel):
    @staticmethod
    def get_knob_config():
        return {"learning_rate": FloatKnob(1e-4, 1e-1, is_exp=True),
                "epochs": FixedKnob(1)}

    def build_module(self, num_classes, input_shape):
        return _Mlp(hidden_layers=1, hidden_units=16, num_classes=num_classes)
"""
    cls = load_model_class(src, "MyModel")
    assert cls.__name__ == "MyModel"
    m = cls(learning_rate=1e-3)
    m.train("synthetic://images?classes=3&n=128&seed=0")
    assert 0.0 <= m.evaluate("synthetic://images?classes=3&n=64&seed=1") <= 1.0


def test_load_model_class_rejects_bad():
    from rafiki_tpu.model.base import load_model_class

    with pytest.raises(ValueError):
        load_model_class(b"x = 1", "MyModel")
    with pytest.raises(ValueError):
        load_model_class(b"class MyModel: pass", "MyModel")


def test_tune_model_random_advisor():
    best_knobs, best_score, records = tune_model(
        FeedForward, TRAIN, TEST, total_trials=3, advisor="random", seed=0)
    assert len(records) == 3
    assert best_score == max(r["score"] for r in records)
    assert best_score > 0.3
